"""Device-mesh slice executor: mapReduce as SPMD collectives.

The reference fans a query out goroutine-per-slice and per-node, then
reduces associatively — sum for Count, pair-merge for TopN
(executor.go:1103-1236). On TPU the slice axis IS a mesh axis: packed
slice blocks are sharded over devices with `jax.sharding`, the per-slice
map is the sharded computation inside `shard_map`, and the reduce is an
XLA collective riding ICI — `psum` for Count, `psum` of per-row counts +
`top_k` for TopN — instead of an HTTP/gossip merge.

Axis conventions:
- ``slices``: the column-slice axis (data-parallel; the reference's unit
  of placement, cluster.go:198-240). Count/TopN reduce over it.
- ``rows``: candidate-row axis for TopN blocks (tensor-parallel
  analogue); per-row counts are psum'd over ``slices``, gathered over
  ``rows`` for the final top-k.

Program forms: the XLA serving path (the recorded A/B winner, and the
only path off-TPU) compiles through the shape-stable **global-view
catalogue** in ``parallel.programs`` — plain ``jax.jit`` over globally
sharded arrays with explicit ``NamedSharding`` placement, slice axes
padded to canonical buckets (``programs.slice_bucket``) so the compile
count is bucket-bound instead of scaling with slice count, and the
final Count/TopN reduction is an in-program all-reduce. The Pallas
fused kernels keep their per-shard ``shard_map`` form here
(``pallas_call`` is a per-shard primitive); the dispatch entry points
pick per backend. Both forms share one compile-accounting wrapper
(``_finalize_program``) and one public entry-point surface.
"""

from __future__ import annotations

import functools
import heapq
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..fault import failpoints as _failpoints
from ..obs import accounting as _accounting
from ..obs import trace as obs_trace
from ..ops.kernels import _BITWISE
from ..sched import context as sched_context

AXIS_SLICES = "slices"
AXIS_ROWS = "rows"


def _dispatch_gate() -> None:
    """Every device-dispatch entry point passes here before compiling
    or dispatching a program: the query-budget check (sched) plus the
    ``mesh.dispatch`` failpoint (fault) — an injected FailpointError
    is an OSError, so the executor's device-trouble handlers fall back
    to the host path exactly as they would for a real backend fault."""
    sched_context.check_current()
    if _failpoints.ACTIVE is not None:
        _failpoints.ACTIVE.hit("mesh.dispatch")


# -- per-tenant device-queue fairness ----------------------------------------
# Admission (sched.admission) strides tenants at the HTTP front door,
# but ONE admitted query fans out many device dispatches; below
# admission every dispatch raced FIFO for the backend, so a wide
# tenant's fan-out could monopolize the device queue against a quiet
# tenant's single program. The FairDispatchQueue closes that gap: a
# bounded slot pool at the dispatch boundary where, under contention,
# waiters are admitted in stride order over their tenants' effective
# admission weights (the same penalty-boxed weights sched.tenants
# computes — one fairness currency at both levels). Uncontended cost
# is one lock acquire; the queue is installed only when the server
# runs with tenants (install_fair_dispatch), and PILOSA_MESH_FAIR=0
# removes it entirely.

class FairDispatchQueue:
    """Stride-scheduled slot pool for device dispatches.

    Each tenant carries a virtual ``pass``; enqueueing advances it by
    ``1/weight`` and waiters wake lowest-pass-first, so over any
    contended window tenants hold slots in proportion to their
    weights regardless of how many dispatches each has queued. A new
    (or long-idle) tenant starts at the global pass frontier — it
    cannot bank credit, only compete fairly from now on."""

    def __init__(self, slots: int, weight_fn=None):
        self.slots = max(1, int(slots))
        self.weight_fn = weight_fn
        self._mu = threading.Lock()
        self._in_flight = 0
        # Heap entries are [pass, seq, Event, cancelled]; list order
        # compares (pass, seq) — seq is unique, the Event never
        # participates. ``cancelled`` marks a waiter that gave up
        # (query killed while queued); release() skips it.
        self._heap: list[list] = []
        self._seq = 0
        self._tenant_pass: dict[str, float] = {}
        self._global_pass = 0.0
        self._dispatches = 0
        self._waits = 0

    def _stride(self, tenant: str) -> float:
        weight = 1.0
        fn = self.weight_fn
        if fn is not None:
            try:
                weight = float(fn(tenant))
            except Exception:  # noqa: BLE001 - fairness is advisory
                weight = 1.0
        return 1.0 / max(weight, 1e-3)

    def acquire(self, tenant: str) -> None:
        with self._mu:
            self._dispatches += 1
            if self._in_flight < self.slots and not self._heap:
                self._in_flight += 1
                return
            self._waits += 1
            p = max(self._tenant_pass.get(tenant, 0.0),
                    self._global_pass) + self._stride(tenant)
            self._tenant_pass[tenant] = p
            self._seq += 1
            entry = [p, self._seq, threading.Event(), False]
            heapq.heappush(self._heap, entry)
        ev = entry[2]
        while not ev.wait(0.05):
            # Keep the query's cancellation/kill/deadline checks live
            # while queued — a killed query must not occupy the queue.
            try:
                sched_context.check_current()
            except BaseException:
                with self._mu:
                    if not ev.is_set():
                        entry[3] = True
                        raise
                # Woken concurrently with the cancel: we own a slot —
                # hand it on before propagating.
                self.release()
                raise

    def release(self) -> None:
        with self._mu:
            while self._heap:
                _p, _seq, ev, cancelled = heapq.heappop(self._heap)
                if cancelled:
                    continue
                self._global_pass = _p
                ev.set()  # slot transfers: _in_flight is unchanged
                return
            self._in_flight -= 1

    def state(self) -> dict:
        with self._mu:
            return {"slots": self.slots,
                    "inFlight": self._in_flight,
                    "queued": sum(1 for e in self._heap if not e[3]),
                    "dispatches": self._dispatches,
                    "waits": self._waits}


_FAIR: "FairDispatchQueue | None" = None
_FAIR_DEPTH = threading.local()
DEFAULT_FAIR_SLOTS = 8


def install_fair_dispatch(weight_fn=None, slots: int = 0) -> None:
    """Arm per-tenant dispatch fairness (server.open, once tenants
    exist). ``weight_fn(tenant) -> float`` is typically
    ``TenantRegistry.effective_weight``. PILOSA_MESH_FAIR=0 vetoes
    (the escape hatch when a deployment wants raw FIFO dispatch);
    PILOSA_MESH_FAIR_SLOTS overrides the slot count."""
    global _FAIR
    if os.environ.get("PILOSA_MESH_FAIR", "") == "0":
        _FAIR = None
        return
    if not slots:
        try:
            slots = int(os.environ.get("PILOSA_MESH_FAIR_SLOTS", "")
                        or DEFAULT_FAIR_SLOTS)
        except ValueError:
            slots = DEFAULT_FAIR_SLOTS
    _FAIR = FairDispatchQueue(slots, weight_fn)


def uninstall_fair_dispatch() -> None:
    global _FAIR
    _FAIR = None


def fair_dispatch_state() -> "dict | None":
    q = _FAIR
    return q.state() if q is not None else None


def _fair_dispatch(fn):
    """Entry-point wrapper: hold one fair slot for the duration of the
    dispatch call. Reentrant per thread (topn_topk_sharded's Pallas
    path calls topn_exact_sharded — the outer slot covers both), and
    a straight pass-through until install_fair_dispatch arms it."""
    @functools.wraps(fn)
    def gated(*args, **kwargs):
        q = _FAIR
        if q is None or getattr(_FAIR_DEPTH, "d", 0):
            return fn(*args, **kwargs)
        ctx = sched_context.current()
        tenant = (getattr(ctx, "tenant", "") or "") if ctx else ""
        q.acquire(tenant or "default")
        _FAIR_DEPTH.d = 1
        try:
            return fn(*args, **kwargs)
        finally:
            _FAIR_DEPTH.d = 0
            q.release()
    return gated


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: the stable ``jax.shard_map``
    (check_vma) when this jax has it, else the 0.4-era
    ``jax.experimental.shard_map.shard_map``, whose equivalent knob is
    ``check_rep`` — without the fallback every device program dies at
    trace time on 0.4.x containers and the whole mesh layer silently
    demotes to the host path."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy_sm
    return legacy_sm(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)


_LEGACY_DISPATCH_LOCK = threading.Lock()


def _legacy_locked(fn):
    """Serialize a compiled collective program on legacy jax (no
    ``jax.shard_map``): the 0.4 CPU backend deadlocks when two
    collective programs are in flight at once — each program's
    per-device threads park in the AllReduce rendezvous of a
    different RunId and neither set can complete (observed: concurrent
    executor queries on the 8-virtual-device test mesh). One
    process-wide lock held dispatch-to-completion fixes it; modern
    jax handles concurrent collectives itself, so the stable path
    pays nothing."""
    if hasattr(jax, "shard_map"):
        return fn

    def locked(*args, **kwargs):
        with _LEGACY_DISPATCH_LOCK:
            return jax.block_until_ready(fn(*args, **kwargs))
    return locked


# -- compile-cache observability ---------------------------------------------
# Every serving program is built by an lru_cache'd builder below; a
# builder RUN is a compile-cache miss, and the program's FIRST
# invocation pays the XLA trace+compile. Both are counted here (plus
# the wall seconds of those first calls) so "is the cache hitting, and
# does anything warm it" — VERDICT weak #2's 5.4 s cold-query question
# — is answerable from /status, /metrics, and MANIFEST.json instead of
# a stopwatch.

_COMPILE_MU = threading.Lock()
_COMPILE_STATS = {"programsBuilt": 0, "firstCalls": 0,
                  "compileSeconds": 0.0,
                  # Persistent on-disk cache outcomes (jax monitoring
                  # events, counted once arm_compile_cache registers
                  # the listener): a restarted process whose programs
                  # load from disk shows HITS here — the direct answer
                  # to "did the cache survive the restart".
                  "persistentHits": 0, "persistentMisses": 0}


def _on_jax_cache_event(event: str, **kwargs) -> None:
    if event.endswith("/cache_hits"):
        with _COMPILE_MU:
            _COMPILE_STATS["persistentHits"] += 1
    elif event.endswith("/cache_misses"):
        with _COMPILE_MU:
            _COMPILE_STATS["persistentMisses"] += 1


def _finalize_program(fn):
    """Builder epilogue: legacy-dispatch lock + compile accounting.

    Accounting is per XLA COMPILATION, not per builder run: a jitted
    program re-traces for every distinct input shape, so before the
    bucket-stable catalogue a program serving 8, 12, 16... slices paid
    (and hid) one compile per slice count. The wrapper detects a
    compile by the jitted cache growing across the call
    (``_cache_size``) and charges its wall time to ``firstCalls`` /
    ``compileSeconds`` — making "compile count stays bucket-bound as
    slice count grows" an assertable number. The predicted first call
    additionally records an ``xla_compile`` span on any traced query
    that triggers it."""
    jitted = fn  # the jax.jit object (cache-size introspection)
    fn = _legacy_locked(fn)
    with _COMPILE_MU:
        _COMPILE_STATS["programsBuilt"] += 1
    sized = hasattr(jitted, "_cache_size")
    state = {"first": True}

    @functools.wraps(fn)
    def program(*args, **kwargs):
        first = state["first"]
        try:
            pre = jitted._cache_size() if sized else None
        except Exception:  # noqa: BLE001 - introspection only
            pre = None
        t0 = time.perf_counter()
        if first:
            state["first"] = False  # benign race: double-count at worst
            with obs_trace.span_current("xla_compile"):
                out = fn(*args, **kwargs)
        else:
            out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        try:
            compiled = (jitted._cache_size() > pre if pre is not None
                        else first)
        except Exception:  # noqa: BLE001 - introspection only
            compiled = first
        if compiled:
            with _COMPILE_MU:
                _COMPILE_STATS["firstCalls"] += 1
                _COMPILE_STATS["compileSeconds"] += dt
            # Attribute the trace+compile to the query that paid it
            # (obs.accounting: compileMs in its cost ledger).
            _accounting.note_compile(dt)
        return out

    return program


def _note_dispatch(*operands) -> None:
    """Charge one device-program dispatch (+ its operand bytes) to the
    current query's cost ledger (obs.accounting) — the per-query form
    of the mesh_dispatch trace span. None-cost fast path: one
    thread-local read."""
    cost = _accounting.current_cost()
    if cost is not None:
        cost.note_device_dispatch(
            sum(int(getattr(a, "nbytes", 0)) for a in operands))


def _all_program_caches():
    """Every lru_cache'd builder across the shard_map forms here AND
    the global-view catalogue (parallel.programs) — resolved lazily so
    either module can import first."""
    caches = list(_PROGRAM_CACHES)
    try:
        from . import programs as programs_mod
        caches.extend(programs_mod.PROGRAM_CACHES)
    except (ImportError, AttributeError):
        pass  # partial init during circular import: mesh's own caches
    return caches


def compile_stats() -> dict:
    """Aggregate XLA program-cache counters: lookup hits/misses over
    every lru_cache'd builder, live program count, the first-call
    compile totals, and the armed persistent-cache directory (None =
    cross-process reuse off)."""
    hits = misses = programs = 0
    for cache in _all_program_caches():
        info = cache.cache_info()
        hits += info.hits
        misses += info.misses
        programs += info.currsize
    with _COMPILE_MU:
        stats = dict(_COMPILE_STATS)
    stats["compileSeconds"] = round(stats["compileSeconds"], 3)
    return {"hits": hits, "misses": misses, "programs": programs,
            "persistentCacheDir": _compile_cache_dir,
            **stats}


def _mesh_pallas_mode(mesh: Mesh) -> str | None:
    """Pallas dispatch mode for programs compiled onto ``mesh`` —
    "compiled" on TPU meshes, "interpret" when forced for tests, None
    for the XLA fusion path (ops.pallas_kernels.pallas_mode)."""
    from ..ops import pallas_kernels
    return pallas_kernels.pallas_mode(mesh.devices.flat[0].platform)


# The Pallas kernels hold every leaf's tile in VMEM at once; beyond
# this the XLA path (which fuses the fold without materializing all
# leaves) is both safer and faster.
_PALLAS_MAX_LEAVES = 16


def _rows_popcount(expr, leaves, mode):
    """Per-slice-row int32 counts of ``expr`` over ``leaves`` [L, S, W],
    via the fused Pallas kernel when ``mode`` says so, else XLA."""
    if mode is not None and leaves.shape[0] > _PALLAS_MAX_LEAVES:
        mode = None
    if mode is not None:
        from ..ops import pallas_kernels
        return pallas_kernels.expr_count_rows_pallas(
            expr, leaves, interpret=(mode == "interpret"))
    words = _eval_expr(expr, leaves)
    pc = jax.lax.population_count(words).astype(jnp.int32)
    return jnp.sum(pc, axis=-1)


_compile_cache_armed = False
_compile_cache_dir: str | None = None


def arm_compile_cache(path: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache before first device
    use, so a RESTARTED process reuses on-disk compiled programs
    instead of re-paying the multi-second trace+compile (VERDICT weak
    #2: the canonical pass measured a 5.4 s first device query; with
    the cache hitting, a second process compiles the same program in a
    fraction — measured 3.6x faster through the tunnel's compile
    server, and ~2.5x on the CPU backend).

    ``path`` is the caller's default location — the server passes a
    directory under the holder data dir, so the cache lives (and is
    cleaned up) with the index it serves. Priority:
    PILOSA_TPU_COMPILE_CACHE env (``=0`` disables) > explicit ``path``
    > the per-machine cache dir on TPU only (CPU runs without an
    explicit path — tests, dev shells — must not silently grow a
    home-dir cache). First armer wins (jax.config is process-global);
    returns the armed directory or None."""
    global _compile_cache_armed, _compile_cache_dir
    if _compile_cache_armed:
        return _compile_cache_dir
    _compile_cache_armed = True
    import os

    from ..utils import cache_dir
    env = os.environ.get("PILOSA_TPU_COMPILE_CACHE")
    if env == "0":
        return None
    path = env or path
    if not path:
        if jax.devices()[0].platform != "tpu":
            return None
        path = cache_dir("xla")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.1)
        _compile_cache_dir = path
    except Exception:  # noqa: BLE001 - cache is an optimization only
        return _compile_cache_dir
    try:
        # Count on-disk cache outcomes (hit = a compile served from
        # disk) into compile_stats — the observable that proves a
        # second process reused the first one's compilations.
        from jax._src import monitoring as _jax_monitoring
        _jax_monitoring.register_event_listener(_on_jax_cache_event)
    except Exception:  # noqa: BLE001 - private API, visibility only
        pass
    return _compile_cache_dir


def _arm_compile_cache() -> None:
    arm_compile_cache(None)


def make_mesh(n_devices: int | None = None, rows: int = 1) -> Mesh:
    """A (rows × slices) device mesh. ``rows=1`` gives the common 1-D
    slice mesh; TopN row-sharding uses rows>1."""
    _arm_compile_cache()
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"need {n} devices, have {len(devs)}")
    if n % rows:
        raise ValueError("n_devices must be divisible by rows")
    grid = np.array(devs[:n]).reshape(rows, n // rows)
    return Mesh(grid, (AXIS_ROWS, AXIS_SLICES))


def _slice_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS_SLICES))


def shard_slices(mesh: Mesh, arr: np.ndarray) -> jax.Array:
    """Place ``[n_slices, ...]`` on the mesh, sharded over the slice axis.
    n_slices must divide evenly (pad with zero slices host-side)."""
    return jax.device_put(arr, _slice_sharding(mesh))


def densify_mode() -> str | None:
    """Sparse-upload dispatch: "compiled" on real TPU (the measured
    3-6x cold-upload win, benchmarks/DENSIFY.json), "interpret" when
    forced for CPU tests (PILOSA_TPU_SPARSE_UPLOAD=interpret), None =
    dense uploads only (=0, or non-TPU backends where device_put does
    not cross a tunnel)."""
    import os
    v = os.environ.get("PILOSA_TPU_SPARSE_UPLOAD", "auto")
    if v == "0":
        return None
    if v == "interpret":
        return "interpret"
    return "compiled" if jax.devices()[0].platform == "tpu" else None


@functools.lru_cache(maxsize=64)
def _densify_sharded_fn(mesh: Mesh, lead_shape: tuple, subs: int,
                        g_slots: int, interpret: bool):
    from ..ops import pallas_kernels as pk
    n_words = subs * 128

    def per_shard(lanes, vals):  # [..., subs, G] slice-sharded axis 0
        flat_l = lanes.reshape((-1, subs, g_slots))
        flat_v = vals.reshape((-1, subs, g_slots))
        out = pk.densify_pallas(flat_l, flat_v, n_words, interpret)
        return out.reshape(lanes.shape[:-2] + (n_words,))

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES), P(AXIS_SLICES)),
        out_specs=P(AXIS_SLICES), check_vma=False)))


@_fair_dispatch
def densify_sharded(mesh: Mesh, lanes: np.ndarray, vals: np.ndarray,
                    interpret: bool = False) -> jax.Array:
    """Upload bucketed sparse rows (ops.packed.bucket_prepared) and
    densify per shard: ``[S, (R,) subs, G]`` → slice-sharded
    ``[S, (R,) subs*128]`` dense words. The cold-path replacement for
    packing dense host-side and shipping 4 bytes per word through the
    tunnel (the round-3 c5 first-query tax)."""
    _dispatch_gate()
    dl = shard_slices(mesh, lanes)
    dv = shard_slices(mesh, vals)
    fn = _densify_sharded_fn(mesh, lanes.shape[:-2], lanes.shape[-2],
                             lanes.shape[-1], interpret)
    return fn(dl, dv)


def pad_to_multiple(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad axis 0 with zero slices to a multiple of n (zero slices are
    identity for every count/TopN reduction)."""
    rem = arr.shape[0] % n
    if rem == 0:
        return arr
    pad = [(0, n - rem)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


@functools.lru_cache(maxsize=None)
def _count_fn(mesh: Mesh, op: str):
    """[S, W] × [S, W] → scalar total count, psum over the slice axis.

    Per-shard totals are split into 16-bit halves (int64 is off by
    default; a 1 B-column slab overflows int32) and recombined host-side.
    """
    bitwise = _BITWISE[op]

    def per_shard(a, b):  # a, b: [S/n, W]
        pc = jax.lax.population_count(bitwise(a, b)).astype(jnp.int32)
        row = jnp.sum(pc, axis=-1).ravel()  # ≤ 2^15 counts of ≤ 2^20 each
        hi = jax.lax.psum(jnp.sum(row >> 16), AXIS_SLICES)
        lo = jax.lax.psum(jnp.sum(row & 0xFFFF), AXIS_SLICES)
        return jnp.stack([hi, lo])  # one output = one host fetch

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES), P(AXIS_SLICES)),
        out_specs=P())))


def count_op(mesh: Mesh, op: str, a: jax.Array, b: jax.Array) -> int:
    """Count(op(a, b)) over slice-sharded packed blocks — the mesh form of
    the executor's Count mapReduce (executor.go:568-597).

    Limited to 2^15 total slice-rows: the psum'd 16-bit lo half overflows
    int32 past that (same bound as kernels.op_count_total) — callers
    chunk the slice axis above it.
    """
    if a.ndim > 1 and a.shape[0] > (1 << 15):
        raise ValueError("count_op: more than 2^15 slice-rows per call")
    hilo = np.asarray(_count_fn(mesh, op)(a, b))
    return (int(hilo[0]) << 16) + int(hilo[1])


@functools.lru_cache(maxsize=256)  # keyed on query-shaped exprs: bound it
def _count_expr_fn_cached(mesh: Mesh, expr: tuple, mode: str | None):
    def per_shard(leaves):  # leaves: [L, S/n, W]
        his, los = _exprs_hi_lo((expr,), leaves, mode)
        return jnp.stack([jax.lax.psum(his[0], AXIS_SLICES),
                          jax.lax.psum(los[0], AXIS_SLICES)])

    # check_vma off when Pallas is in the shard body: pallas_call's
    # out_shape carries no varying-axis info, which trips the inference.
    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(None, AXIS_SLICES),), out_specs=P(),
        check_vma=(mode is None))))


def count_expr_fn(mesh: Mesh, expr: tuple):
    """[L, S, W] leaf blocks → stacked (hi, lo) 16-bit halves of
    the expression bitmap's count (decode via hilo_combine — ONE
    output array = one host fetch).

    ``expr`` is a hashable tree: ``("leaf", i)`` selects leaf block i,
    ``(op, a, b)`` combines subtrees with a bitwise op from kernels._BITWISE.
    One jitted SPMD program per (mesh, expr) — the whole PQL bitmap
    expression (e.g. Count(Intersect(Bitmap, Bitmap))) is evaluated
    elementwise over every slice at once and reduced in-program,
    replacing the reference's per-slice goroutine map + sum reduce
    (executor.go:568-597,1103-1236). On TPU the per-shard body is the
    fused Pallas expression-count kernel (ops.pallas_kernels); elsewhere
    the global-view catalogue program (parallel.programs). Public: the
    pod layer (parallel.multihost) feeds these programs process-local
    shards.
    """
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        return programs_mod.count_exprs_block_program(mesh, (expr,))
    return _count_expr_fn_cached(mesh, expr, mode)


def _exprs_hi_lo(exprs, leaves, mode):
    """Per-expression (hi, lo) 16-bit count halves over one leaf block
    [L, S/n, W] — each expression reads only ITS leaves (no redundant
    HBM traffic; the Pallas leaf-tile cap applies per expression).
    Shared body of the batched-count programs."""
    his, los = [], []
    n = leaves.shape[0]
    for expr in exprs:
        ids = expr_leaf_ids(expr)
        if ids == list(range(n)):
            sub, local = leaves, expr  # common case: uses every leaf
        else:
            sub = leaves[jnp.asarray(ids)]
            local = remap_expr_leaves(
                expr, {g: li for li, g in enumerate(ids)})
        row = _rows_popcount(local, sub, mode).ravel()
        his.append(jnp.sum(row >> 16))
        los.append(jnp.sum(row & 0xFFFF))
    return jnp.stack(his), jnp.stack(los)


@functools.lru_cache(maxsize=256)
def _count_exprs_fn_cached(mesh: Mesh, exprs: tuple, mode: str | None):
    def per_shard(leaves):  # leaves: [L, S/n, W]
        his, los = _exprs_hi_lo(exprs, leaves, mode)
        return jnp.stack([jax.lax.psum(his, AXIS_SLICES),
                          jax.lax.psum(los, AXIS_SLICES)])

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(None, AXIS_SLICES),), out_specs=P(),
        check_vma=(mode is None))))


def count_exprs_fn(mesh: Mesh, exprs: tuple):
    """K-expression batch form of count_expr_fn: ``[L, S, W]`` shared
    leaf block → stacked [2, K] (hi, lo) 16-bit halves, one program =
    one host fetch. Public for the pod layer (parallel.multihost)."""
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        return programs_mod.count_exprs_block_program(mesh, exprs)
    return _count_exprs_fn_cached(mesh, exprs, mode)


def slice_chunk_bound(n_dev: int) -> int:
    """Max slice-rows per psum'd program: the 16-bit lo halves sum to at
    most ``rows × 0xFFFF``, which must stay under int32 — 2^15 rows is
    the bound, and padding to the device multiple must not cross it."""
    return (1 << 15) - n_dev


@_fair_dispatch
def count_expr(mesh: Mesh, expr: tuple, leaves: np.ndarray) -> int:
    """Count the bitmap expression over slice-sharded leaf blocks.

    ``leaves`` is ``[n_leaves, n_slices, n_words]`` u32; slices are
    padded to the canonical bucket (programs.slice_bucket — zero slices
    are the count identity, and bucket-stable shapes keep the compile
    count bucket-bound) and chunked at the hi/lo int32 bound, so any
    slice count works.
    """
    _dispatch_gate()
    from . import programs as programs_mod
    n_dev = mesh.shape[AXIS_SLICES]
    fn = count_expr_fn(mesh, expr)
    total = 0
    step = slice_chunk_bound(n_dev)
    with obs_trace.span_current("mesh_dispatch", kind="count_expr",
                                slices=int(leaves.shape[1])):
        for off in range(0, leaves.shape[1], step):
            chunk = programs_mod.bucket_pad(
                leaves[:, off:off + step], 1, n_dev)
            # Per chunk: each loop pass dispatches one program.
            _note_dispatch(chunk)
            total += hilo_combine(
                fn(shard_slices_axis1(mesh, chunk)))[0]
    return total


def expr_leaf_ids(expr) -> list[int]:
    """Ordered unique leaf ids referenced by an expr tree (iterative —
    wide folds are ~leaf-count deep)."""
    seen: list[int] = []
    stack = [expr]
    while stack:
        node = stack.pop()
        if node[0] == "leaf":
            if node[1] not in seen:
                seen.append(node[1])
        else:
            stack.append(node[2])
            stack.append(node[1])
    return seen


def remap_expr_leaves(expr, remap: dict[int, int]) -> tuple:
    """Rebuild an expr tree with leaf ids remapped (iterative)."""
    done: dict[int, tuple] = {}
    stack = [expr]
    while stack:
        node = stack[-1]
        if node[0] == "leaf":
            done[id(node)] = ("leaf", remap[node[1]])
            stack.pop()
            continue
        left, right = node[1], node[2]
        if id(left) in done and id(right) in done:
            done[id(node)] = (node[0], done[id(left)], done[id(right)])
            stack.pop()
        else:
            if id(right) not in done:
                stack.append(right)
            if id(left) not in done:
                stack.append(left)
    return done[id(expr)]


@functools.lru_cache(maxsize=256)
def _count_exprs_sharded_fn(mesh: Mesh, exprs: tuple, n_leaves: int,
                            mode: str | None):
    def per_shard(*leaf_shards):  # each [S/n, W]
        his, los = _exprs_hi_lo(exprs, jnp.stack(leaf_shards), mode)
        return jnp.stack([jax.lax.psum(his, AXIS_SLICES),
                          jax.lax.psum(los, AXIS_SLICES)])

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES),) * n_leaves, out_specs=P(),
        check_vma=(mode is None))))


@_fair_dispatch
def count_exprs_sharded(mesh: Mesh, exprs: tuple,
                        leaf_arrays: list[jax.Array]) -> list[int]:
    """K expression counts in ONE compiled program over shared
    device-resident leaf slabs — a PQL query carrying several Count
    calls pays one dispatch (and one tunnel/host sync) instead of K.
    The reference executes calls strictly sequentially
    (executor.go:135-142); the counts are independent, so fusing them
    is observationally identical. Same bounds as count_expr_sharded.
    """
    _dispatch_gate()
    if leaf_arrays[0].shape[0] > slice_chunk_bound(
            mesh.shape[AXIS_SLICES]):
        raise ValueError("count_exprs_sharded: slice count above the"
                         " int32 hi/lo bound")
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        fn = programs_mod.count_exprs_program(mesh, exprs,
                                              len(leaf_arrays))
    else:
        fn = _count_exprs_sharded_fn(mesh, exprs, len(leaf_arrays),
                                     mode)
    _note_dispatch(*leaf_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="count_exprs",
                                exprs=len(exprs),
                                leaves=len(leaf_arrays)):
        return hilo_combine(fn(*leaf_arrays))


def count_expr_sharded(mesh: Mesh, expr: tuple,
                       leaf_arrays: list[jax.Array]) -> int:
    """Count over per-leaf DEVICE-resident [n_slices, n_words] slabs
    (each sharded over the slice axis, e.g. from the residency cache —
    no host pack or upload on this path). All slabs must share one
    shape with n_slices ≤ slice_chunk_bound; leaves stack on device
    inside the compiled program. The K=1 form of count_exprs_sharded.
    """
    return count_exprs_sharded(mesh, (expr,), leaf_arrays)[0]


@_fair_dispatch
def fused_tree_sharded(mesh: Mesh, count_exprs: tuple,
                       topn_items: list[tuple],
                       leaf_arrays: list[jax.Array],
                       rows_arrays: list[jax.Array]
                       ) -> tuple[list[int], list[list[int]]]:
    """A whole multi-op PQL tree — K expression Counts plus M TopN
    exact-count blocks — as ONE compiled XLA computation over shared
    device-resident leaf slabs: one dispatch, one in-program reduction,
    one host fetch (``[2, K + Σ rows]`` hi/lo halves) for everything
    the tree needs. ``topn_items`` is ``[(expr, n_rows), ...]`` with
    ``rows_arrays[i]`` the matching [S, R_i, W] resident candidate
    block. Returns (count values, per-TopN count lists).

    This is the fix for the config 4-5 loss (VERDICT weak #6): the old
    lane paid one host↔device sync per *call*; a tree pays one.
    XLA-path only — the executor's batch lane falls back per call on
    Pallas meshes (where the per-kind shard_map programs serve).
    """
    _dispatch_gate()
    if leaf_arrays and leaf_arrays[0].shape[0] > slice_chunk_bound(
            mesh.shape[AXIS_SLICES]):
        raise ValueError("fused_tree_sharded: slice count above the"
                         " int32 hi/lo bound")
    from . import programs as programs_mod
    fn = programs_mod.fused_program(
        mesh, tuple(count_exprs),
        tuple((expr, int(rows.shape[1]))
              for (expr, _), rows in zip(topn_items, rows_arrays)),
        len(leaf_arrays))
    _note_dispatch(*leaf_arrays, *rows_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="fused_tree",
                                exprs=len(count_exprs),
                                topns=len(topn_items),
                                leaves=len(leaf_arrays)):
        flat = hilo_combine(fn(*leaf_arrays, *rows_arrays))
    counts = flat[:len(count_exprs)]
    out_topn: list[list[int]] = []
    off = len(count_exprs)
    for rows in rows_arrays:
        n = int(rows.shape[1])
        out_topn.append(flat[off:off + n])
        off += n
    return counts, out_topn


@functools.lru_cache(maxsize=256)
def _topn_exact_sharded_fn(mesh: Mesh, expr, n_leaves: int,
                           mode: str | None):
    def per_shard(rows, *leaf_shards):  # rows [S/n, R, W]
        if n_leaves:
            leaves = jnp.stack(leaf_shards)  # [L, S/n, W]
        else:
            leaves = jnp.zeros((0,) + rows.shape[::2], dtype=rows.dtype)
        return _psum_hi_lo_rows(
            _shard_topn_inter(expr, rows, leaves, mode))

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES),) * (n_leaves + 1),
        out_specs=P(), check_vma=(mode is None))))


def _shard_topn_inter(expr, rows, leaves, mode):
    """Per-(slice, row) intersection counts for one shard — the shared
    count body of the TopN programs (Pallas kernel or XLA fusion)."""
    if mode is not None and leaves.shape[0] > _PALLAS_MAX_LEAVES:
        mode = None
    if mode is not None:
        from ..ops import pallas_kernels
        return pallas_kernels.topn_block_count_pallas(
            expr, rows, leaves, interpret=(mode == "interpret"))
    words = rows
    if expr is not None:
        src = _eval_expr(expr, leaves)
        words = jnp.bitwise_and(rows, src[:, None, :])
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32),
                   axis=-1)


def hilo_combine(hilo) -> list[int]:
    """Decode one stacked [2, ...] (hi, lo) device output into exact
    Python ints: ``(hi << 16) + lo`` vectorized, one host fetch."""
    arr = np.asarray(hilo).astype(np.int64)
    return ((arr[0] << 16) + arr[1]).ravel().tolist()


def _psum_hi_lo_rows(per_slice):
    """[S/n, R] per-slice counts → stacked [2, R] (hi, lo) 16-bit
    halves, psum'd over the slice axis (the int32-safe reduction
    split). ONE output array: each separate device output fetched
    host-side costs its own ~65 ms tunnel round trip — returning
    (hi, lo) as two arrays doubled every count/TopN query's sync
    cost (round-4 finding, c4 repeat p50 ≈ 2x the sync floor)."""
    hi = jax.lax.psum(jnp.sum(per_slice >> 16, axis=0), AXIS_SLICES)
    lo = jax.lax.psum(jnp.sum(per_slice & 0xFFFF, axis=0), AXIS_SLICES)
    return jnp.stack([hi, lo])


def _filtered_counts(expr, rows, leaves, threshold, tanimoto, mode):
    """[S/n, R] intersection counts with the reference's per-slice
    threshold/Tanimoto pruning applied (fragment.go:560-614 — a slice's
    contribution drops when that slice's row count or intersection
    count fails the bar; exact integer forms of the float comparisons)."""
    inter = _shard_topn_inter(expr, rows, leaves, mode)   # [S/n, R]
    rowc = _shard_topn_inter(None, rows, leaves[:0], mode)
    srcc = _rows_popcount(expr, leaves, mode)             # [S/n]
    s = srcc[:, None]                                     # [S/n, 1]
    # cnt > srcc·t/100  ∧  cnt < srcc·100/t  ∧  inter > 0
    # ∧  ceil(100·inter / (cnt + srcc − inter)) > t
    keep_tan = ((100 * rowc > s * tanimoto)
                & (rowc * tanimoto < s * 100)
                & (inter > 0)
                & (100 * inter > tanimoto * (rowc + s - inter)))
    keep_thr = (rowc >= threshold) & (inter >= threshold)
    keep = jnp.where(tanimoto > 0, keep_tan, keep_thr)
    return jnp.where(keep, inter, 0)


@functools.lru_cache(maxsize=256)
def _topn_filtered_sharded_fn(mesh: Mesh, expr, n_leaves: int,
                              mode: str | None):
    """Per-row counts with the reference's per-slice threshold/Tanimoto
    pruning applied BEFORE the slice reduction (fragment.go:560-614 —
    the per-slice algorithm drops a slice's contribution when that
    slice's row count or intersection count fails the bar, then the
    executor sums the survivors; exact integer forms of the float
    comparisons, identical results). threshold/tanimoto are runtime
    scalars — one compiled program per (mesh, expr)."""

    def per_shard(threshold, tanimoto, rows, *leaf_shards):
        return _psum_hi_lo_rows(_filtered_counts(
            expr, rows, jnp.stack(leaf_shards), threshold, tanimoto,
            mode))

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P()) + (P(AXIS_SLICES),) * (n_leaves + 1),
        out_specs=P(), check_vma=(mode is None))))


@_fair_dispatch
def topn_filtered_sharded(mesh: Mesh, expr, rows: jax.Array,
                          leaf_arrays: list[jax.Array],
                          threshold: int = 1,
                          tanimoto: int = 0) -> list[int]:
    """TopN counts with per-slice threshold/Tanimoto pruning on device
    (see _topn_filtered_sharded_fn). Same residency contract as
    topn_exact_sharded."""
    _dispatch_gate()
    if rows.shape[0] > slice_chunk_bound(mesh.shape[AXIS_SLICES]):
        raise ValueError("topn_filtered_sharded: slice count above the"
                         " int32 hi/lo bound")
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        fn = programs_mod.topn_program(mesh, expr, len(leaf_arrays),
                                       filtered=True)
    else:
        fn = _topn_filtered_sharded_fn(mesh, expr, len(leaf_arrays),
                                       mode)
    threshold = min(threshold, 2**31 - 1)  # counts never exceed 2^31
    _note_dispatch(rows, *leaf_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="topn_filtered",
                                rows=int(rows.shape[1])):
        return hilo_combine(
            fn(jnp.int32(threshold), jnp.int32(tanimoto), rows,
               *leaf_arrays))[:rows.shape[1]]


@_fair_dispatch
def topn_exact_sharded(mesh: Mesh, expr, rows: jax.Array,
                       leaf_arrays: list[jax.Array]) -> list[int]:
    """TopN exact counts over a DEVICE-resident candidate block
    ``rows [n_slices, R, W]`` and per-leaf slabs (all sharded over the
    slice axis, e.g. from the residency cache). Single program — the
    caller bounds n_slices (slice_chunk_bound) and the block bytes.
    """
    _dispatch_gate()
    if rows.shape[0] > slice_chunk_bound(mesh.shape[AXIS_SLICES]):
        raise ValueError("topn_exact_sharded: slice count above the"
                         " int32 hi/lo bound — use topn_exact")
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        fn = programs_mod.topn_program(mesh, expr, len(leaf_arrays),
                                       filtered=False)
    else:
        fn = _topn_exact_sharded_fn(mesh, expr, len(leaf_arrays), mode)
    _note_dispatch(rows, *leaf_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="topn_exact",
                                rows=int(rows.shape[1])):
        return hilo_combine(fn(rows, *leaf_arrays))[:rows.shape[1]]


@_fair_dispatch
def topn_topk_sharded(mesh: Mesh, expr, rows: jax.Array,
                      leaf_arrays: list[jax.Array],
                      k: int) -> tuple[list[int], list[int]]:
    """Sourceless-TopN top-k over a DEVICE-resident candidate block:
    counts reduce AND the top-k selection happens inside one program
    (programs.topn_topk_program), so the host fetches [3, k] instead
    of the whole [2, R] count table. Returns (counts, row indices),
    count-descending with ascending-index tie-break — the host
    pairs_sort order. Pallas meshes have no top-k kernel; there the
    exact-count program runs and the selection folds host-side, same
    contract."""
    _dispatch_gate()
    if rows.shape[0] > slice_chunk_bound(mesh.shape[AXIS_SLICES]):
        raise ValueError("topn_topk_sharded: slice count above the"
                         " int32 hi/lo bound")
    k = max(1, min(int(k), int(rows.shape[1])))
    if _mesh_pallas_mode(mesh) is not None:
        counts = topn_exact_sharded(mesh, expr, rows, leaf_arrays)
        order = np.lexsort((np.arange(len(counts)),
                            -np.asarray(counts)))[:k]
        return [counts[i] for i in order.tolist()], order.tolist()
    from . import programs as programs_mod
    fn = programs_mod.topn_topk_program(mesh, expr, len(leaf_arrays), k)
    _note_dispatch(rows, *leaf_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="topn_topk",
                                rows=int(rows.shape[1]), k=k):
        out = np.asarray(fn(rows, *leaf_arrays)).astype(np.int64)
    counts = ((out[0] << 16) + out[1]).tolist()
    return counts, out[2].tolist()


def shard_slices_axis1(mesh: Mesh, arr: np.ndarray) -> jax.Array:
    """Place ``[L, n_slices, ...]`` on the mesh, sharded over axis 1."""
    spec = [None] * arr.ndim
    spec[1] = AXIS_SLICES
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def _flatten_fold(expr):
    """(op, [leaf ids]) when ``expr`` is a pure left fold of one op over
    leaves — the shape _compile_device_expr builds for n-ary PQL calls.
    None for mixed trees. Iterative: a 1000-child Union is a 1000-deep
    left-leaning tuple tree, and recursing it would overflow Python's
    stack before XLA ever saw it."""
    op = expr[0]
    if op == "leaf":
        return None
    ids = []
    node = expr
    while isinstance(node, tuple) and node[0] == op:
        if node[2][0] != "leaf":
            return None
        ids.append(node[2][1])
        node = node[1]
    if node[0] != "leaf":
        return None
    ids.append(node[1])
    ids.reverse()
    return op, ids


def _eval_expr(expr, leaves):
    flat = _flatten_fold(expr)
    if (flat is not None and len(flat[1]) >= 3
            and flat[0] in ("or", "and", "andnot")):
        # Wide fold → one associative lax.reduce over the leaf axis
        # instead of a leaf-count-deep op chain. Left-fold Difference
        # rewrites exactly: ((a∖b)∖c)… = a ∧ ¬(b∨c∨…). (xor and any
        # other op fall through to the generic chain below.)
        op, ids = flat
        sel = leaves if list(ids) == list(range(leaves.shape[0])) \
            else leaves[jnp.asarray(ids)]
        if op == "or":
            return jax.lax.reduce(sel, np.uint32(0),
                                  jax.lax.bitwise_or, (0,))
        if op == "and":
            return jax.lax.reduce(sel, np.uint32(0xFFFFFFFF),
                                  jax.lax.bitwise_and, (0,))
        rest = jax.lax.reduce(sel[1:], np.uint32(0),
                              jax.lax.bitwise_or, (0,))
        return jnp.bitwise_and(sel[0], jnp.bitwise_not(rest))
    if expr[0] == "leaf":
        return leaves[expr[1]]
    return _BITWISE[expr[0]](_eval_expr(expr[1], leaves),
                             _eval_expr(expr[2], leaves))


@functools.lru_cache(maxsize=256)
def _topn_exact_fn_cached(mesh: Mesh, expr, mode: str | None):
    def per_shard(rows, leaves):  # rows: [S/n, R, W]; leaves: [L, S/n, W]
        return _psum_hi_lo_rows(
            _shard_topn_inter(expr, rows, leaves, mode))

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES), P(None, AXIS_SLICES)),
        out_specs=P(), check_vma=(mode is None))))


@functools.lru_cache(maxsize=256)
def _topn_filtered_fn_cached(mesh: Mesh, expr, mode: str | None):
    def per_shard(threshold, tanimoto, rows, leaves):
        return _psum_hi_lo_rows(_filtered_counts(
            expr, rows, leaves, threshold, tanimoto, mode))

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(), P(), P(AXIS_SLICES), P(None, AXIS_SLICES)),
        out_specs=P(), check_vma=(mode is None))))


def topn_filtered_fn(mesh: Mesh, expr):
    """The streaming-layout filtered TopN program: ``(threshold,
    tanimoto, rows [S, R, W], leaves [L, S, W]) → stacked [2, R]
    per-row (hi, lo)`` (decode via hilo_combine),
    with per-slice threshold/Tanimoto pruning before the reduction.
    Public for the pod layer (parallel.multihost), like topn_exact_fn."""
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        return programs_mod.topn_block_program(mesh, expr,
                                               filtered=True)
    return _topn_filtered_fn_cached(mesh, expr, mode)


def topn_exact_fn(mesh: Mesh, expr):
    """Exact candidate counts across slices, one psum-reduced program.

    rows [S, R, W] (candidate row blocks per slice) → stacked [2, R]
    per-row (hi, lo) — decode via hilo_combine
    16-bit halves of ``popcount(row ∩ expr)`` (or plain row popcount
    when expr is None), summed over every slice — the device form of
    the executor's TopN exact-count re-query (executor.go:273-310
    second phase). Per-(slice, row) counts ≤ 2^20 are split 16/16
    before the psum so int32 holds up to 2^15 slices per call (callers
    chunk above that). On TPU the per-shard body is the fused Pallas
    TopN block kernel. Public: the pod layer (parallel.multihost)
    feeds these programs process-local shards.
    """
    mode = _mesh_pallas_mode(mesh)
    if mode is None:
        from . import programs as programs_mod
        return programs_mod.topn_block_program(mesh, expr,
                                               filtered=False)
    return _topn_exact_fn_cached(mesh, expr, mode)


@_fair_dispatch
def materialize_expr_sharded(mesh: Mesh, expr,
                             leaf_arrays: list[jax.Array]) -> np.ndarray:
    """[S, W] dense words of the expression bitmap: one sharded device
    fold over the leaf slabs (the materializing form of count_expr —
    BASELINE config 2's Union/Difference over many rows), fetched to
    host for roaring repack. No count reduction → no slice-count bound;
    wide folds reduce associatively on device (_eval_expr's lax.reduce
    path). Always the global-view catalogue program (no Pallas body
    exists for materialization).
    """
    _dispatch_gate()
    from . import programs as programs_mod
    fn = programs_mod.materialize_program(mesh, expr, len(leaf_arrays))
    _note_dispatch(*leaf_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="materialize",
                                leaves=len(leaf_arrays)):
        return np.asarray(fn(*leaf_arrays))


@_fair_dispatch
def bsi_range_sharded(mesh: Mesh, op: str, upred, depth: int,
                      plane_arrays: list[jax.Array]) -> np.ndarray:
    """[S, W] dense matched words of a BSI comparison: the whole
    bit-plane circuit (storage.bsi semantics, ops.kernels circuit
    body) over device-resident plane slabs — ``plane_arrays[0]`` the
    existence row, ``plane_arrays[1+i]`` offset-value bit i, each
    ``[n_slices, W]`` sharded over the slice axis — as ONE compiled
    SPMD program per (mesh, op, depth). The predicate travels as a
    traced LSB-first bit vector, so repeated range queries at one
    depth reuse the compilation. ``op`` "><" takes ``upred = (lo,
    hi)`` in offset space; everything else a single offset predicate.
    """
    _dispatch_gate()
    from ..ops import kernels
    if op == "><":
        lo, hi = upred
        pbits = kernels.bsi_predicate_bits(lo, depth)
        pbits2 = kernels.bsi_predicate_bits(hi, depth)
    else:
        pbits = kernels.bsi_predicate_bits(upred, depth)
        pbits2 = np.zeros(depth, dtype=np.uint32)
    from . import programs as programs_mod
    fn = programs_mod.bsi_range_program(mesh, op, len(plane_arrays))
    _note_dispatch(*plane_arrays)
    with obs_trace.span_current("mesh_dispatch", kind="bsi_range",
                                depth=depth):
        return np.asarray(fn(pbits, pbits2, *plane_arrays))


# Device-block budget for one topn_exact call (mirrors the 256 MB
# per-block bound of the per-fragment path, fragment.py chunk=2048).
TOPN_BLOCK_BYTES = 256 << 20


@_fair_dispatch
def topn_exact(mesh: Mesh, expr, rows: np.ndarray,
               leaves: np.ndarray | None, threshold: int = 1,
               tanimoto: int = 0) -> list[int]:
    """[R] exact counts of each candidate row against ``expr`` (or the
    rows' own popcounts when expr is None), summed over all slices.
    threshold>1 / tanimoto engage the per-slice pruning program.

    Chunks both axes: slices at the int32 hi/lo bound and candidate
    rows by the device-block byte budget — counts are independent per
    row, additive per slice, and the pruning masks are per-slice, so
    any tiling is exact.
    """
    _dispatch_gate()
    n_dev = mesh.shape[AXIS_SLICES]
    filtered = threshold > 1 or tanimoto > 0
    if filtered:
        # Counts never exceed 2^31, so clamping is semantically exact
        # (and jnp.int32 would raise on larger Python ints).
        threshold = min(threshold, 2**31 - 1)
        fn = functools.partial(topn_filtered_fn(mesh, expr),
                               jnp.int32(threshold), jnp.int32(tanimoto))
    else:
        fn = topn_exact_fn(mesh, expr)
    from . import programs as programs_mod
    n_slices, n_rows, n_words = rows.shape
    slice_chunk = min(slice_chunk_bound(n_dev), n_slices) or 1
    row_chunk = max(1, TOPN_BLOCK_BYTES // (slice_chunk * n_words * 4))
    totals = [0] * n_rows
    for s_off in range(0, n_slices, slice_chunk):
        lc = None
        if leaves is not None:
            lc = leaves[:, s_off:s_off + slice_chunk]
        for r_off in range(0, n_rows, row_chunk):
            rc = rows[s_off:s_off + slice_chunk, r_off:r_off + row_chunk]
            lcc = lc if lc is not None else \
                np.zeros((0, rc.shape[0], 1), dtype=np.uint32)
            # Bucket-stable slice padding: zero slices are the count
            # identity, and the bucketed shape reuses one compiled
            # program across nearby slice counts.
            rc = programs_mod.bucket_pad(rc, 0, n_dev)
            lcc = programs_mod.bucket_pad(lcc, 1, n_dev)
            counts = hilo_combine(fn(shard_slices(mesh, rc),
                                     shard_slices_axis1(mesh, lcc)))
            for r in range(rc.shape[1]):
                totals[r_off + r] += counts[r]
    return totals


@functools.lru_cache(maxsize=None)
def _topn_fn(mesh: Mesh, op: str, k: int):
    """rows [S, R, W] × src [S, W] → (top-k counts, top-k row indices).

    Per-slice intersection counts for ALL candidate rows in one fused
    pass (the vectorized replacement for the reference's sequential
    threshold loop, fragment.go:560-614), psum'd over the slice axis,
    gathered over the row axis, then a single device top_k.
    """
    bitwise = _BITWISE[op]

    def per_shard(rows, src):  # rows: [S/n, R/m, W], src: [S/n, W]
        words = bitwise(rows, src[:, None, :])
        pc = jax.lax.population_count(words).astype(jnp.int32)
        counts = jnp.sum(pc, axis=(0, 2))              # [R/m]
        counts = jax.lax.psum(counts, AXIS_SLICES)     # slice reduce (ICI)
        counts = jax.lax.all_gather(counts, AXIS_ROWS,
                                    tiled=True)        # [R]
        vals, idx = jax.lax.top_k(counts, k)
        return vals, idx

    # check_vma off: the all_gather over ``rows`` makes counts replicated,
    # but the varying-axis inference can't prove it.
    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES, AXIS_ROWS), P(AXIS_SLICES)),
        out_specs=(P(), P()), check_vma=False)))


def topn_counts(mesh: Mesh, op: str, rows: jax.Array, src: jax.Array,
                k: int) -> tuple[np.ndarray, np.ndarray]:
    """(counts, row_indices) of the k candidate rows with the largest
    ``count(op(row, src))`` across all slices."""
    vals, idx = _topn_fn(mesh, op, k)(rows, src)
    return np.asarray(vals), np.asarray(idx)


@functools.lru_cache(maxsize=None)
def _query_step_fn(mesh: Mesh, k: int):
    """The flagship distributed query step, jitted over the full mesh.

    One fused SPMD program: Count(Intersect) + Count(Union) over a
    slice-sharded pair of bitmap slabs, plus TopN(k) of a row-sharded
    candidate block against the intersection — i.e. configs 4 and 5 of
    BASELINE.md in a single compiled step. Collectives: psum over
    ``slices``, all_gather over ``rows``.
    """

    def per_shard(a, b, rows):
        # a, b: [S/n, W]; rows: [S/n, R/m, W]
        inter = jnp.bitwise_and(a, b)
        union = jnp.bitwise_or(a, b)
        pc_i = jnp.sum(jax.lax.population_count(inter).astype(jnp.int32))
        pc_u = jnp.sum(jax.lax.population_count(union).astype(jnp.int32))
        n_inter = jax.lax.psum(pc_i, AXIS_SLICES)
        n_union = jax.lax.psum(pc_u, AXIS_SLICES)
        words = jnp.bitwise_and(rows, inter[:, None, :])
        counts = jnp.sum(jax.lax.population_count(words).astype(jnp.int32),
                         axis=(0, 2))
        counts = jax.lax.psum(counts, AXIS_SLICES)
        counts = jax.lax.all_gather(counts, AXIS_ROWS, tiled=True)
        top_vals, top_ids = jax.lax.top_k(counts, k)
        return n_inter, n_union, top_vals, top_ids

    return _finalize_program(jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(AXIS_SLICES), P(AXIS_SLICES),
                  P(AXIS_SLICES, AXIS_ROWS)),
        out_specs=(P(), P(), P(), P()), check_vma=False)))


def query_step(mesh: Mesh, a: jax.Array, b: jax.Array, rows: jax.Array,
               k: int):
    """Run the fused distributed query step; see _query_step_fn."""
    n_i, n_u, vals, ids = _query_step_fn(mesh, k)(a, b, rows)
    return int(n_i), int(n_u), np.asarray(vals), np.asarray(ids)

# Every lru_cache'd shard_map program builder still hosted here, for
# compile_stats()'s hit/miss aggregation (the global-view catalogue's
# caches live in parallel.programs.PROGRAM_CACHES and are folded in by
# _all_program_caches()).
_PROGRAM_CACHES = (
    _densify_sharded_fn, _count_fn, _count_expr_fn_cached,
    _count_exprs_fn_cached, _count_exprs_sharded_fn,
    _topn_exact_sharded_fn, _topn_filtered_sharded_fn,
    _topn_exact_fn_cached, _topn_filtered_fn_cached, _topn_fn,
    _query_step_fn,
)
