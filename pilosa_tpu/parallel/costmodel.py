"""Calibrated device/host routing for the executor's fast paths.

The reference has one path and always takes it (executor.go:1103-1236's
host map-reduce). This build has two — the roaring host path and the
mesh device path — and the right one depends on hardware the code can't
know statically: through a tunnel one host↔device round trip costs
~130 ms, while a direct-attached chip does it in ~1 ms. A fixed slice
threshold therefore mis-routes on one rig or the other (round 2's
measured c4: 128-slice Counts went to a device path 4× slower than the
host through the tunnel).

So the executor calibrates at first mesh use and predicts per query:

- ``sync_s``   — one measured no-op dispatch + result fetch round trip
                 (the device path's fixed cost, whatever the transport);
- ``host_bps`` — the measured roaring intersection-count rate on this
                 host (the host path's per-byte cost on packed words);
- ``device_bps`` — HBM-rate constant for the fused count kernel (the
                 device's per-byte cost; ~2nd-order vs the sync floor).

Routing rule: the device serves unless the predicted host cost is a
CLEAR win (< margin × device cost, margin 0.5 by default). The margin
keeps marginal shapes on the device, where residency caching and
dispatch batching improve repeat queries; the env override
``PILOSA_TPU_COST_MARGIN`` tunes it, ``PILOSA_TPU_COST_MODEL=0``
disables the veto entirely (pre-calibration behavior).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

# Assumed HBM streaming rate for the fused count kernel. Deliberately a
# constant: at the shapes where it matters the sync floor dominates, and
# measuring it well needs the big-operand bench (bench.py), not a
# startup probe. ~400 GB/s is v5e-class effective rate.
DEVICE_BPS = 4.0e11


@dataclass
class Calibration:
    sync_s: float       # one dispatch + fetch round trip, seconds
    host_bps: float     # roaring count throughput, bytes/second
    upload_bps: float = 1.0e9   # host→device transfer rate (measured)

    def device_cost(self, total_bytes: int, cold_bytes: int = 0) -> float:
        # cold_bytes = data not device-resident: it must be packed and
        # shipped at the measured transfer rate (through a tunnel this
        # is the dominant term — ~512 MB of candidate block costs
        # seconds, not the microseconds the HBM term suggests).
        return (self.sync_s + cold_bytes / self.upload_bps
                + total_bytes / DEVICE_BPS)

    def host_cost(self, total_bytes: int) -> float:
        return total_bytes / self.host_bps


class CostModel:
    def __init__(self, cal: Calibration, margin: float = 0.5):
        self.cal = cal
        self.margin = margin

    def device_pays(self, total_bytes: int, cold_bytes: int = 0) -> bool:
        """False only when the host path is a clear predicted win."""
        host = self.cal.host_cost(total_bytes)
        device = self.cal.device_cost(total_bytes, cold_bytes)
        return host >= self.margin * device


def _measure_sync_s(mesh) -> float:
    """One no-op dispatch + fetch through whatever transport this mesh
    uses (tunnel: ~130 ms; direct or CPU: ~1 ms). Compile excluded."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def probe(x):
        return x.sum()

    x = jax.device_put(jnp.ones(128, jnp.int32), mesh.devices.flat[0])
    int(probe(x))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        int(probe(x))  # int() forces the result fetch
        best = min(best, time.perf_counter() - t0)
    return max(best, 1e-6)


def _measure_upload_bps(mesh, sync_s: float) -> float:
    """Host→device transfer rate for a packed block. The measured wall
    time includes one round-trip floor (which device_cost prices
    separately as sync_s), so subtract it — on a tunnel rig the floor
    is ~10× a 16 MB transfer and would otherwise be double-counted,
    under-estimating the rate ~15×."""
    import jax

    buf = np.zeros(4 << 20, dtype=np.uint32)  # 16 MB
    dev = mesh.devices.flat[0]
    jax.device_put(buf, dev).block_until_ready()  # warm the path
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        jax.device_put(buf, dev).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    transfer_s = max(best - sync_s, best / 10, 1e-9)
    return buf.nbytes / transfer_s


def _measure_host_bps() -> float:
    """The host path's real per-byte rate: roaring intersection_count
    over dense bitmap containers (the shape the device path competes
    with), including the per-container Python dispatch cost."""
    from ..storage import roaring

    n_bits = 1 << 23  # 8 Mbit → 128 bitmap containers → 1 MB operands
    a = roaring.Bitmap.from_sorted(
        np.arange(0, n_bits, 2, dtype=np.uint64))
    b = roaring.Bitmap.from_sorted(
        np.arange(0, n_bits, 3, dtype=np.uint64))
    a.intersection_count(b)  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        a.intersection_count(b)
        best = min(best, time.perf_counter() - t0)
    # Bytes "processed" = both operands' packed words.
    return (2 * n_bits / 8) / max(best, 1e-9)


_cache: dict[str, Calibration] = {}
_cache_mu = threading.Lock()


def get_model(mesh, margin: float = 0.5) -> CostModel:
    """Calibrate once per backend platform per process; the margin is
    per-caller (a cached calibration must not freeze the first caller's
    margin for everyone). Measurement happens OUTSIDE the lock — on a
    tunnel rig it costs several ~130 ms round trips, and concurrent
    queries must not stall behind it; a losing racer just discards its
    duplicate measurement."""
    platform = mesh.devices.flat[0].platform
    with _cache_mu:
        cal = _cache.get(platform)
    if cal is None:
        sync_s = _measure_sync_s(mesh)
        cal = Calibration(sync_s=sync_s,
                          host_bps=_measure_host_bps(),
                          upload_bps=_measure_upload_bps(mesh, sync_s))
        with _cache_mu:
            cal = _cache.setdefault(platform, cal)
    return CostModel(cal, margin)
