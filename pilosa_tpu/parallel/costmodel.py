"""Calibrated device/host routing for the executor's fast paths.

The reference has one path and always takes it (executor.go:1103-1236's
host map-reduce). This build has two — the roaring host path and the
mesh device path — and the right one depends on hardware the code can't
know statically: through a tunnel one host↔device round trip costs
~130 ms, while a direct-attached chip does it in ~1 ms. A fixed slice
threshold therefore mis-routes on one rig or the other (round 2's
measured c4: 128-slice Counts went to a device path 4× slower than the
host through the tunnel).

So the executor calibrates at first mesh use and predicts per query:

- ``sync_s``   — one measured no-op dispatch + result fetch round trip
                 (the device path's fixed cost, whatever the transport);
- ``host_bps`` — the measured roaring intersection-count rate on this
                 host (the host path's per-byte cost on packed words);
- ``device_bps`` — HBM-rate constant for the fused count kernel (the
                 device's per-byte cost; ~2nd-order vs the sync floor).

Routing rule: the device serves unless the predicted host cost is a
CLEAR win (< margin × device cost, margin 0.5 by default). The margin
keeps marginal shapes on the device, where residency caching and
dispatch batching improve repeat queries; the env override
``PILOSA_TPU_COST_MARGIN`` tunes it, ``PILOSA_TPU_COST_MODEL=0``
disables the veto entirely (pre-calibration behavior).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

# Assumed HBM streaming rate for the fused count kernel. Deliberately a
# constant: at the shapes where it matters the sync floor dominates, and
# measuring it well needs the big-operand bench (bench.py), not a
# startup probe. ~400 GB/s is v5e-class effective rate.
DEVICE_BPS = 4.0e11

# Committed default constants — the MEASURED medians from the planner
# calibration pass (benchmarks/suite.py config_planner, MANIFEST
# ``planner.constants``), used before this machine's own calibration
# exists (planner placement pricing at cold start, tests). The startup
# probe + the drift loop supersede them at first mesh use. The earlier
# hand-picked defaults (upload 1.0e9, pack 2.0e9) over-estimated the
# roaring→dense pack rate ~16×, making cold uploads look cheap.
DEFAULT_SYNC_S = 1.5e-5      # direct-attached dispatch+fetch floor
DEFAULT_HOST_BPS = 9.2e9     # roaring intersection-count rate
DEFAULT_UPLOAD_BPS = 1.7e9   # host→device transfer rate
DEFAULT_PACK_BPS = 1.3e8     # host-side roaring→dense pack rate


@dataclass
class Calibration:
    sync_s: float       # one dispatch + fetch round trip, seconds
    host_bps: float     # roaring count throughput, bytes/second
    upload_bps: float = DEFAULT_UPLOAD_BPS  # host→device transfer rate
    pack_bps: float = DEFAULT_PACK_BPS  # roaring→dense pack rate
    # Drift-correction multipliers, adjusted by the feedback loop when
    # predicted and observed leg costs diverge (CostModel.record).
    host_scale: float = 1.0
    device_scale: float = 1.0
    # Extra multiplier for STREAMING device legs (block re-packed every
    # query): with packing priced by pack_bps these should predict
    # ~true, and their own scale lets the drift loop correct residual
    # streaming-only error without fighting the resident legs'
    # device_scale over one knob (VERDICT r4 item 6: price the packing
    # instead of excluding the leg from drift recording).
    stream_scale: float = 1.0

    def device_cost(self, total_bytes: int, cold_bytes: int = 0,
                    streaming: bool = False,
                    crossings: int = 1) -> float:
        # cold_bytes = data not device-resident: it must be PACKED
        # host-side (roaring → dense words at pack_bps) and shipped at
        # the measured transfer rate (through a tunnel the transfer is
        # the dominant term — ~512 MB of candidate block costs seconds,
        # not the microseconds the HBM term suggests).
        # crossings = host↔device round trips the plan actually pays:
        # a fused multi-op tree (executor._device_batch_run) dispatches
        # ONE program for the whole tree, so it pays sync_s once — not
        # once per Count/TopN call the tree contains.
        cost = (self.sync_s * crossings + cold_bytes / self.upload_bps
                + cold_bytes / self.pack_bps
                + total_bytes / DEVICE_BPS) * self.device_scale
        if streaming:
            cost *= self.stream_scale
        return cost

    def host_cost(self, total_bytes: int) -> float:
        return total_bytes / self.host_bps * self.host_scale

    def to_dict(self) -> dict:
        return {"sync_s": self.sync_s, "host_bps": self.host_bps,
                "upload_bps": self.upload_bps,
                "pack_bps": self.pack_bps,
                "host_scale": self.host_scale,
                "device_scale": self.device_scale,
                "stream_scale": self.stream_scale}

    @classmethod
    def from_dict(cls, d: dict) -> "Calibration":
        return cls(sync_s=float(d["sync_s"]),
                   host_bps=float(d["host_bps"]),
                   upload_bps=float(d.get("upload_bps",
                                          DEFAULT_UPLOAD_BPS)),
                   pack_bps=float(d.get("pack_bps", DEFAULT_PACK_BPS)),
                   host_scale=float(d.get("host_scale", 1.0)),
                   device_scale=float(d.get("device_scale", 1.0)),
                   stream_scale=float(d.get("stream_scale", 1.0)))


# Feedback-loop tuning: recalibrate a leg once it has DRIFT_MIN_SAMPLES
# observations whose median actual/predicted ratio leaves
# [1/DRIFT_BOUND, DRIFT_BOUND]; scales clamp to
# [1/_SCALE_CLAMP, _SCALE_CLAMP].
DRIFT_MIN_SAMPLES = 12
DRIFT_BOUND = 2.0
# Wide clamp: startup probes on shared VMs have been observed ~100x off
# (the exact scenario the loop exists to fix); the clamp only guards
# unbounded runaway, not plausible correction magnitudes.
_SCALE_CLAMP = 256.0


class CostModel:
    """Routing predictions + the closed feedback loop over them.

    Round-3 weakness: calibration happened once per process (one bad
    startup probe mis-priced every query until restart) and nothing
    compared predictions with reality. Now every routed query can
    record (predicted, actual) for the leg it ran; when the median
    drift of a leg exceeds DRIFT_BOUND x, that leg's scale multiplier
    is folded by the observed median and the (machine, platform)
    calibration is re-persisted — the model re-converges in-process,
    no restart needed."""

    def __init__(self, cal: Calibration, margin: float = 0.5,
                 persist_key: str | None = None):
        self.cal = cal
        self.margin = margin
        self.persist_key = persist_key
        self.recalibrations = 0
        self._mu = threading.Lock()
        self._drift = {"host": deque(maxlen=64),
                       "device": deque(maxlen=64),
                       "device_stream": deque(maxlen=64)}

    _SCALE_ATTR = {"host": "host_scale", "device": "device_scale",
                   "device_stream": "stream_scale"}

    def device_pays(self, total_bytes: int, cold_bytes: int = 0,
                    streaming: bool = False,
                    host_bytes: int | None = None,
                    crossings: int = 1) -> bool:
        """False only when the host path is a clear predicted win.

        ``host_bytes`` prices the host alternative on ITS real byte
        walk when it differs from the device operand block — a fused
        multi-op tree deduplicates shared leaf slabs on device, while
        the per-call host path re-walks each call's leaves (and packs
        every TopN candidate row); pricing both sides on the
        deduplicated block systematically over-charged the mesh leg
        for exactly the multi-op queries fusion accelerates.
        ``crossings`` is the number of device dispatches the plan pays
        (1 for a fused tree, whatever the chunk loop needs otherwise).
        """
        host = self.cal.host_cost(
            host_bytes if host_bytes is not None else total_bytes)
        device = self.cal.device_cost(total_bytes, cold_bytes,
                                      streaming, crossings=crossings)
        return host >= self.margin * device

    def predict(self, leg: str, total_bytes: int,
                cold_bytes: int = 0) -> float:
        if leg == "device":
            return self.cal.device_cost(total_bytes, cold_bytes)
        if leg == "device_stream":
            return self.cal.device_cost(total_bytes, cold_bytes,
                                        streaming=True)
        return self.cal.host_cost(total_bytes)

    def record(self, leg: str, predicted_s: float,
               actual_s: float) -> None:
        """Feed one routed query's (predicted, actual) leg cost back
        into the model; recalibrates when the median drift of that leg
        exceeds DRIFT_BOUND in either direction."""
        if predicted_s <= 0 or actual_s <= 0:
            return
        with self._mu:
            d = self._drift.get(leg)
            if d is None:
                return
            d.append(actual_s / predicted_s)
            if len(d) < DRIFT_MIN_SAMPLES:
                return
            med = sorted(d)[len(d) // 2]
            if 1.0 / DRIFT_BOUND <= med <= DRIFT_BOUND:
                return
            attr = self._SCALE_ATTR[leg]
            scale = getattr(self.cal, attr) * med
            scale = min(max(scale, 1.0 / _SCALE_CLAMP), _SCALE_CLAMP)
            setattr(self.cal, attr, scale)
            d.clear()
            self.recalibrations += 1
        if self.persist_key:
            _persist_calibration(self.persist_key, self.cal)

    def drift_snapshot(self) -> dict:
        with self._mu:
            out = {}
            for leg, d in self._drift.items():
                vals = sorted(d)
                out[leg] = {
                    "n": len(vals),
                    "median": round(vals[len(vals) // 2], 3) if vals
                    else None}
            out["recalibrations"] = self.recalibrations
            out["hostScale"] = round(self.cal.host_scale, 4)
            out["deviceScale"] = round(self.cal.device_scale, 4)
            out["streamScale"] = round(self.cal.stream_scale, 4)
            return out


def _measure_sync_s(mesh) -> float:
    """One no-op dispatch + fetch through whatever transport this mesh
    uses (tunnel: ~130 ms; direct or CPU: ~1 ms). Compile excluded."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def probe(x):
        return x.sum()

    x = jax.device_put(jnp.ones(128, jnp.int32), mesh.devices.flat[0])
    int(probe(x))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        int(probe(x))  # int() forces the result fetch
        best = min(best, time.perf_counter() - t0)
    return max(best, 1e-6)


def _measure_upload_bps(mesh, sync_s: float) -> float:
    """Host→device transfer rate for a packed block. The measured wall
    time includes one round-trip floor (which device_cost prices
    separately as sync_s), so subtract it — on a tunnel rig the floor
    is ~10× a 16 MB transfer and would otherwise be double-counted,
    under-estimating the rate ~15×."""
    import jax

    buf = np.zeros(4 << 20, dtype=np.uint32)  # 16 MB
    dev = mesh.devices.flat[0]
    jax.device_put(buf, dev).block_until_ready()  # warm the path
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        jax.device_put(buf, dev).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    transfer_s = max(best - sync_s, best / 10, 1e-9)
    return buf.nbytes / transfer_s


def _measure_pack_bps() -> float:
    """Host-side roaring→dense packing rate (the streaming device legs
    re-pack their candidate block every query; round 4 excluded them
    from drift recording because this term was unpriced)."""
    from ..ops import packed
    from ..storage import roaring

    rng = np.random.default_rng(3)
    storage = roaring.Bitmap.from_sorted(np.sort(rng.choice(
        1 << 23, size=1 << 18, replace=False)).astype(np.uint64))
    out = np.zeros(packed.WORDS_PER_SLICE, dtype=np.uint32)
    packed.pack_storage_row(storage, 0, out)  # warm
    best = float("inf")
    for _ in range(3):
        out[:] = 0
        t0 = time.perf_counter()
        for row in range(8):
            packed.pack_storage_row(storage, row % 8, out)
        best = min(best, time.perf_counter() - t0)
    return 8 * out.nbytes / max(best, 1e-9)


def _measure_host_bps() -> float:
    """The host path's real per-byte rate: roaring intersection_count
    over dense bitmap containers (the shape the device path competes
    with), including the per-container Python dispatch cost."""
    from ..storage import roaring

    n_bits = 1 << 23  # 8 Mbit → 128 bitmap containers → 1 MB operands
    a = roaring.Bitmap.from_sorted(
        np.arange(0, n_bits, 2, dtype=np.uint64))
    b = roaring.Bitmap.from_sorted(
        np.arange(0, n_bits, 3, dtype=np.uint64))
    a.intersection_count(b)  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        a.intersection_count(b)
        best = min(best, time.perf_counter() - t0)
    # Bytes "processed" = both operands' packed words.
    return (2 * n_bits / 8) / max(best, 1e-9)


_cache: dict[str, Calibration] = {}
_cache_mu = threading.Lock()


def _cal_path(key: str) -> str:
    from ..utils import cache_dir
    return cache_dir(f"costcal-{key}.json")


def _persist_calibration(key: str, cal: Calibration) -> None:
    try:
        path = _cal_path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "w") as f:
            json.dump(cal.to_dict(), f)
        os.replace(path + ".tmp", path)
    except OSError:
        pass  # persistence is best-effort


def _load_calibration(key: str) -> Calibration | None:
    try:
        with open(_cal_path(key)) as f:
            return Calibration.from_dict(json.load(f))
    except (OSError, ValueError, KeyError):
        return None


def default_calibration() -> Calibration:
    """Best available constants WITHOUT touching a mesh: this
    machine's persisted calibration when one exists (whatever platform
    it was measured on — the host-side rates carry across and the sync
    floor is in the right decade), the committed measured defaults
    otherwise. Used to prime the planner's placement pricing before
    the first device query calibrates for real (sched.warmup)."""
    import glob
    import platform as platform_mod
    try:
        pattern = _cal_path(f"{platform_mod.node()}-*")
        for path in sorted(glob.glob(pattern)):
            with open(path) as f:
                return Calibration.from_dict(json.load(f))
    except (OSError, ValueError, KeyError):
        pass
    return Calibration(sync_s=DEFAULT_SYNC_S,
                       host_bps=DEFAULT_HOST_BPS)


def get_model(mesh, margin: float = 0.5) -> CostModel:
    """Calibrate once per backend platform per process; the margin is
    per-caller (a cached calibration must not freeze the first caller's
    margin for everyone). Measurement happens OUTSIDE the lock — on a
    tunnel rig it costs several ~130 ms round trips, and concurrent
    queries must not stall behind it; a losing racer just discards its
    duplicate measurement.

    Calibrations persist per (machine, platform) across restarts
    (~/.cache/pilosa_tpu/costcal-*.json): a restart reuses the tuned
    model — including feedback-loop scale corrections — instead of
    re-pricing the world from one startup probe. Delete the file or
    set PILOSA_TPU_COST_RECAL=1 to force a fresh measurement."""
    import platform as platform_mod
    platform = mesh.devices.flat[0].platform
    key = f"{platform_mod.node()}-{platform}"
    with _cache_mu:
        cal = _cache.get(platform)
    if cal is None:
        if os.environ.get("PILOSA_TPU_COST_RECAL") != "1":
            cal = _load_calibration(key)
        if cal is None:
            sync_s = _measure_sync_s(mesh)
            cal = Calibration(
                sync_s=sync_s,
                host_bps=_measure_host_bps(),
                upload_bps=_measure_upload_bps(mesh, sync_s),
                pack_bps=_measure_pack_bps())
            _persist_calibration(key, cal)
        with _cache_mu:
            cal = _cache.setdefault(platform, cal)
    return CostModel(cal, margin, persist_key=key)
