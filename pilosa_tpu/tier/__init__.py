"""Tiered storage: the working-set manager (docs/STORAGE.md).

The index is allowed to be much bigger than RAM: the tier subsystem
decides *what lives where* and moves it safely between three
residency tiers —

- **hot** — the fragment is fully open: mmap-resident storage, TopN
  cache ranked, device rows eligible for HBM residency.
- **cold** — the fragment was demoted: WAL barriered, op-log folded
  into a fresh checksummed snapshot, caches flushed, and the file
  reopened metadata-only. Container blocks fault back in on first
  read, each verified against the PR-15 footer's per-block crc table
  (the block map), so queries against cold fragments transparently
  promote exactly what they touch.
- **blob** — the cold file itself left local disk through the
  pluggable blob store (tier.blob; the local-dir backend stands in
  for object storage), pushed block-diff-style so a re-push after a
  small change moves only the changed blocks. A ``<path>.blob`` stub
  keeps the fragment discoverable across restarts; the first read
  fetches, verifies the reassembled footer, and re-enters cold.

The :class:`~pilosa_tpu.tier.ledger.ResidencyLedger` tracks every
fragment's tier, byte footprint, and last-touching tenant; the
:class:`~pilosa_tpu.tier.manager.TierManager` runs the demotion /
eviction / blob / prefetch loops against it, honoring the PR-14
per-tenant cache-share discipline so one tenant's cold scan can
never flush another tenant's working set.
"""

from .blob import BlobStore, LocalDirBlobStore, open_blob_store  # noqa: F401
from .ledger import ResidencyLedger  # noqa: F401
from .manager import ColdFetchError, TierManager  # noqa: F401
