"""TierManager: the working-set manager's control loops.

One paced background loop per node (plus an optional prefetch loop)
runs four phases over the residency ledger each pass:

1. **Sync** — reconcile the ledger with the holder's open fragments
   (new fragments enter at their current tier, closed ones drop out)
   and refresh hot byte footprints.
2. **Idle demotion** — hot fragments untouched for ``[tier] idle``
   close to a checksummed cold snapshot (``Fragment.demote_cold``:
   WAL barrier → op-log fold → metadata-only reopen).
3. **Watermark eviction** — when resident bytes exceed
   ``high_watermark × resident_budget``, the ledger's victim order
   (over-cache-share tenants first, LRU within; see tier.ledger)
   demotes hot fragments and re-chills cold ones until resident falls
   to the low watermark.
4. **Blob push** — cold fragments untouched for ``blob_idle`` leave
   local disk through the pluggable blob store (tier.blob block-diff
   push); a ``<path>.blob`` stub keeps them discoverable.

Cold-fetch failures (``ColdFetchError``) mark the fragment's
(index, slice) **blocked**: the executor consults
``holder.tier_blocked`` exactly like the quarantine registry's
``slice_blocked``, so reads fail over / degrade per the ``?partial=1``
contract instead of returning a wrong answer. The loop retries
blocked fetches each pass and unblocks on success — self-healing, no
operator action.

The prefetcher reads the ``pilosa_tier_fragment_touches_total`` rate
series from the on-disk metric history (obs.history) — the same
per-(tenant, index, slice) touch counter the read gate feeds — and
promotes the hottest cold fragments while resident stays under the
low watermark, skipping entirely when admission is busy.

Lock discipline: the ledger is a leaf lock (never held while taking
fragment locks); transitions take ``frag._snap_mu`` then ``frag._mu``
(the fragment's own close/snapshot order); the manager's ``_mu``
guards only its maps and is never held across a transition.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..fault import failpoints as _fp
from ..obs import metrics as obs_metrics
from ..storage import integrity as integrity_mod
from ..utils import logger as logger_mod
from . import blob as blob_mod
from .ledger import BLOB, COLD, HOT, ResidencyLedger

DEFAULT_INTERVAL_S = 10.0
DEFAULT_PACE_S = 0.01

TOUCH_FAMILY = "pilosa_tier_fragment_touches_total"


class ColdFetchError(OSError):
    """A blob-tier fragment could not be materialized (store
    unreachable, objects missing, reassembly failed verification).
    Subclasses OSError so transport-style error handling treats it as
    'the read failed here' — the executor fails the slice over /
    degrades per the partial contract, never serves a guess."""


class TierManager:
    def __init__(self, holder, *, resident_budget: int = 0,
                 high_watermark: float = 0.9,
                 low_watermark: float = 0.7,
                 idle_s: float = 300.0, blob_idle_s: float = 3600.0,
                 cold_dir: str = "", blob: str = "",
                 interval_s: float = DEFAULT_INTERVAL_S,
                 prefetch_interval_s: float = 0.0,
                 pace_s: float = DEFAULT_PACE_S,
                 tenants=None, history=None, busy_fn=None,
                 logger=None):
        self.holder = holder
        self.ledger = ResidencyLedger()
        self.resident_budget = int(resident_budget)
        self.high_watermark = float(high_watermark)
        self.low_watermark = min(float(low_watermark),
                                 float(high_watermark))
        self.idle_s = float(idle_s)
        self.blob_idle_s = float(blob_idle_s)
        self.cold_dir = cold_dir
        if cold_dir:
            os.makedirs(cold_dir, exist_ok=True)
        self.store = blob_mod.open_blob_store(blob, cold_dir or ".")
        self.interval_s = max(0.05, float(interval_s))
        self.prefetch_interval_s = float(prefetch_interval_s)
        self.pace_s = max(0.0, float(pace_s))
        self.tenants = tenants          # sched.tenants.TenantRegistry
        self.history = history          # obs.history.MetricHistory
        self.busy_fn = busy_fn          # () -> bool: admission busy?
        self.logger = logger or logger_mod.NOP
        self._mu = threading.Lock()
        self._frags: dict[tuple, object] = {}
        # (index, frame, view, slice) -> {"reason", "since"}; the
        # slice rollup mirrors QuarantineRegistry.slice_blocked.
        self._blocked: dict[tuple, dict] = {}
        self._blocked_slices: dict[tuple, int] = {}
        # Stall bookkeeping (the watchdog's tier_stall input): work
        # was pending at the end of a pass but no transition has
        # completed since _last_transition.
        self._work_pending = False
        self._last_transition = time.monotonic()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Lifetime counters for /debug/tier (metrics carry the same
        # numbers; these avoid a registry scrape in state()).
        self.demotions = 0
        self.rechills = 0
        self.promotions = 0
        self.blob_pushes = 0
        self.blob_fetches = 0
        self.fetch_failures = 0
        self.errors = 0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(target=self._run, name="pilosa-tier",
                             daemon=True)
        t.start()
        self._threads = [t]
        if self.prefetch_interval_s > 0:
            p = threading.Thread(target=self._run_prefetch,
                                 name="pilosa-tier-prefetch",
                                 daemon=True)
            p.start()
            self._threads.append(p)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.pass_once()
            except Exception as e:  # noqa: BLE001 - the loop must not die
                self.logger.printf("tier: pass failed: %s", e)

    def _run_prefetch(self) -> None:
        while not self._stop.wait(self.prefetch_interval_s):
            try:
                self.prefetch_once()
            except Exception as e:  # noqa: BLE001
                self.logger.printf("tier: prefetch failed: %s", e)

    # -- the pass -------------------------------------------------------------

    def pass_once(self) -> dict:
        """One manager pass; returns a summary for tests/debug."""
        self.sync()
        demoted = self._demote_idle()
        evicted = self._evict()
        pushed = self._push_idle()
        retried = self._retry_blocked()
        self.ledger.update_gauges()
        # Work is "pending" when pressure remains that only a future
        # transition can relieve: still over the high watermark, or
        # blocked fetches outstanding. The watchdog trips tier_stall
        # when this stays true with no transition completing.
        over = (self.resident_budget > 0
                and self.ledger.resident_bytes()
                > int(self.high_watermark * self.resident_budget))
        self._work_pending = over or bool(self._blocked)
        return {"demoted": demoted, "evicted": evicted,
                "pushed": pushed, "retried": retried}

    def sync(self) -> None:
        """Reconcile ledger + fragment hooks with the holder."""
        seen = set()
        for frag in self.holder.iter_fragments():
            if not getattr(frag, "_open", False):
                continue
            key = self.ledger.key_of(frag)
            seen.add(key)
            with self._mu:
                known = self._frags.get(key)
                self._frags[key] = frag
            if known is not frag:
                frag.tier = self
            st = getattr(frag, "tier_state", HOT)
            e = self.ledger.get(frag)
            if e is None:
                self.ledger.track(frag, st, self._frag_bytes(frag, st))
            elif e.tier != st:
                # Out-of-band transition (operator demote_cold, crash
                # recovery): the fragment is the record, not the ledger.
                self.ledger.set_tier(frag, st, self._frag_bytes(frag, st))
            elif e.tier == HOT:
                # Hot footprints drift as writes land; refresh.
                e.nbytes = self._frag_bytes(frag, HOT)
        with self._mu:
            gone = [(k, self._frags.pop(k))
                    for k in list(self._frags) if k not in seen]
        for key, frag in gone:
            self.ledger.forget(frag)
            self._unblock_key(key)

    @staticmethod
    def _frag_bytes(frag, tier: str) -> int:
        path = frag.path if tier != BLOB else frag.path + ".blob"
        try:
            if tier == BLOB:
                with open(path, "rb") as f:
                    return int(json.load(f).get("size", 0))
            return os.path.getsize(path)
        except (OSError, ValueError):
            return 0

    def _demote_idle(self) -> int:
        if self.idle_s <= 0:
            return 0
        n = 0
        for key in self.ledger.idle_hot(self.idle_s):
            if self._stop.is_set():
                break
            frag = self._frags.get(key)
            if frag is not None and self._demote(frag, "idle"):
                n += 1
        return n

    def _evict(self) -> int:
        budget = self.resident_budget
        if budget <= 0:
            return 0
        resident = self.ledger.resident_bytes()
        if resident <= int(self.high_watermark * budget):
            return 0
        need = resident - int(self.low_watermark * budget)
        n = 0
        for key in self.ledger.victims(need, budget, self._shares()):
            if self._stop.is_set():
                break
            frag = self._frags.get(key)
            if frag is None:
                continue
            e = self.ledger.get(frag)
            if e is None:
                continue
            if e.tier == HOT:
                if self._demote(frag, "watermark"):
                    n += 1
            elif e.tier == COLD and e.faulted_bytes > 0:
                if self._rechill(frag):
                    n += 1
        return n

    def _shares(self) -> Optional[dict]:
        reg = self.tenants
        if reg is None:
            return None
        try:
            shares = {name: float(reg.policy(name).cache_share)
                      for name in reg.known()}
        except Exception:  # noqa: BLE001 - shares are advisory
            return None
        # The ledger falls back to shares.get("", 1.0) for tenants
        # with no configured policy; map that to the default policy.
        from ..utils.config import DEFAULT_TENANT
        shares[""] = shares.get(DEFAULT_TENANT, 1.0)
        return shares

    def _demote(self, frag, reason: str) -> bool:
        self.ledger.pin(frag, True)
        try:
            try:
                nbytes = frag.demote_cold()
            except OSError as e:
                # ENOSPC mid-snapshot (or any write failure): the old
                # file stays the record, the fragment stays hot, and
                # the diskfull degradation (507s) throttles writers —
                # demotion just didn't happen this pass.
                self.logger.printf("tier: demotion failed %s/%s/%s/%d:"
                                   " %s", frag.index, frag.frame,
                                   frag.view, frag.slice, e)
                self.errors += 1
                return False
            if nbytes <= 0:
                return False
            self.ledger.set_tier(frag, COLD, nbytes)
            obs_metrics.TIER_DEMOTIONS.labels(reason).inc()
            self.demotions += 1
            self._transition()
        finally:
            self.ledger.pin(frag, False)
        self._pace()
        return True

    def _rechill(self, frag) -> bool:
        """Reclaim a cold fragment's faulted residency by resetting
        its fault set — the cold scanner pays for its own scan."""
        self.ledger.pin(frag, True)
        try:
            if not frag.tier_rechill():
                return False
            self.ledger.set_tier(frag, COLD)  # resets faulted bytes
            obs_metrics.TIER_DEMOTIONS.labels("watermark").inc()
            self.rechills += 1
            self._transition()
        finally:
            self.ledger.pin(frag, False)
        self._pace()
        return True

    # -- blob tier ------------------------------------------------------------

    def _push_idle(self) -> int:
        if self.store is None or self.blob_idle_s <= 0:
            return 0
        n = 0
        for key in self.ledger.idle_cold(self.blob_idle_s):
            if self._stop.is_set():
                break
            frag = self._frags.get(key)
            if frag is not None and self.push_blob(frag):
                n += 1
        return n

    def push_blob(self, frag) -> bool:
        """Move one cold fragment's file into the blob store (block
        diff), then replace it with a ``.blob`` stub. Crash-safe
        order: objects → stub → remove file — at every kill point the
        restart either still has the data file (stub deleted, re-push
        re-diffs) or has a complete stub + pushed objects."""
        if self.store is None:
            return False
        self.ledger.pin(frag, True)
        try:
            with frag._snap_mu, frag._mu:
                if (not frag._open or frag.quarantined
                        or getattr(frag, "tier_state", HOT) != COLD):
                    return False
                storage = frag.storage
                info = getattr(storage, "footer", None)
                if (storage is None or storage.op_n or info is None
                        or info.offsets is None):
                    return False
                end = info.body_len + info.size
                mm = frag._mmap
                if mm is None or len(mm) < end:
                    return False
                buf = bytes(mm[:end])
                prefix = blob_mod.fragment_prefix(
                    frag.index, frag.frame, frag.view, frag.slice)
                try:
                    if _fp.ACTIVE is not None:
                        _fp.ACTIVE.hit("tier.fetch", host="push",
                                       path=frag.path)
                    blob_mod.push_fragment(self.store, prefix, buf,
                                           info)
                except OSError as e:
                    obs_metrics.TIER_FETCHES.labels(
                        "push", "error").inc()
                    self.logger.printf("tier: blob push failed %s: %s",
                                       prefix, e)
                    self.errors += 1
                    return False
                obs_metrics.TIER_FETCHES.labels("push", "ok").inc()
                stub = {"index": frag.index, "frame": frag.frame,
                        "view": frag.view, "slice": frag.slice,
                        "prefix": prefix, "size": end,
                        "bodyLen": info.body_len,
                        "bodyCrc": int(info.body_crc),
                        "blocks": info.block_n}
                tmp = frag.path + ".blob.tmp"
                with open(tmp, "wb") as f:
                    f.write(json.dumps(stub).encode())
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, frag.path + ".blob")
                frag._close_storage()
                frag.storage = None
                frag.tier_state = BLOB
                frag._cold_pending = None
                for p in (frag.path, frag.cache_path):
                    try:
                        os.remove(p)
                    except FileNotFoundError:
                        pass
            self.ledger.set_tier(frag, BLOB, end)
            obs_metrics.TIER_DEMOTIONS.labels("blob").inc()
            self.blob_pushes += 1
            self._transition()
        finally:
            self.ledger.pin(frag, False)
        self._pace()
        return True

    def fetch_blob(self, frag) -> None:
        """Materialize a blob-tier fragment's data file back onto
        local disk. Called UNDER ``frag._mu`` from the fragment's
        read-path gate (the caller reopens storage afterwards). The
        reassembled bytes are verified against the manifest's block
        crcs AND the footer's whole-body digest before the
        ``os.replace`` — a wrong answer can never be admitted, only a
        ColdFetchError raised (which blocks the slice until a retry
        succeeds)."""
        t0 = time.perf_counter()
        prefix = blob_mod.fragment_prefix(frag.index, frag.frame,
                                          frag.view, frag.slice)
        try:
            if self.store is None:
                raise ColdFetchError(
                    f"tier: no blob store configured for {prefix}")
            if _fp.ACTIVE is not None:
                _fp.ACTIVE.hit("tier.fetch", host="fetch",
                               path=frag.path)
            buf = blob_mod.fetch_fragment(self.store, prefix)
            man = blob_mod.read_manifest(self.store, prefix)
            info = integrity_mod.parse_footer(
                buf, int(man["bodyLen"]))
            if info is None:
                raise integrity_mod.CorruptionError(
                    f"blob fragment {prefix}: fetched file has no"
                    f" footer")
            integrity_mod.verify_body(buf, info)
            tmp = frag.path + ".fetching"
            with open(tmp, "wb") as f:
                f.write(buf)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, frag.path)
        except (OSError, ValueError) as e:
            corrupt = isinstance(e, integrity_mod.CorruptionError)
            obs_metrics.TIER_FETCHES.labels(
                "fetch", "corrupt" if corrupt else "error").inc()
            self.fetch_failures += 1
            self._mark_blocked(frag, str(e))
            if isinstance(e, ColdFetchError):
                raise
            raise ColdFetchError(
                f"tier: cold fetch failed for {prefix}: {e}") from e
        try:
            os.remove(frag.path + ".blob")
        except OSError:
            pass
        obs_metrics.TIER_FETCHES.labels("fetch", "ok").inc()
        obs_metrics.TIER_FAULT_SECONDS.observe(
            time.perf_counter() - t0)
        self.blob_fetches += 1
        self._unblock(frag)
        self._transition()

    def note_fetched(self, frag, nbytes: int) -> None:
        """The fragment finished its post-fetch cold reopen."""
        self.ledger.track(frag, COLD, nbytes)

    def _retry_blocked(self) -> int:
        """Re-attempt blocked fetches (store back up, objects
        repaired). Success unblocks the slice — reads resume without
        operator action."""
        with self._mu:
            keys = list(self._blocked)
        n = 0
        for key in keys:
            if self._stop.is_set():
                break
            frag = self._frags.get(key)
            if frag is None:
                continue
            try:
                with frag._mu:
                    if getattr(frag, "tier_state", HOT) != BLOB:
                        self._unblock(frag)
                        continue
                    frag._tier_fetch_locked()
                n += 1
                self._pace()
            except (OSError, ValueError):
                continue
        return n

    # -- blocked-slice surface (the executor consult) -------------------------

    def _mark_blocked(self, frag, reason: str) -> None:
        key = self.ledger.key_of(frag)
        with self._mu:
            if key not in self._blocked:
                sk = (frag.index, frag.slice)
                self._blocked_slices[sk] = \
                    self._blocked_slices.get(sk, 0) + 1
            self._blocked[key] = {"index": frag.index,
                                  "frame": frag.frame,
                                  "view": frag.view,
                                  "slice": frag.slice,
                                  "reason": reason,
                                  "since": time.time()}
        self.logger.printf(
            "tier: BLOCKED %s/%s/%s/%d (cold fetch failed): %s",
            frag.index, frag.frame, frag.view, frag.slice, reason)

    def _unblock(self, frag) -> None:
        self._unblock_key(self.ledger.key_of(frag))

    def _unblock_key(self, key: tuple) -> None:
        with self._mu:
            if self._blocked.pop(key, None) is None:
                return
            sk = (key[0], key[3])
            n = self._blocked_slices.get(sk, 0) - 1
            if n <= 0:
                self._blocked_slices.pop(sk, None)
            else:
                self._blocked_slices[sk] = n

    def slice_blocked(self, index: str, slice: int) -> bool:
        """True when a blob-tier fragment of (index, slice) cannot be
        fetched — the read path must not serve the slice locally
        (same contract as QuarantineRegistry.slice_blocked)."""
        if not self._blocked_slices:  # lock-free empty fast path
            return False
        return (index, slice) in self._blocked_slices

    # -- read-path hooks (called under frag._mu; ledger is a leaf) ------------

    def on_access(self, frag) -> None:
        """Every gated read lands here: stamp the ledger and feed the
        touch counter the prefetcher ranks by."""
        from ..sched import context as sched_context
        ctx = sched_context.current()
        tenant = getattr(ctx, "tenant", "") if ctx is not None else ""
        self.ledger.touch(frag, tenant)
        obs_metrics.TIER_TOUCH.labels(tenant or "default", frag.index,
                                      str(frag.slice)).inc()

    def note_fault(self, frag, nbytes: int) -> None:
        self.ledger.note_fault(frag, nbytes)

    def note_promoted(self, frag, nbytes: int, trigger: str) -> None:
        # The TIER_PROMOTIONS counter is incremented by the fragment's
        # _tier_promote_locked (the one site every trigger funnels
        # through) — only the ledger/lifetime accounting lives here.
        self.ledger.set_tier(frag, HOT, nbytes)
        self.promotions += 1
        self._transition()

    # -- prefetch -------------------------------------------------------------

    def prefetch_once(self) -> int:
        """Promote the hottest cold/blob fragments (by recent touch
        rate from the metric history) while resident stays under the
        low watermark. Returns promotions made."""
        cold_keys = self.ledger.keys(COLD) + self.ledger.keys(BLOB)
        if not cold_keys or self.history is None:
            return 0
        if self.busy_fn is not None and self.busy_fn():
            obs_metrics.TIER_PREFETCH.labels("skipped_busy").inc()
            return 0
        rates = self._touch_rates()
        if not rates:
            return 0
        budget = self.resident_budget
        low = int(self.low_watermark * budget) if budget > 0 else 0
        scored = sorted(
            cold_keys,
            key=lambda k: -rates.get((k[0], k[3]), 0.0))
        n = 0
        for key in scored:
            if self._stop.is_set():
                break
            if rates.get((key[0], key[3]), 0.0) <= 0.0:
                break
            frag = self._frags.get(key)
            if frag is None:
                continue
            e = self.ledger.get(frag)
            if e is None or e.pinned:
                continue
            if (budget > 0 and self.ledger.resident_bytes() + e.nbytes
                    > low):
                obs_metrics.TIER_PREFETCH.labels(
                    "skipped_budget").inc()
                break
            try:
                frag.promote(trigger="prefetch")
                obs_metrics.TIER_PREFETCH.labels("promoted").inc()
                n += 1
            except (OSError, ValueError) as e:
                obs_metrics.TIER_PREFETCH.labels("error").inc()
                self.logger.printf("tier: prefetch failed %s: %s",
                                   frag.path, e)
            self._pace()
        return n

    def _touch_rates(self) -> dict[tuple, float]:
        """(index, slice) -> mean touch rate over the recent history
        window. Touch counters are per-(tenant, index, slice); tenants
        sum — prefetch ranks fragments, not tenants."""
        out: dict[tuple, float] = {}
        try:
            res = self.history.series(family=TOUCH_FAMILY,
                                      window_s=600.0)
        except Exception:  # noqa: BLE001 - history is advisory
            return out
        for s in res.get("series", ()):
            labels = s.get("labels") or {}
            idx = labels.get("index")
            try:
                slc = int(labels.get("slice", ""))
            except (TypeError, ValueError):
                continue
            pts = [v for _t, v in s.get("points", ())]
            if not pts or idx is None:
                continue
            rate = sum(pts) / len(pts)
            out[(idx, slc)] = out.get((idx, slc), 0.0) + rate
        return out

    # -- bookkeeping ----------------------------------------------------------

    def _transition(self) -> None:
        self._last_transition = time.monotonic()

    def _pace(self) -> None:
        if self.pace_s:
            self._stop.wait(self.pace_s)

    def stall_age(self) -> Optional[float]:
        """Seconds since the last completed transition while work is
        pending, or None when nothing is waiting on the manager (the
        watchdog tier_stall input)."""
        if not self._work_pending:
            return None
        return time.monotonic() - self._last_transition

    def scrub_blob(self, frag) -> dict:
        """The scrubber's blob-tier leg: verify the fragment's blob
        objects against manifest crcs + body digest (same verdict
        shape as scrub_file). A corrupt verdict does NOT quarantine —
        the local node holds no bytes to distrust; it blocks the
        fetch path instead so the failure surfaces as degraded, not
        wrong."""
        if self.store is None:
            return {"corrupt": False, "coverage": "none",
                    "error": "no blob store", "blocks": 0}
        prefix = blob_mod.fragment_prefix(frag.index, frag.frame,
                                          frag.view, frag.slice)
        verdict = blob_mod.verify_fragment(self.store, prefix)
        if verdict.get("corrupt"):
            self._mark_blocked(
                frag, f"scrub: {verdict.get('error', 'corrupt')}")
        return verdict

    # -- exposition -----------------------------------------------------------

    def state(self) -> dict:
        counts = self.ledger.counts()
        with self._mu:
            blocked = [dict(v) for v in self._blocked.values()]
        return {
            "enabled": True,
            "residentBudget": self.resident_budget,
            "residentBytes": self.ledger.resident_bytes(),
            "highWatermark": self.high_watermark,
            "lowWatermark": self.low_watermark,
            "idleS": self.idle_s,
            "blobIdleS": self.blob_idle_s,
            "intervalS": self.interval_s,
            "prefetchIntervalS": self.prefetch_interval_s,
            "tiers": {t: {"fragments": n, "bytes": b}
                      for t, (n, b) in counts.items()},
            "tenantResident": self.ledger.tenant_resident(),
            "demotions": self.demotions,
            "rechills": self.rechills,
            "promotions": self.promotions,
            "blobPushes": self.blob_pushes,
            "blobFetches": self.blob_fetches,
            "fetchFailures": self.fetch_failures,
            "errors": self.errors,
            "blocked": blocked,
            "store": self.store.state() if self.store else None,
            "stallAgeS": (round(self.stall_age(), 1)
                          if self.stall_age() is not None else None),
        }

    def entries(self, tier: str = "") -> list[dict]:
        return self.ledger.entries(tier)
