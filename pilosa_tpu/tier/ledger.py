"""Residency ledger: who lives where, how big, and whose working set.

One entry per tracked fragment, keyed (index, frame, view, slice):
the tier (hot / cold / blob), the on-disk byte footprint, how many of
a cold fragment's bytes have been faulted back in, the monotonic
last-touch stamp, and the tenant whose reads last touched it. The
manager's watermark loop asks the ledger two questions:

- ``resident_bytes()`` — the number the budget is stated against:
  hot fragments count whole, cold fragments count their faulted
  blocks only, blob fragments count nothing.
- ``victims(...)`` — which fragments to demote to get back under the
  low watermark, honoring the per-tenant cache-share discipline
  (sched.tenants ``cache_share``): tenants OVER their share of the
  resident budget are drained first (LRU within each), and tenants
  under their share are only touched when every over-share tenant is
  exhausted — so a cold-scanning tenant's own fragments absorb its
  own pressure before anyone else's working set pays.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics

HOT = "hot"
COLD = "cold"
BLOB = "blob"

# Ledger attribution for reads outside any tenant context (library
# calls, background loops). Matches the tenants-subsystem default.
DEFAULT_TENANT = "default"


class Entry:
    __slots__ = ("key", "tier", "nbytes", "faulted_bytes",
                 "last_touch", "tenant", "pinned")

    def __init__(self, key: tuple, tier: str, nbytes: int,
                 tenant: str = DEFAULT_TENANT):
        self.key = key
        self.tier = tier
        self.nbytes = int(nbytes)
        self.faulted_bytes = 0
        self.last_touch = time.monotonic()
        self.tenant = tenant
        # True while the manager is mid-transition on this fragment
        # (demoting, pushing, fetching) — victim selection skips it.
        self.pinned = False

    def resident(self) -> int:
        if self.tier == HOT:
            return self.nbytes
        if self.tier == COLD:
            return min(self.faulted_bytes, self.nbytes)
        return 0

    def to_json(self) -> dict:
        return {"index": self.key[0], "frame": self.key[1],
                "view": self.key[2], "slice": self.key[3],
                "tier": self.tier, "bytes": self.nbytes,
                "faultedBytes": self.faulted_bytes,
                "tenant": self.tenant,
                "idleS": round(time.monotonic() - self.last_touch, 1)}


class ResidencyLedger:
    """Thread-safe; the internal lock is a LEAF — never held while
    acquiring fragment or manager locks (the read-path touch runs
    under the fragment lock, the demotion loop takes fragment locks
    first), so the two directions cannot deadlock."""

    def __init__(self):
        self._mu = threading.Lock()
        self._entries: dict[tuple, Entry] = {}

    @staticmethod
    def key_of(frag) -> tuple:
        return (frag.index, frag.frame, frag.view, frag.slice)

    # -- tracking -------------------------------------------------------------

    def track(self, frag, tier: str, nbytes: int) -> Entry:
        key = self.key_of(frag)
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                e = Entry(key, tier, nbytes)
                self._entries[key] = e
            else:
                e.tier = tier
                e.nbytes = int(nbytes)
            if tier != COLD:
                e.faulted_bytes = 0
            return e

    def forget(self, frag) -> None:
        with self._mu:
            self._entries.pop(self.key_of(frag), None)

    def get(self, frag) -> Optional[Entry]:
        return self._entries.get(self.key_of(frag))

    def touch(self, frag, tenant: str = "") -> None:
        e = self._entries.get(self.key_of(frag))
        if e is not None:
            e.last_touch = time.monotonic()
            if tenant:
                e.tenant = tenant

    def note_fault(self, frag, nbytes: int) -> None:
        e = self._entries.get(self.key_of(frag))
        if e is not None:
            e.faulted_bytes += int(nbytes)

    def set_tier(self, frag, tier: str, nbytes: Optional[int] = None
                 ) -> None:
        with self._mu:
            e = self._entries.get(self.key_of(frag))
            if e is None:
                return
            e.tier = tier
            if nbytes is not None:
                e.nbytes = int(nbytes)
            if tier != COLD:
                e.faulted_bytes = 0

    def pin(self, frag, pinned: bool) -> None:
        e = self._entries.get(self.key_of(frag))
        if e is not None:
            e.pinned = pinned

    # -- accounting -----------------------------------------------------------

    def resident_bytes(self) -> int:
        with self._mu:
            return sum(e.resident() for e in self._entries.values())

    def tenant_resident(self) -> dict[str, int]:
        with self._mu:
            out: dict[str, int] = {}
            for e in self._entries.values():
                r = e.resident()
                if r:
                    out[e.tenant] = out.get(e.tenant, 0) + r
            return out

    def counts(self) -> dict[str, tuple[int, int]]:
        """{tier: (fragments, bytes)} — bytes are the tier's total
        data bytes (resident share for cold is faulted only)."""
        with self._mu:
            out = {HOT: [0, 0], COLD: [0, 0], BLOB: [0, 0]}
            for e in self._entries.values():
                row = out[e.tier]
                row[0] += 1
                row[1] += e.nbytes
            return {k: (v[0], v[1]) for k, v in out.items()}

    def update_gauges(self) -> None:
        counts = self.counts()
        resident = self.resident_bytes()
        for tier, (n, nbytes) in counts.items():
            obs_metrics.TIER_FRAGMENTS.labels(tier).set(n)
            obs_metrics.TIER_BYTES.labels(tier).set(nbytes)
        obs_metrics.TIER_BYTES.labels("resident").set(resident)

    # -- victim selection -----------------------------------------------------

    def idle_hot(self, idle_s: float) -> list[tuple]:
        """Hot entries untouched for ``idle_s`` — the idle-sweep
        demotion candidates, oldest first."""
        now = time.monotonic()
        with self._mu:
            out = [e for e in self._entries.values()
                   if e.tier == HOT and not e.pinned
                   and now - e.last_touch >= idle_s]
        out.sort(key=lambda e: e.last_touch)
        return [e.key for e in out]

    def idle_cold(self, idle_s: float) -> list[tuple]:
        """Cold entries untouched for ``idle_s`` — blob-push
        candidates, oldest first."""
        now = time.monotonic()
        with self._mu:
            out = [e for e in self._entries.values()
                   if e.tier == COLD and not e.pinned
                   and now - e.last_touch >= idle_s]
        out.sort(key=lambda e: e.last_touch)
        return [e.key for e in out]

    def victims(self, need_bytes: int, budget: int,
                shares: Optional[dict[str, float]] = None
                ) -> list[tuple]:
        """Fragments to demote (hot) or re-chill (cold with faulted
        blocks — a cold scan's residency is reclaimed by resetting
        its fault set, not by touching anyone else) to reclaim
        ``need_bytes``, in eviction order. The per-tenant discipline:
        tenants whose resident usage exceeds ``share × budget`` give
        up residency first (most-over-share tenant's LRU entry
        first); tenants under their share are only drained once no
        over-share tenant has anything left to give. With no shares
        (or no budget) this degrades to plain global LRU."""
        with self._mu:
            cands = [e for e in self._entries.values()
                     if not e.pinned and e.resident() > 0]
            usage: dict[str, int] = {}
            for e in self._entries.values():
                r = e.resident()
                if r:
                    usage[e.tenant] = usage.get(e.tenant, 0) + r
        if not cands:
            return []
        cands.sort(key=lambda e: e.last_touch)
        if not shares or budget <= 0:
            out, got = [], 0
            for e in cands:
                if got >= need_bytes:
                    break
                out.append(e.key)
                got += e.resident()
            return out

        def over_by(tenant: str) -> int:
            share = shares.get(tenant, shares.get("", 1.0))
            return usage.get(tenant, 0) - int(share * budget)

        # Two passes: over-share tenants' LRU entries first (the
        # most-over tenant pays first and its usage is debited as we
        # pick, so pressure drains proportionally), then — only if
        # still short — everyone else's global LRU.
        out: list[tuple] = []
        got = 0
        remaining = list(cands)
        while got < need_bytes:
            over = [e for e in remaining if over_by(e.tenant) > 0]
            if not over:
                break
            # Most-over tenant's least-recently-touched entry.
            over.sort(key=lambda e: (-over_by(e.tenant), e.last_touch))
            e = over[0]
            remaining.remove(e)
            out.append(e.key)
            got += e.resident()
            usage[e.tenant] = usage.get(e.tenant, 0) - e.resident()
        for e in remaining:
            if got >= need_bytes:
                break
            out.append(e.key)
            got += e.resident()
        return out

    # -- introspection --------------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self, tier: str = "") -> list[dict]:
        with self._mu:
            return [e.to_json() for e in self._entries.values()
                    if not tier or e.tier == tier]

    def keys(self, tier: str = "") -> list[tuple]:
        with self._mu:
            return [k for k, e in self._entries.items()
                    if not tier or e.tier == tier]
