"""Pluggable blob store for the truly-cold tier and the backup archive.

The store holds demoted fragment snapshots decomposed the same way
the resize FragmentStreamer moves them: per container block, keyed by
content. A fragment's blob layout::

    <prefix>/manifest.json   {"bodyLen", "footerLen", "blockN",
                              "crcs": [u32...], "head": "head-<crc>",
                              "blocks": ["blk-<i>-<crc>", ...],
                              "tail": "tail-<hash>", "size"}
    <prefix>/head-<crc32>    header region [0, offsets[0])
    <prefix>/blk-<i>-<crc32> container block i's bytes
    <prefix>/tail-<hash>     footer bytes [bodyLen, bodyLen+footerLen)
                             (hash-named, not crc: a footer ends with
                             its own crc32, so crc32(tail) is the same
                             constant for every valid footer)

Pushes are block-diffs: a block object whose name (index + crc32,
straight from the PR-15 footer table) already exists is skipped, so
re-pushing a fragment after a small change uploads only the changed
blocks — the same convergence economics as the resize stream, against
a store instead of a peer. Objects are content-named and writes are
tmp+rename, so a crashed push never leaves a readable-but-wrong
object; the manifest lands last and is the commit point.

The object-pool helpers (``build_manifest`` / ``push_objects`` /
``fetch_objects`` / ``verify_objects``) take the manifest explicitly,
so a consumer that stores manifests elsewhere — the backup archive
keeps them inside a whole-backup manifest, letting every backup share
one content-addressed pool — reuses the exact push/verify machinery
the tier uses. ``push_fragment`` / ``fetch_fragment`` /
``verify_fragment`` keep the tier's per-prefix-manifest layout.

:class:`LocalDirBlobStore` stands in for object storage (one file per
object under a root dir). Any object store with put/get/delete/exists
semantics slots in behind :class:`BlobStore`.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import zlib
from typing import Optional

from ..storage import integrity as integrity_mod


class BlobStore:
    """Minimal object-store surface the tier manager needs."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def state(self) -> dict:
        return {"kind": type(self).__name__}


def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives a host
    crash. Best-effort on platforms whose dirs refuse O_RDONLY opens
    or fsync (the rename itself is still atomic)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class LocalDirBlobStore(BlobStore):
    """One file per object under ``root`` — the local-dir backend
    standing in for object storage. Keys use ``/`` separators and map
    to subdirectories; writes are tmp+rename within the root so a
    concurrent reader never sees a torn object, and both the object
    bytes and the parent directory entry are fsynced before ``put``
    returns — the archive consistency contract requires that a
    visible object is a READABLE object even across a host crash
    (without the directory fsync, the rename itself can be lost while
    a dependent manifest written later survives)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        if ".." in key or key.startswith("/"):
            raise ValueError(f"bad blob key: {key!r}")
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        parent = os.path.dirname(path) or self.root
        os.makedirs(parent, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=parent, prefix=".put-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _fsync_dir(parent)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def get(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> list[str]:
        out = []
        base = self.root
        for root, _dirs, files in os.walk(base):
            for name in files:
                if name.startswith(".put-"):
                    continue
                rel = os.path.relpath(os.path.join(root, name), base)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def state(self) -> dict:
        keys = self.list()
        return {"kind": "dir", "root": self.root, "objects": len(keys)}


def open_blob_store(spec: str, cold_dir: str) -> Optional[BlobStore]:
    """``[tier] blob`` spec → a store. ``""`` disables the blob tier;
    ``dir`` roots the local-dir backend at ``<cold_dir>/blob``;
    ``dir:<path>`` roots it explicitly."""
    if not spec:
        return None
    if spec == "dir":
        return LocalDirBlobStore(os.path.join(cold_dir, "blob"))
    if spec.startswith("dir:"):
        return LocalDirBlobStore(spec[len("dir:"):])
    raise ValueError(f"unknown tier blob backend: {spec!r}")


def fragment_prefix(index: str, frame: str, view: str, slice: int
                    ) -> str:
    return f"{index}/{frame}/{view}/{slice}"


# -- shared object-pool machinery ---------------------------------------------


def build_manifest(buf: bytes,
                   info: integrity_mod.FooterInfo) -> dict:
    """The per-fragment object manifest for a verified cold snapshot
    (body + footer, no op records): content-derived object names plus
    the footer's block geometry. Pure — no store I/O."""
    offs = info.offsets
    sizes = info.sizes
    head_end = int(offs[0]) if info.block_n else info.body_len
    head = bytes(buf[:head_end])
    tail = bytes(buf[info.body_len:info.body_len + info.size])
    return {"bodyLen": info.body_len, "footerLen": info.size,
            "blockN": info.block_n,
            "crcs": [int(c) for c in info.crcs],
            "offsets": [int(o) for o in offs],
            "sizes": [int(s) for s in sizes],
            "head": f"head-{zlib.crc32(head) & 0xFFFFFFFF:08x}",
            "blocks": [f"blk-{i}-{int(info.crcs[i]):08x}"
                       for i in range(info.block_n)],
            # NOT crc-named: the footer ends with its own crc32, and
            # crc32(data || crc32(data)) is the constant residue
            # 0x2144DF1C for EVERY valid footer — crc-naming (even
            # seeded or prefixed: CRC is affine, equal-length tails
            # shift identically) would alias every tail in a shared
            # pool to one object.
            "tail": f"tail-{hashlib.blake2b(tail, digest_size=4).hexdigest()}",
            "size": info.body_len + info.size}


def push_objects(store: BlobStore, prefix: str, buf: bytes,
                 manifest: dict, put=None) -> tuple[int, int]:
    """Push the head/block/tail objects ``manifest`` names under
    ``prefix``, skipping objects the store already holds — the
    block-diff push. Does NOT write a manifest (the caller owns the
    commit point). ``put`` overrides the store write (fault-injection
    wrappers). Returns (objects_pushed, bytes_pushed)."""
    put = put or (lambda key, data: store.put(key, data))
    offs = manifest["offsets"]
    sizes = manifest["sizes"]
    body_len = int(manifest["bodyLen"])
    block_n = int(manifest["blockN"])
    head_end = int(offs[0]) if block_n else body_len
    pushed = nbytes = 0
    parts = [(manifest["head"], bytes(buf[:head_end]))]
    for i in range(block_n):
        off, size = int(offs[i]), int(sizes[i])
        parts.append((manifest["blocks"][i],
                      bytes(buf[off:off + size])))
    parts.append((manifest["tail"],
                  bytes(buf[body_len:body_len
                            + int(manifest["footerLen"])])))
    for name, data in parts:
        key = f"{prefix}/{name}"
        if store.exists(key):
            continue
        put(key, data)
        pushed, nbytes = pushed + 1, nbytes + len(data)
    return pushed, nbytes


def fetch_objects(store: BlobStore, prefix: str, manifest: dict,
                  get=None) -> bytes:
    """Reassemble a fragment file from the objects ``manifest`` names.
    Raises CorruptionError when any object's bytes contradict the
    manifest's recorded crcs or sizes — the caller discards and
    retries/blocks, never admits bad bytes. ``get`` overrides the
    store read (fault-injection wrappers)."""
    get = get or (lambda key: store.get(key))
    parts = [get(f"{prefix}/{manifest['head']}")]
    for i, name in enumerate(manifest["blocks"]):
        data = get(f"{prefix}/{name}")
        want = int(manifest["crcs"][i])
        if (zlib.crc32(data) & 0xFFFFFFFF) != want:
            raise integrity_mod.CorruptionError(
                f"blob fragment {prefix}: block {i} crc mismatch")
        parts.append(data)
    parts.append(get(f"{prefix}/{manifest['tail']}"))
    buf = b"".join(parts)
    if len(buf) != int(manifest["size"]):
        raise integrity_mod.CorruptionError(
            f"blob fragment {prefix}: reassembled {len(buf)}B,"
            f" manifest says {manifest['size']}B")
    return buf


def verify_objects(store: BlobStore, prefix: str,
                   manifest: dict) -> dict:
    """Scrub one fragment's objects: every object's bytes against the
    manifest crcs (block objects) and the reassembled body against
    the footer digest. Verdict dict in the scrub_file shape."""
    try:
        buf = fetch_objects(store, prefix, manifest)
    except integrity_mod.CorruptionError as e:
        return {"corrupt": True, "error": str(e), "coverage": "full"}
    except OSError as e:
        return {"corrupt": True, "error": f"missing object: {e}",
                "coverage": "none"}
    try:
        info = integrity_mod.parse_footer(buf, int(manifest["bodyLen"]))
        if info is None:
            return {"corrupt": True, "error": "no footer",
                    "coverage": "none"}
        integrity_mod.verify_body(buf, info)
    except ValueError as e:
        return {"corrupt": True, "error": str(e), "coverage": "full"}
    return {"corrupt": False, "coverage": "full",
            "blocks": int(manifest["blockN"]),
            "bytes": len(buf)}


# -- the tier's per-prefix-manifest layout ------------------------------------


def push_fragment(store: BlobStore, prefix: str, buf: bytes,
                  info: integrity_mod.FooterInfo) -> tuple[int, int]:
    """Decompose a verified cold snapshot (body + footer, no op
    records) into content-named objects under ``prefix``, skipping
    blocks the store already holds — the block-diff push. Returns
    (objects_pushed, bytes_pushed). The manifest write is the commit
    point and always lands last."""
    manifest = build_manifest(buf, info)
    pushed, nbytes = push_objects(store, prefix, buf, manifest)
    store.put(f"{prefix}/manifest.json",
              json.dumps(manifest).encode())
    return pushed, nbytes


def read_manifest(store: BlobStore, prefix: str) -> Optional[dict]:
    try:
        return json.loads(store.get(f"{prefix}/manifest.json"))
    except (OSError, ValueError):
        return None


def fetch_fragment(store: BlobStore, prefix: str) -> bytes:
    """Reassemble a fragment file from its blob objects. Raises
    CorruptionError when any object's bytes contradict the manifest's
    recorded crcs or the reassembled footer fails verification — the
    caller discards and retries/blocks, never admits bad bytes."""
    manifest = read_manifest(store, prefix)
    if manifest is None:
        raise integrity_mod.CorruptionError(
            f"blob fragment {prefix}: no manifest")
    return fetch_objects(store, prefix, manifest)


def delete_fragment(store: BlobStore, prefix: str) -> int:
    """Drop every object under ``prefix`` (manifest FIRST, so a crash
    mid-delete leaves an unreadable — not wrong — remainder)."""
    n = 0
    store.delete(f"{prefix}/manifest.json")
    for key in store.list(prefix + "/"):
        store.delete(key)
        n += 1
    return n


def verify_fragment(store: BlobStore, prefix: str) -> dict:
    """Scrub one blob fragment: every object's bytes against the
    manifest crcs (block objects) and the reassembled body against
    the footer digest. Verdict dict in the scrub_file shape."""
    manifest = read_manifest(store, prefix)
    if manifest is None:
        return {"corrupt": True, "error": "no manifest",
                "coverage": "none"}
    return verify_objects(store, prefix, manifest)
