"""Automatic replica repair of quarantined fragments.

The read path already fails over around a quarantined fragment
(executor skips the local owner; peers' legs re-map), so quarantine is
safe — but a quarantined copy is a replica DOWN: one more failure and
the slice degrades to the ``?partial=1`` contract. The repairer closes
the loop: for every quarantined fragment it

1. picks a healthy replica owner (breaker-ordered, open circuits
   skipped — the PR-5 placement discipline);
2. drops the suspect local state (``Fragment.reset_for_repair`` —
   the data file moves aside, a fresh footered WAL takes its place so
   concurrent writes keep landing durably);
3. re-streams the content source→local through the directed
   :class:`~pilosa_tpu.server.syncer.FragmentStreamer` built for
   elastic resize — the identical block-diff protocol, with the
   TARGET side served by an in-process adapter (the local fragment
   answers its block checksums and applies the additive import
   directly; the HTTP fragment routes refuse quarantined fragments so
   remote anti-entropy can't consume the incomplete copy);
4. runs the diff until a pass pushes zero bits (convergence — every
   block checksum matches the source), snapshots the repaired state
   to disk under a fresh footer, re-verifies the file, and
   un-quarantines.

Acked writes that arrived while the local copy was corrupt are not
lost: every write fans out to all replica owners, so the source
replica already holds them and the re-stream brings them home. With
NO healthy replica (replicas=1, or every peer down/corrupt) the
fragment stays quarantined — degraded per the partial contract, never
a silent wrong answer — and the repairer retries on its rescan
cadence in case a replica returns.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..cluster.client import Client, ClientError
from ..errors import FragmentNotFoundError
from ..obs import metrics as obs_metrics
from ..utils import logger as logger_mod
from .syncer import FragmentStreamer

DEFAULT_RESCAN_S = 15.0
DEFAULT_RETRY_S = 30.0
MAX_DIFF_PASSES = 8


class _LocalTarget:
    """The FragmentStreamer's view of THIS node as a stream target,
    bypassing HTTP: the fragment routes answer 409 for quarantined
    fragments (a half-streamed copy must not feed remote anti-entropy
    or resize diffs), so the repairer reads block checksums and
    applies the additive import in-process instead."""

    def __init__(self, holder):
        self.holder = holder

    def fragment_blocks(self, index: str, frame: str, view: str,
                        slice: int, host=None):
        frag = self.holder.fragment(index, frame, view, slice)
        if frag is None:
            raise FragmentNotFoundError()
        return frag.blocks()

    def fragment_import(self, index: str, frame: str, view: str,
                        slice: int, positions, host=None) -> None:
        f = self.holder.frame(index, frame)
        if f is None:
            raise FragmentNotFoundError()
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(slice)
        frag.import_positions(np.asarray(positions, dtype=np.uint64))


class Repairer:
    """One background thread draining the holder's quarantine
    registry. Wakes on every new quarantine (the registry's
    ``on_quarantine`` hook) and rescans on a slow cadence to catch
    entries recorded before it started (open-time quarantines) and
    no-replica entries whose replica may have returned."""

    def __init__(self, holder, cluster, host: str,
                 client_factory=Client, fault=None,
                 pace_s: float = 0.0,
                 rescan_s: float = DEFAULT_RESCAN_S,
                 retry_s: float = DEFAULT_RETRY_S,
                 logger=logger_mod.NOP):
        self.holder = holder
        self.cluster = cluster
        self.host = host
        self.client_factory = client_factory
        self.fault = fault
        self.pace_s = pace_s
        self.rescan_s = max(0.05, float(rescan_s))
        self.retry_s = float(retry_s)
        self.logger = logger
        self.repairs = 0
        self.failures = 0
        self._local = _LocalTarget(holder)
        self._last_attempt: dict[tuple, float] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        q = getattr(holder, "quarantine", None)
        if q is not None:
            q.on_quarantine = self._note

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-repair",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)

    def _note(self, frag) -> None:
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.rescan_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.repair_all()
            except Exception as e:  # noqa: BLE001 - repairer must not die
                self.logger.printf("repair: pass failed: %s", e)

    # -- repair --------------------------------------------------------------

    def repair_all(self) -> int:
        """One pass over every quarantined fragment; returns the number
        repaired."""
        repaired = 0
        for frag in self.holder.iter_fragments():
            if self._stop.is_set():
                break
            if not frag.quarantined or not frag._open:
                continue
            key = (frag.index, frag.frame, frag.view, frag.slice)
            now = time.monotonic()
            last = self._last_attempt.get(key, 0.0)
            if last and now - last < self.retry_s:
                continue
            self._last_attempt[key] = now
            if self.repair_fragment(frag) == "repaired":
                repaired += 1
                self._last_attempt.pop(key, None)
        return repaired

    def _source_peers(self, frag) -> list:
        """Healthy replica owners to stream from, breaker-ordered —
        local and open-circuit peers excluded. A mid-resize moving
        slice defers (the resize streamer owns those fragments)."""
        if self.cluster.moving_slice(frag.index, frag.slice) is not None:
            return []
        owners = [n for n in self.cluster.fragment_nodes(
            frag.index, frag.slice) if n.host != self.host]
        if self.fault is not None and len(owners) > 1:
            owners = self.fault.order_nodes(owners, local=self.host)
        return [n for n in owners
                if self.fault is None
                or self.fault.would_allow(n.host)]

    def repair_fragment(self, frag) -> str:
        """Repair ONE quarantined fragment; returns the outcome
        (``repaired`` / ``failed`` / ``no_replica``)."""
        peers = self._source_peers(frag)
        if not peers:
            obs_metrics.STORAGE_REPAIRS.labels("no_replica").inc()
            self.logger.printf(
                "repair: %s/%s/%s/%d has no healthy replica — stays"
                " quarantined (partial contract)", frag.index,
                frag.frame, frag.view, frag.slice)
            return "no_replica"
        last_err: Optional[Exception] = None
        for peer in peers:
            try:
                if self._repair_from(frag, peer.host):
                    obs_metrics.STORAGE_REPAIRS.labels(
                        "repaired").inc()
                    self.repairs += 1
                    self.logger.printf(
                        "repair: %s/%s/%s/%d restored from %s",
                        frag.index, frag.frame, frag.view, frag.slice,
                        peer.host)
                    return "repaired"
            except (ClientError, FragmentNotFoundError, OSError) as e:
                last_err = e
                self.logger.printf(
                    "repair: %s/%s/%s/%d from %s failed: %s",
                    frag.index, frag.frame, frag.view, frag.slice,
                    peer.host, e)
        obs_metrics.STORAGE_REPAIRS.labels("failed").inc()
        self.failures += 1
        del last_err
        return "failed"

    def _repair_from(self, frag, source_host: str) -> bool:
        """The re-stream against one source replica: reset, diff-until-
        clean through the FragmentStreamer, persist + re-verify,
        un-quarantine."""
        # The source must actually HOLD the fragment before we trust a
        # zero-bit diff as convergence: stream_fragment answers (0, 0)
        # for a MISSING source too, and un-quarantining against a peer
        # that never materialized the fragment would serve the fresh
        # empty replacement as authoritative — the silent-wrong-answer
        # class this subsystem exists to kill. (An EXISTING empty
        # fragment answers [] here, not 404 — genuinely-empty repairs
        # stay valid.) Raises FragmentNotFoundError/ClientError to the
        # caller's next-peer loop.
        probe = self.client_factory(source_host)
        probe.fragment_blocks(frag.index, frag.frame, frag.view,
                              frag.slice, host=source_host)
        frag.reset_for_repair()

        def factory(host, _src=source_host):
            if host == self.host:
                return self._local
            return self.client_factory(host)

        streamer = FragmentStreamer(client_factory=factory,
                                    logger=self.logger,
                                    fault=self.fault,
                                    pace_s=self.pace_s)
        converged = False
        for _ in range(MAX_DIFF_PASSES):
            bits, _nbytes = streamer.stream_fragment(
                frag.index, frag.frame, frag.view, frag.slice,
                source_host=source_host, target_host=self.host)
            if bits == 0:
                # Every block checksum matches the source: converged.
                converged = True
                break
        if not converged:
            return False
        # Persist the repaired state under a fresh footer, atomically
        # swapping the data file, then re-verify the bytes on disk
        # before trusting them (verify_on_disk re-quarantines on a
        # corrupt verdict, so a bad disk fails loudly here).
        frag.snapshot(sync=True)
        verdict = frag.verify_on_disk()
        if verdict.get("corrupt"):
            return False  # bad disk: stays quarantined, retried later
        frag.clear_quarantine()
        return True

    def state(self) -> dict:
        return {"repairs": self.repairs, "failures": self.failures,
                "rescanS": self.rescan_s, "retryS": self.retry_s}
