"""Server runtime: the composition root.

Reference: server.go. Binds the listener (``:0`` supported), opens the
Holder, wires Cluster/Broadcaster/Executor/Handler, serves HTTP on a
threading WSGI server, and runs the background loops: anti-entropy
(server.go:182-214), max-slice polling of peers (server.go:216-252), and
the 1-minute cache flush (holder.go:324-358). ``receive_message`` applies
the five schema-mutation broadcasts (server.go:255-300).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from ..cluster.broadcast import (NOP_BROADCASTER, CancelQueryMessage,
                                 ResizeMessage, StaticNodeSet)
from ..cluster import resize as resize_mod
from ..cluster.client import Client
from ..cluster.topology import (NODE_STATE_DOWN, NODE_STATE_UP, Cluster,
                                Node)
from ..errors import PilosaError
from ..executor import Executor
from ..fault import FaultManager
from ..fault import failpoints as fault_failpoints
from ..models.frame import FrameOptions
from ..models.holder import Holder
from ..models.index import IndexOptions
from ..obs import accounting as obs_accounting
from ..obs import blackbox as obs_blackbox
from ..obs.federate import Federator
from ..obs.history import MetricHistory
from ..obs.metrics import RegistryStatsClient, default_registry
from ..obs.profile import ContinuousProfiler
from ..obs.runtime import RuntimeCollector, build_info
from ..obs.sampler import TailSampler
from ..obs.sentinel import Sentinel
from ..obs.slo import SLOTracker, TenantSLOTracker
from ..obs.trace import Tracer
from ..obs.watchdog import Watchdog
from ..proto import internal_pb2 as pb
from ..sched import (AdmissionController, QueryRegistry, TenantRegistry,
                     Warmup, warmup_enabled)
from ..utils import logger as logger_mod
from ..storage.scrub import Scrubber
from ..tier.manager import TierManager
from ..utils.config import (BlackboxConfig, CaptureConfig, FaultConfig,
                            HistoryConfig, MetricsConfig, ProfileConfig,
                            QueryConfig, ScrubConfig, SentinelConfig,
                            SLOConfig, TenantsConfig, TierConfig,
                            TraceConfig, WatchdogConfig,
                            parse_resolutions)
from ..utils.stats import NOP, MultiStatsClient
from .handler import Handler
from .httpd import HTTPServer
from .repair import Repairer

DEFAULT_ANTI_ENTROPY_INTERVAL = 600.0   # seconds (server.go:37)
DEFAULT_POLLING_INTERVAL = 60.0         # max-slice poll (server.go:33)
CACHE_FLUSH_INTERVAL = 60.0             # holder.go:31


class Server:
    """One pilosa-tpu node."""

    def __init__(self, data_dir: str, host: str = "localhost:10101",
                 cluster: Optional[Cluster] = None, broadcaster=None,
                 broadcast_receiver=None, stats=NOP,
                 anti_entropy_interval: float
                 = DEFAULT_ANTI_ENTROPY_INTERVAL,
                 polling_interval: float = DEFAULT_POLLING_INTERVAL,
                 logger=logger_mod.NOP,
                 query_config: Optional[QueryConfig] = None,
                 metrics_config: Optional[MetricsConfig] = None,
                 trace_config: Optional[TraceConfig] = None,
                 profile_config: Optional[ProfileConfig] = None,
                 slo_config: Optional[SLOConfig] = None,
                 fault_config: Optional[FaultConfig] = None,
                 gen_staleness_s: Optional[float] = None,
                 blackbox_config: Optional[BlackboxConfig] = None,
                 watchdog_config: Optional[WatchdogConfig] = None,
                 resize_pace_s: float = 0.0,
                 resize_grace_s: float = 30.0,
                 history_config: Optional[HistoryConfig] = None,
                 sentinel_config: Optional[SentinelConfig] = None,
                 tenants_config: Optional[TenantsConfig] = None,
                 scrub_config: Optional[ScrubConfig] = None,
                 tier_config: Optional[TierConfig] = None,
                 capture_config: Optional[CaptureConfig] = None,
                 backup_config=None):
        self.data_dir = data_dir
        self.host = host
        self.logger = logger
        self.cluster = cluster or Cluster(
            nodes=[Node(host)], node_set=StaticNodeSet([Node(host)]))
        self.broadcaster = broadcaster or NOP_BROADCASTER
        self.broadcast_receiver = broadcast_receiver
        # Observability (obs subsystem; docs/OBSERVABILITY.md): when
        # metrics are on, every legacy StatsClient call site also
        # feeds the process Prometheus registry (/metrics) through the
        # bridge — one call site, every backend.
        self.metrics_config = metrics_config or MetricsConfig()
        self.trace_config = trace_config or TraceConfig()
        if self.metrics_config.enabled:
            stats = MultiStatsClient(
                [stats, RegistryStatsClient(default_registry())])
        self.stats = stats
        self.tracer = Tracer(enabled=self.trace_config.enabled,
                             max_traces=self.trace_config.max_traces,
                             max_spans=self.trace_config.max_spans)
        # Tail sampling + flight recorder + stall watchdog (obs
        # subsystem, docs/OBSERVABILITY.md): built in open() — the
        # disk rings live under the holder data dir.
        self.blackbox_config = blackbox_config or BlackboxConfig()
        self.watchdog_config = watchdog_config or WatchdogConfig()
        self.sampler: Optional[TailSampler] = None
        self.blackbox: Optional[obs_blackbox.Blackbox] = None
        self.watchdog: Optional[Watchdog] = None
        # Fleet observability (this PR; docs/OBSERVABILITY.md): the
        # on-disk metric history, the cluster federator behind
        # /metrics/cluster + /debug/cluster, and the regression
        # sentinel — built in open() (the history ring lives under
        # the holder data dir).
        self.history_config = history_config or HistoryConfig()
        self.sentinel_config = sentinel_config or SentinelConfig()
        self.history: Optional[MetricHistory] = None
        self.sentinel: Optional[Sentinel] = None
        self.federator: Optional[Federator] = None
        # Peer build identities learned via the gossip push/pull
        # piggyback (build_wire_state): version skew across a
        # mixed-version fleet stays visible through /debug/cluster
        # even for nodes a scrape can't reach right now.
        self.peer_builds: dict[str, dict] = {}
        # Continuous profiler + SLO tracker (obs subsystem). The
        # accounting knob stays PER SERVER (threaded into the handler
        # and the batch lane) — a process-global flip here would let
        # the last-constructed in-process server decide accounting for
        # every other one.
        self.profile_config = profile_config or ProfileConfig()
        self.profiler = ContinuousProfiler(
            hz=self.profile_config.hz, ring=self.profile_config.ring)
        self.slo_config = slo_config or SLOConfig()
        self.slo: Optional[SLOTracker] = None
        if self.metrics_config.enabled:
            self.slo = SLOTracker(
                objective_s=self.slo_config.objective,
                target=self.slo_config.target)
        self.runtime: Optional[RuntimeCollector] = None
        self.anti_entropy_interval = anti_entropy_interval
        self.polling_interval = polling_interval

        # Fault-tolerance subsystem (fault; docs/FAULT_TOLERANCE.md):
        # per-peer health EWMA + circuit breakers shared by the
        # executor's placement, every pooled Client, the anti-entropy
        # syncer, and the gossip liveness callback. Disabled =
        # None everywhere, the pre-fault behavior.
        self.fault_config = fault_config or FaultConfig()
        self.fault: Optional[FaultManager] = None
        if self.fault_config.enabled:
            self.fault = FaultManager(
                breaker_threshold=self.fault_config.breaker_threshold,
                backoff_base_s=self.fault_config.breaker_backoff,
                backoff_cap_s=self.fault_config.breaker_backoff_cap,
                hedge_s=self.fault_config.hedge, node=host)

        # Cluster-wide generation knowledge (cluster.generations;
        # docs/DISTRIBUTED.md): every pooled Client feeds peers'
        # piggybacked X-Pilosa-Generations tokens here, and the
        # executor's result caches key + validate remote slices
        # against it.
        from ..cluster.generations import (DEFAULT_STALENESS_S,
                                           GenerationMap)
        self.gens = GenerationMap(
            staleness_s=(gen_staleness_s if gen_staleness_s is not None
                         else DEFAULT_STALENESS_S))

        # Query lifecycle subsystem (sched; docs/SCHEDULING.md): the
        # weighted admission queue in front of the executor — with the
        # tenant (= index) as a second stride level (sched.tenants:
        # weights, caps, quotas, cost-kill ceilings, penalty box) —
        # the in-flight registry behind /debug/queries, and (from
        # open()) the cold-start warmup lane.
        self.query_config = query_config or QueryConfig()
        self.tenants_config = tenants_config or TenantsConfig()
        self.tenants = TenantRegistry(self.tenants_config.table,
                                      node=host)
        # Cluster-wide kill fan-out: reads self.broadcaster at CALL
        # time (it is swapped after open() for http/gossip modes).
        self.tenants.kill_broadcast = self._broadcast_kill
        self.admission = AdmissionController(
            concurrency=self.query_config.concurrency,
            queue_depth=self.query_config.queue_depth,
            tenants=self.tenants)
        # Per-tenant SLO burn (obs.slo.TenantSLOTracker), recorded on
        # the runtime collector's cadence against the SAME objective
        # as the aggregate tracker.
        self.tenant_slo: Optional[TenantSLOTracker] = None
        if self.metrics_config.enabled:
            self.tenant_slo = TenantSLOTracker(
                objective_s=self.slo_config.objective,
                target=self.slo_config.target)
        self.query_registry = QueryRegistry(
            slow_threshold_s=self.query_config.slow_threshold or None,
            stats=stats, logger=logger)
        self.warmup: Optional[Warmup] = None

        self.holder = Holder(data_dir, on_create_slice=self._on_create_slice,
                             stats=stats, logger=logger)
        # Storage integrity (storage.scrub / server.repair;
        # docs/FAULT_TOLERANCE.md): the background scrubber
        # re-verifying on-disk checksums and the repairer re-streaming
        # quarantined fragments from replicas — built in open().
        self.scrub_config = scrub_config or ScrubConfig()
        self.scrubber: Optional[Scrubber] = None
        self.repairer: Optional[Repairer] = None
        # Tiered storage (pilosa_tpu.tier; docs/STORAGE.md): the
        # working-set manager serving indexes bigger than RAM — built
        # in open() when [tier] enables it (the cold dir lives under
        # the data dir by default).
        self.tier_config = tier_config or TierConfig()
        self.tier: Optional[TierManager] = None
        # Workload capture (obs.capture; docs/OBSERVABILITY.md): the
        # recorded-traffic ring behind /debug/capture* — built in
        # open() (the segment ring lives under the data dir).
        self.capture_config = capture_config or CaptureConfig()
        self.capture = None
        # Disaster recovery (pilosa_tpu.backup;
        # docs/DISASTER_RECOVERY.md): the archive store, this node's
        # continuous WAL-segment archiver, and the in-flight backup
        # coordinator op (None unless THIS node is driving one) —
        # built in open() when [backup] names an archive.
        from ..utils.config import BackupConfig
        self.backup_config = backup_config or BackupConfig()
        self.backup_store = None
        self.wal_archiver = None
        self.backup_op = None
        self._backup_mu = threading.Lock()
        self._last_backup: Optional[dict] = None
        self.executor: Optional[Executor] = None
        self.handler: Optional[Handler] = None
        self.pod = None  # parallel.pod.Pod once open() joins a pod

        # Elastic resize (cluster.resize; docs/CLUSTER_RESIZE.md):
        # this node's in-flight coordinator op (None unless THIS node
        # is driving a resize), the post-finalize write-accept grace,
        # and the last settled resize for gossip catch-up.
        self.resize_pace_s = resize_pace_s
        self.resize_grace_s = resize_grace_s
        self.resize_op = None
        self._resize_mu = threading.Lock()
        self._last_resize: Optional[dict] = None

        self._httpd = None
        self._threads: list[threading.Thread] = []
        self._closing = threading.Event()
        # One pooled Client per target host, shared by the executor
        # fan-out and the max-slice poll loop so keep-alive connections
        # actually get reused (client.py pools per Client instance).
        self._clients: dict[str, Client] = {}
        self._clients_mu = threading.Lock()

    def _broadcast_kill(self, qid: str) -> None:
        """Fan a cost-policy kill cluster-wide (sched.tenants): the
        SAME CancelQueryMessage an operator DELETE rides, so peers
        cancel the legs registered under the killed id."""
        from ..cluster.broadcast import CancelQueryMessage
        self.broadcaster.send_async(CancelQueryMessage(qid))

    def client_for(self, host: str) -> Client:
        """The shared keep-alive Client for a peer host."""
        with self._clients_mu:
            client = self._clients.get(host)
            if client is None:
                client = self._clients[host] = Client(
                    host, fault=self.fault, gens=self.gens)
            return client

    def _client_factory(self, host: str) -> Client:
        """client_factory seam for layers that build their own Client
        (anti-entropy, frame restore): fault-aware like client_for,
        but a fresh instance per call (the syncer closes its own)."""
        return Client(host, fault=self.fault, gens=self.gens)

    # -- lifecycle (server.go:89-180) ----------------------------------------

    def open(self) -> None:
        # GIL fairness for multi-tenant latency isolation: CPython's
        # default 5 ms switch interval lets one tenant's CPU-bound
        # handler thread hold the interpreter for whole milliseconds
        # while a quiet tenant's 2 ms query waits — a direct p99
        # transfer between tenants that admission cannot see. 1 ms
        # keeps cross-thread handoff latency ~interference-sized;
        # PILOSA_TPU_GIL_SWITCH_MS overrides (0 keeps the interpreter
        # default). Process-global by nature, set once at open.
        raw_switch = os.environ.get("PILOSA_TPU_GIL_SWITCH_MS", "1")
        try:
            switch_ms = float(raw_switch)
        except ValueError:
            switch_ms = 1.0
        if switch_ms > 0:
            import sys as sys_mod
            sys_mod.setswitchinterval(switch_ms / 1e3)

        bind_host, sep, port_s = self.host.rpartition(":")
        if not sep:  # bare hostname, no port
            bind_host, port_s = self.host, ""
        bind_host = bind_host or "localhost"
        try:
            port = int(port_s) if port_s else 10101
        except ValueError:
            raise PilosaError(f"invalid host: {self.host!r}"
                              " (expected host:port)")

        # Pod membership (multi-host TPU) joins before any jax use so the
        # executor's mesh spans every chip in the pod; a no-op unless the
        # PILOSA_TPU_DIST_* env contract is set (parallel.multihost).
        from ..parallel import multihost, pod as pod_mod
        multihost.initialize_from_env()

        # Persistent XLA compile cache, defaulted UNDER THE DATA DIR so
        # a restarted server re-reads its own compiled programs from
        # disk instead of re-paying the multi-second trace+compile
        # (VERDICT weak #2: the cache existed but nothing armed it off
        # TPU, so every fresh process compiled from scratch). Armed
        # before any device use; PILOSA_TPU_COMPILE_CACHE still
        # overrides (=0 disables).
        from ..parallel import mesh as mesh_mod
        mesh_mod.arm_compile_cache(
            os.path.join(self.holder.path, ".xla-cache"))

        self.holder.open()
        # Placement-epoch durability (cluster.resize): a node that
        # lived through resizes must not boot back at epoch 0 with
        # the configured (stale) membership — restore the last
        # persisted (epoch, hosts) pair before anything consults
        # placement.
        self._load_epoch()

        # Pod-internal query broadcast (parallel.pod): the coordinator
        # fans device-batched Count/TopN to every pod process as one
        # collective and replicates schema mutations to pod workers.
        self.pod = pod_mod.maybe_pod(self.holder)
        if self.pod is not None and self.pod.is_coordinator:
            self.broadcaster = pod_mod.PodBroadcaster(self.broadcaster,
                                                      self.pod)

        # Failpoints (fault.failpoints): arm the [fault.failpoints] /
        # PILOSA_FAULT_* schedule before any serving path runs, seeding
        # first so the logged seed reproduces the whole schedule.
        if self.fault_config.seed:
            fault_failpoints.seed_default(self.fault_config.seed)
        for site, spec in (self.fault_config.failpoints or {}).items():
            fault_failpoints.arm(site, spec)
            self.logger.printf("failpoint armed: %s = %s (seed %d)",
                               site, spec,
                               fault_failpoints.default().seed)

        client = _RoutingClient(self)
        self.executor = Executor(
            self.holder, host=self.host, cluster=self.cluster,
            client=client, pod=self.pod, fault=self.fault,
            gens=self.gens, gen_staleness_s=self.gens.staleness_s,
            result_cache_entries=self.query_config.result_cache_entries,
            result_cache_bits=self.query_config.result_cache_bits,
            cluster_cache_entries=self.query_config
            .cluster_cache_entries,
            tenants=self.tenants)
        # Cold-start warmup: background-compile the hot XLA programs so
        # the first real device query doesn't pay the multi-second
        # trace+compile (state surfaces at /status; PILOSA_TPU_WARMUP=0
        # or a disabled mesh skips it).
        if warmup_enabled() and self.executor.use_mesh:
            self.warmup = Warmup(self.executor, logger=self.logger)
            self.warmup.start()
        # On-disk metric history (obs.history): one sampling pass per
        # runtime-collector tick into bounded multi-resolution rings
        # persisted under the data dir (crash-safe; survives SIGKILL
        # minus the unflushed tail).
        if self.metrics_config.enabled and self.history_config.enabled:
            self.history = MetricHistory(
                os.path.join(self.holder.path, "history"),
                resolutions=parse_resolutions(
                    self.history_config.resolutions),
                max_series=self.history_config.max_series,
                segment_bytes=self.history_config.segment_bytes,
                max_segments=self.history_config.segments)
        if self.metrics_config.enabled:
            self.runtime = RuntimeCollector(
                holder=self.holder, executor=self.executor,
                admission=self.admission,
                interval_s=self.metrics_config.runtime_interval,
                slo=self.slo, tenant_slo=self.tenant_slo,
                profiler=self.profiler,
                history=self.history)
        # Cluster federation (obs.federate): /metrics/cluster,
        # /debug/cluster, and history scope=cluster fan a bounded
        # parallel scrape over the pooled (breaker-aware) clients.
        self.federator = Federator(
            self.host, cluster=self.cluster,
            client_for=self.client_for,
            peer_timeout_s=self.metrics_config.federate_timeout,
            fanout=self.metrics_config.federate_fanout)
        # Publish build identity now that jax is loaded (the
        # pilosa_build_info gauge + the /status build block).
        build_info()
        # Tail sampling (obs.sampler): always-on span buffers with an
        # end-of-query keep decision; kept traces persist to a segment
        # ring under the data dir that survives restarts.
        if self.trace_config.tail:
            from ..obs.diskring import SegmentRing
            self.sampler = TailSampler(
                disk=SegmentRing(
                    os.path.join(self.holder.path, "traces"),
                    segment_bytes=self.trace_config.disk_segment_bytes,
                    max_segments=self.trace_config.disk_segments),
                head_n=self.trace_config.head_n,
                slow_floor_s=self.trace_config.slow_floor,
                admission=self.admission)
        # Blackbox flight recorder (obs.blackbox): periodic whole-
        # system snapshots into a bounded disk ring; dumped in full on
        # SIGTERM, fatal thread death, watchdog trip, or the API.
        if self.blackbox_config.enabled:
            self.blackbox = obs_blackbox.Blackbox(
                os.path.join(self.holder.path, "blackbox"),
                state_fn=self._blackbox_state,
                interval_s=self.blackbox_config.interval,
                segment_bytes=self.blackbox_config.segment_bytes,
                max_segments=self.blackbox_config.segments,
                max_dumps=self.blackbox_config.dumps,
                node=self.host, logger=self.logger)
            self.blackbox.start()
            obs_blackbox.install_process_hooks()
        # Storage scrubber + repairer (storage.scrub / server.repair):
        # the scrubber re-verifies on-disk checksums on a paced
        # cadence; the repairer drains the quarantine registry by
        # re-streaming from replicas (woken by the registry's
        # on_quarantine hook, wired in its constructor). Started at
        # the end of open() with the other loops.
        if self.scrub_config.enabled:
            self.scrubber = Scrubber(
                self.holder, interval_s=self.scrub_config.interval,
                pace_s=self.scrub_config.pace, logger=self.logger)
        if self.scrub_config.repair:
            self.repairer = Repairer(
                self.holder, self.cluster, self.host,
                client_factory=self._client_factory, fault=self.fault,
                rescan_s=self.scrub_config.repair_rescan,
                logger=self.logger)
        # Tiered storage (pilosa_tpu.tier; docs/STORAGE.md): the
        # working-set manager — demotion/eviction/blob loops over the
        # residency ledger, honoring per-tenant cache shares, with the
        # prefetcher ranking cold fragments by the metric history's
        # touch rates. Started at the end of open() with the other
        # loops.
        if self.tier_config.enabled:
            self.tier = TierManager(
                self.holder,
                resident_budget=self.tier_config.resident_budget,
                high_watermark=self.tier_config.high_watermark,
                low_watermark=self.tier_config.low_watermark,
                idle_s=self.tier_config.idle,
                blob_idle_s=self.tier_config.blob_idle,
                cold_dir=(self.tier_config.cold_dir
                          or os.path.join(self.holder.path, "_tier")),
                blob=self.tier_config.blob,
                interval_s=self.tier_config.interval,
                prefetch_interval_s=self.tier_config
                .prefetch_interval,
                pace_s=self.tier_config.pace,
                tenants=self.tenants, history=self.history,
                busy_fn=lambda: self.admission.in_flight() > 0,
                logger=self.logger)
            self.holder.tier = self.tier
            # Fragments already opened above get their manager hook
            # now (later opens are picked up by the sync pass).
            self.tier.sync()
        # Disaster recovery (pilosa_tpu.backup;
        # docs/DISASTER_RECOVERY.md): the archive store + this node's
        # continuous WAL-segment archiver. The archiver's sink hooks
        # the group-commit WAL before any traffic arrives, so the
        # PITR record starts at boot, not at the first backup.
        if self.backup_config.archive:
            from ..backup import archive as backup_archive
            from ..backup.walarchive import WalArchiver
            self.backup_store = backup_archive.open_archive(
                self.backup_config.archive, self.holder.path)
            self.wal_archiver = WalArchiver(
                self.backup_store, self.holder.path, self.host,
                interval_s=self.backup_config.wal_interval,
                logger=self.logger)
            self.wal_archiver.start()
        # Stall watchdog (obs.watchdog): wedged WAL flusher, stuck
        # legs, gossip silence, non-draining admission queue. A trip
        # force-keeps in-flight traces and dumps the blackbox.
        if self.watchdog_config.enabled:
            self.watchdog = Watchdog(
                registry=self.query_registry, admission=self.admission,
                tracer=self.tracer, sampler=self.sampler,
                blackbox=self.blackbox,
                gossip_age_fn=self._gossip_age,
                resize_progress_fn=self._resize_progress,
                backup_progress_fn=self._backup_progress,
                scrub_progress_fn=(self.scrubber.stall_age
                                   if self.scrubber is not None
                                   else None),
                tier_progress_fn=(self.tier.stall_age
                                  if self.tier is not None
                                  else None),
                interval_s=self.watchdog_config.interval,
                wal_stall_s=self.watchdog_config.wal_stall,
                deadline_grace_s=self.watchdog_config.deadline_grace,
                gossip_silence_s=self.watchdog_config.gossip_silence,
                queue_stall_s=self.watchdog_config.queue_stall,
                resize_stall_s=self.watchdog_config.resize_stall,
                scrub_stall_s=self.watchdog_config.scrub_stall,
                tier_stall_s=self.watchdog_config.tier_stall,
                backup_stall_s=self.watchdog_config.backup_stall,
                retrip_s=self.watchdog_config.retrip,
                logger=self.logger)
            self.watchdog.start()
        # Regression sentinel (obs.sentinel): slow-cadence robust-z +
        # manifest-envelope rules over the live history; a finding
        # force-keeps in-flight traces (reason ``anomaly``) and lands
        # a blackbox snapshot naming the regressed metric.
        if self.sentinel_config.enabled and self.history is not None:
            self.sentinel = Sentinel(
                self.history, registry=self.query_registry,
                tracer=self.tracer, sampler=self.sampler,
                blackbox=self.blackbox,
                interval_s=self.sentinel_config.interval,
                window_s=self.sentinel_config.window,
                baseline_s=self.sentinel_config.baseline,
                zscore=self.sentinel_config.zscore,
                min_points=self.sentinel_config.min_points,
                min_ratio=self.sentinel_config.min_ratio,
                retrip_s=self.sentinel_config.retrip,
                manifest_path=self.sentinel_config.manifest,
                manifest_tolerance=self.sentinel_config
                .manifest_tolerance,
                logger=self.logger)
            self.sentinel.start()
        # Workload capture (obs.capture): the replayable traffic
        # record behind /debug/capture* — mode "off" still builds the
        # store (a live SIGHUP/env flip can arm it later via config
        # reload patterns) but the handler's enabled check makes the
        # per-request cost one attribute read.
        from ..obs.capture import CaptureStore
        self.capture = CaptureStore(
            os.path.join(self.holder.path, "capture"),
            mode=self.capture_config.mode,
            sample_n=self.capture_config.sample_n,
            segment_bytes=self.capture_config.segment_bytes,
            max_segments=self.capture_config.segments,
            redact_tenants={t.strip() for t in
                            self.capture_config.redact.split(",")
                            if t.strip()},
            node=self.host)
        self.handler = Handler(
            self.holder, self.executor, cluster=self.cluster,
            host=self.host, broadcaster=self.broadcaster,
            broadcast_handler=self, status_handler=self,
            stats=self.stats, client_factory=self._client_factory,
            pod=self.pod,
            logger=self.logger, admission=self.admission,
            registry=self.query_registry, warmup=self.warmup,
            default_timeout_s=self.query_config.default_timeout,
            tracer=self.tracer, runtime=self.runtime,
            profiler=self.profiler,
            accounting=self.metrics_config.accounting,
            fault=self.fault, sampler=self.sampler,
            blackbox=self.blackbox, watchdog=self.watchdog,
            history=self.history, sentinel=self.sentinel,
            federator=self.federator, tenants=self.tenants,
            tenant_slo=self.tenant_slo, scrubber=self.scrubber,
            repairer=self.repairer, tier=self.tier,
            capture=self.capture)

        self._httpd = HTTPServer(self.handler, bind_host, port,
                                 logger=self.logger,
                                 query_batcher=self._query_batcher)
        # Re-resolve the port for ":0" binds (server.go:98-106).
        actual_port = self._httpd.server_address[1]
        if actual_port != port:
            new_host = f"{bind_host}:{actual_port}"
            for n in self.cluster.nodes:
                if n.host == self.host:
                    n.host = new_host
            # The membership backend's identity is the HTTP host; keep it
            # in step so gossip members map back to reachable hosts.
            ns = self.cluster.node_set
            if ns is not None and getattr(ns, "host", None) == self.host:
                ns.host = new_host
            self.host = new_host
            self.executor.host = new_host
            self.handler.host = new_host
            if self.repairer is not None:
                # The repairer's self-identity gates its local-target
                # adapter and peer selection.
                self.repairer.host = new_host
            if self.federator is not None:
                self.federator.host = new_host
            if self.capture is not None:
                # Capture records name the serving node; merged
                # multi-node exports disambiguate on it.
                self.capture.node = new_host
            if self.wal_archiver is not None:
                # WAL segments are keyed by the serving identity;
                # the seq counter is lazy, so no segment has been
                # written under the provisional name yet.
                self.wal_archiver.node = new_host
            if self.fault is not None:
                # The self-identity every fault consult skips.
                self.fault.node = new_host
                self.fault.health.node = new_host
                self.fault.breakers.node = new_host

        # Receiver first, then membership open — the gossip join's
        # push/pull needs the status handler attached (server.go:118,123).
        if self.broadcast_receiver is not None:
            self.broadcast_receiver.start(self)
        if self.cluster.node_set is not None:
            ns = self.cluster.node_set
            if self.fault is not None and hasattr(ns,
                                                  "on_state_change"):
                # Gossip liveness feeds the fault layer: a dead rumor
                # opens the peer's breaker before any query pays a
                # timeout; an alive refutation re-arms the probe.
                ns.on_state_change = self._on_peer_state
            ns.open()

        self.logger.printf("listening as http://%s", self.host)
        # Resize journal recovery: an in-flight resize whose
        # coordinator (us) crashed either aborts back to the old
        # epoch (pre-flip) or rolls forward (post-flip). Runs on a
        # background thread with the cluster up — peers must be
        # reachable for the control sends, and boot must not block
        # on them.
        _rj = resize_mod.ResizeJournal.for_data_dir(self.holder.path)
        if _rj.load() and _rj.in_flight():
            self._spawn(self._recover_resize, "resize-recover")
        # Backup journal recovery: an in-flight backup whose
        # coordinator (us) was killed resumes under the same id —
        # journaled fragments and pool-resident objects are skipped,
        # so recovery converges instead of re-shipping.
        if self.backup_store is not None:
            from ..backup import coordinator as backup_coord
            _bj = backup_coord.BackupJournal.for_data_dir(
                self.holder.path)
            if _bj.load() and _bj.in_flight():
                self._spawn(self._recover_backup, "backup-recover")
        if self.runtime is not None:
            self.runtime.start()
        if self.profile_config.continuous:
            self.profiler.start()
        self._spawn(self._serve, "http")
        self._spawn(self._monitor_cache_flush, "cache-flush")
        if self.polling_interval > 0:
            self._spawn(self._monitor_max_slices, "max-slices")
        if self.anti_entropy_interval > 0:
            self._spawn(self._monitor_anti_entropy, "anti-entropy")
        if self.fault is not None:
            self._spawn(self._monitor_breaker_probes, "fault-probe")
        if self.scrubber is not None:
            self.scrubber.start()
        if self.repairer is not None:
            self.repairer.start()
        if self.tier is not None:
            self.tier.start()
        # Device-queue fairness below admission: dispatch slots stride
        # over the same penalty-boxed tenant weights admission uses
        # (parallel.mesh.FairDispatchQueue; PILOSA_MESH_FAIR=0 vetoes).
        mesh_mod.install_fair_dispatch(self.tenants.effective_weight)

    def close(self) -> None:
        self.logger.printf("server closing: %s", self.host)
        self._closing.set()
        if self.resize_op is not None:
            # Cooperative stop; an in-flight journal is recovered (or
            # aborted) on the next open.
            self.resize_op.cancel()
        if self.backup_op is not None:
            # Cooperative stop; the journal stays in flight so the
            # next open resumes the backup under the same id.
            self.backup_op.cancel()
        if self.wal_archiver is not None:
            # Before the holder closes: the final flush ships every
            # buffered batch, so an orderly shutdown loses no PITR
            # coverage.
            self.wal_archiver.close()
        if self.sentinel is not None:
            self.sentinel.stop()
        # Scrub/repair before the holder closes: a mid-pass verify or
        # re-stream must not race fragment close (both threads join).
        if self.repairer is not None:
            self.repairer.stop()
        if self.scrubber is not None:
            self.scrubber.stop()
        # Tier manager before the holder closes: a mid-pass demotion
        # must not race fragment close (stop() joins the loops).
        if self.tier is not None:
            self.tier.stop()
        from ..parallel import mesh as mesh_mod
        mesh_mod.uninstall_fair_dispatch()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.blackbox is not None:
            self.blackbox.stop()
        if self.sampler is not None and self.sampler.disk is not None:
            self.sampler.disk.close()
        if self.capture is not None:
            self.capture.close()
        # Collector before history: a mid-tick sample() racing the
        # close would reopen a fresh disk segment after it (stop()
        # joins the collector thread).
        if self.runtime is not None:
            self.runtime.stop()
        if self.history is not None:
            self.history.close()
        self.profiler.stop()
        if self.warmup is not None:
            self.warmup.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self.cluster.node_set is not None:
            self.cluster.node_set.close()
        if self.executor is not None:
            self.executor.close()
        with self._clients_mu:
            for client in self._clients.values():
                client.close()
            self._clients.clear()
        self.holder.close()

    def _spawn(self, fn, name: str) -> None:
        t = threading.Thread(target=fn, name=f"pilosa-{name}", daemon=True)
        t.start()
        self._threads.append(t)

    def _serve(self) -> None:
        self._httpd.serve_forever()

    def _query_batcher(self, index: str, bodies: list[str]):
        """Combine pipelined plain-PQL query bodies into one executor
        call (the httpd batch lane); None falls back to per-request
        dispatch. Partial-failure semantics are IDENTICAL to sequential
        dispatch: execute_partial reports how far the combined call
        stream got — requests fully covered get their results, the
        request holding the failing call gets the error response, and
        requests after it re-execute individually (none of their calls
        ran). Never re-executes an applied mutation (a re-run SetBit
        would report changed=false to the client that set the bit).

        Lifecycle: the combined run occupies ONE admission slot (its
        lane classified over all calls) and registers ONE QueryContext;
        if admission is full the lane declines (None) and the requests
        fall back to per-request dispatch through the handler, which
        produces the proper per-request 429 + Retry-After."""
        from ..errors import PilosaError
        from ..executor import _WRITE_CALLS, ExecOptions
        from ..pql import parser as pql
        from ..pql.ast import Query
        from ..sched import (LANE_READ, LANE_WRITE, AdmissionFullError,
                             QueryContext)
        from . import codec
        if self.executor is None:
            return None
        try:
            queries = [pql.parse(b) for b in bodies]
        except PilosaError:
            return None
        calls = [c for q in queries for c in q.calls]
        if not calls or all(c.name == "SetRowAttrs" for c in calls):
            return None  # bulk-attrs path applies non-positionally
        lane = (LANE_WRITE if any(c.name in _WRITE_CALLS for c in calls)
                else LANE_READ)
        if lane == LANE_WRITE:
            from ..fault import diskfull as fault_diskfull
            if not fault_diskfull.write_ready():
                # Write-unready after ENOSPC: decline the batch so
                # per-request dispatch answers the proper 507s.
                return None
        try:
            # The batch's tenant is resolved BEFORE the slot is taken
            # (all requests in a batchable run share one index, which
            # IS the principal) — the combined run schedules and
            # charges under it like any single query would.
            slot = self.admission.acquire(lane, tenant=index)
        except AdmissionFullError:
            return None  # per-request dispatch answers the 429s/507s
        ctx = QueryContext(pql=f"<pipelined batch: {len(calls)} calls>",
                           index=index, lane=lane,
                           timeout_s=self.query_config.default_timeout
                           or None, node=self.host, tenant=index)
        if self.metrics_config.accounting:
            obs_accounting.attach(ctx, node=self.host)
        self.tenants.install(ctx)
        err = None  # stays None if execute_partial itself raises —
        # the finally below must never NameError over the real failure
        try:
            with self.query_registry.track(ctx):
                results, err = self.executor.execute_partial(
                    index, Query(calls), opt=ExecOptions(ctx=ctx))
            if lane == LANE_WRITE:
                # Commit barrier before the batch's acks go out — ONE
                # leader flush covers every mutation the whole
                # pipelined group applied (storage.wal group commit).
                from ..storage import wal as storage_wal
                storage_wal.barrier_all()
        finally:
            slot.release()
            # The batch lane bypasses the handler's query path, so it
            # records its own latency sample (obs.metrics).
            import sys as sys_mod

            from ..obs import metrics as obs_metrics
            failed = err is not None or sys_mod.exc_info()[1] is not None
            labels = ("batch", lane, "500" if failed else "200")
            obs_metrics.QUERY_SECONDS.labels(*labels).observe(
                ctx.elapsed())
            obs_metrics.QUERIES_TOTAL.labels(*labels).inc()

        def ok_payload(rs):
            # Write-heavy pipelined streams answer [true]/[false] for
            # almost every request; skip the per-request JSON encode.
            if len(rs) == 1 and rs[0] is True:
                return b'{"results": [true]}\n'
            if len(rs) == 1 and rs[0] is False:
                return b'{"results": [false]}\n'
            payload = codec.query_response_json(rs, [])
            return (json.dumps(payload) + "\n").encode()

        if err is None:
            out = []
            pos = 0
            for q in queries:
                n = len(q.calls)
                out.append(ok_payload(results[pos:pos + n]))
                pos += n
            return out
        out = []
        pos = 0
        failed = False
        for q in queries:
            n = len(q.calls)
            if not failed and len(results) >= pos + n:
                out.append(ok_payload(results[pos:pos + n]))
            elif not failed:
                # This request holds the failing call: the same error
                # response sequential dispatch would produce.
                from ..errors import (QueryCancelledError,
                                      QueryDeadlineError)
                if isinstance(err, QueryDeadlineError):
                    status = 504
                elif isinstance(err, QueryCancelledError):
                    status = 409
                elif isinstance(err, PilosaError):
                    status = 400
                else:
                    status = 500
                body = (json.dumps({"error": str(err)}) + "\n").encode()
                out.append(self._error_payload(body, status))
                failed = True
            else:
                # After the error: none of these calls ran — execute
                # the request normally (per-request error semantics).
                out.append(self._single_query_payload(index, q))
            pos += n
        return out

    def _single_query_payload(self, index: str, q) -> bytes:
        from ..errors import PilosaError
        from . import codec
        try:
            rs = self.executor.execute(index, q)
        except PilosaError as e:
            return self._error_payload(
                (json.dumps({"error": str(e)}) + "\n").encode(), 400)
        except Exception as e:  # noqa: BLE001 - surfaced as 500
            return self._error_payload(
                (json.dumps({"error": str(e)}) + "\n").encode(), 500)
        payload = codec.query_response_json(rs, [])
        return (json.dumps(payload) + "\n").encode()

    @staticmethod
    def _error_payload(body: bytes, status: int) -> bytes:
        """A non-200 batch-lane entry: the httpd renders 200 for plain
        bytes, so error entries carry their own status marker."""
        return (status, body)

    def _on_peer_state(self, host: str, state: str) -> None:
        """Gossip membership callback → fault layer (cluster.gossip
        states map 1:1 onto health's liveness vocabulary)."""
        if self.fault is not None:
            self.fault.note_gossip(host, state)

    # -- elastic resize (cluster.resize; docs/CLUSTER_RESIZE.md) -------------

    def start_resize(self, target_hosts: list[str]):
        """Begin an online resize to ``target_hosts`` with THIS node
        as coordinator; returns the ResizeCoordinator (already running
        on a background thread). One at a time — cluster-wide, the
        prepare install enforces it; locally, this guard does."""
        with self._resize_mu:
            op = self.resize_op
            # A just-constructed coordinator sits in IDLE until its
            # thread reaches the first phase — IDLE with no finish
            # time IS in flight, or two rapid POSTs would both pass
            # the guard and share one journal (review finding).
            if op is not None and not (
                    op.phase in (resize_mod.PHASE_DONE,
                                 resize_mod.PHASE_ABORTED)
                    or op.finished_at):
                raise PilosaError(
                    f"resize {op.id} already in flight"
                    f" (phase {op.phase})")
            if self.cluster.resize is not None:
                raise PilosaError(
                    f"resize {self.cluster.resize.id} already"
                    f" installed cluster-wide")
            # Journal recovery may still be rolling a prior resize
            # forward on its background thread (it registers itself
            # as resize_op only once it runs) — an in-flight journal
            # refuses new resizes outright so two coordinators can
            # never interleave writes to it. The one settle-able
            # state: an ABORT whose broadcast never reached a (since
            # dead) peer — re-send it now; if every node acks, the
            # old resize is settled and the new one may start.
            _rj = resize_mod.ResizeJournal.for_data_dir(
                self.holder.path)
            if _rj.load() and _rj.in_flight():
                if _rj.state.get("phase") == resize_mod.PHASE_ABORTED:
                    stale = resize_mod.ResizeCoordinator(
                        self, _rj.state.get("new") or [],
                        resize_id=str(_rj.state.get("id")),
                        journal=_rj, logger=self.logger)
                    stale.old_hosts = _rj.state.get("old") or []
                    stale.abort(reason="settling unacked abort before"
                                       " a new resize")
                    _rj.load()
                if _rj.in_flight():
                    raise PilosaError(
                        f"resize {_rj.state.get('id')} still settling"
                        f" (journal phase {_rj.state.get('phase')})")
            coord = resize_mod.ResizeCoordinator(
                self, target_hosts, pace_s=self.resize_pace_s,
                grace_s=self.resize_grace_s, logger=self.logger)
            self.resize_op = coord
        self._spawn(coord.run, f"resize-{coord.id}")
        return coord

    def abort_resize(self) -> Optional[dict]:
        """Operator abort: back the in-flight resize out to the old
        epoch. Works from the coordinator (aborts its op) or any node
        that merely has the state installed (broadcasts abort on the
        coordinator's behalf)."""
        op = self.resize_op
        if op is not None and op.phase not in (
                resize_mod.PHASE_DONE, resize_mod.PHASE_ABORTED):
            op.abort(reason="operator abort")
            return op.status()
        rs = self.cluster.resize
        if rs is None:
            return None
        coord = resize_mod.ResizeCoordinator(
            self, rs.new_hosts, resize_id=rs.id, logger=self.logger)
        coord.old_hosts = list(rs.old_hosts)
        # Seed the journal with the full membership BEFORE the abort
        # lands in it: if the abort broadcast can't reach every node
        # and this node restarts, recovery must be able to re-send it
        # to the right hosts (an id-less abort record re-sends to
        # nobody yet marks itself acked — review finding).
        coord.journal.write(id=rs.id, epochFrom=rs.epoch_from,
                            old=list(rs.old_hosts),
                            new=list(rs.new_hosts),
                            coordinator=self.host)
        coord.abort(reason="operator abort (non-coordinator)")
        return coord.status()

    def _recover_resize(self) -> None:
        try:
            status = resize_mod.recover(self, logger=self.logger)
            if status is not None:
                self.logger.printf("resize recovery finished: %s",
                                   status.get("phase"))
        except Exception as e:  # noqa: BLE001 - recovery best-effort
            self.logger.printf("resize recovery failed: %s", e)

    def _resize_progress(self):
        """Watchdog hook (obs.watchdog cause ``resize_stall``):
        (phase, seconds-without-progress) while this node coordinates
        an active resize, else None."""
        op = self.resize_op
        if op is None:
            return None
        if op.phase in (resize_mod.PHASE_IDLE, resize_mod.PHASE_DONE,
                        resize_mod.PHASE_ABORTED):
            return None
        import time as time_mod
        return op.phase, time_mod.monotonic() - op.last_progress

    # -- cluster backup (pilosa_tpu.backup; docs/DISASTER_RECOVERY.md) --------

    def start_backup(self, kind: str = "full"):
        """Begin a cluster backup into the configured archive with
        THIS node as coordinator; returns the BackupCoordinator
        (already running on a background thread). One at a time per
        node — the journal is single-writer."""
        from ..backup import coordinator as backup_coord
        if self.backup_store is None:
            raise PilosaError("no backup archive configured"
                              " ([backup] archive)")
        with self._backup_mu:
            op = self.backup_op
            if op is not None and not (
                    op.phase in (backup_coord.PHASE_DONE,
                                 backup_coord.PHASE_FAILED)
                    or op.finished_at):
                raise PilosaError(
                    f"backup {op.id} already in flight"
                    f" (phase {op.phase})")
            # An in-flight journal belongs to a backup still being
            # recovered (recovery registers itself as backup_op only
            # once it runs) — refuse rather than interleave two
            # coordinators into one journal.
            _bj = backup_coord.BackupJournal.for_data_dir(
                self.holder.path)
            if _bj.load() and _bj.in_flight() and (
                    op is None or _bj.state.get("id") != op.id):
                raise PilosaError(
                    f"backup {_bj.state.get('id')} still recovering"
                    f" (journal phase {_bj.state.get('phase')})")
            coord = backup_coord.BackupCoordinator(
                self, self.backup_store, kind=kind,
                logger=self.logger)
            self.backup_op = coord
        self._spawn(coord.run, f"backup-{coord.id}")
        return coord

    def abort_backup(self) -> Optional[dict]:
        """Operator abort: cooperatively stop the in-flight backup
        this node coordinates. The journal stays in flight, so the
        next open (or a later POST) resumes it instead of discarding
        the objects already pushed."""
        from ..backup import coordinator as backup_coord
        op = self.backup_op
        if op is None or op.phase in (backup_coord.PHASE_DONE,
                                      backup_coord.PHASE_FAILED) \
                or op.finished_at:
            return None
        op.cancel()
        return op.status()

    def _recover_backup(self) -> None:
        try:
            from ..backup import coordinator as backup_coord
            status = backup_coord.recover(self, logger=self.logger)
            if status is not None:
                self.logger.printf("backup recovery finished: %s",
                                   status.get("phase"))
        except Exception as e:  # noqa: BLE001 - recovery best-effort
            self.logger.printf("backup recovery failed: %s", e)

    def _backup_progress(self):
        """Watchdog hook (obs.watchdog cause ``backup_stall``):
        seconds-without-progress while this node coordinates an active
        backup, else None."""
        from ..backup import coordinator as backup_coord
        op = self.backup_op
        if op is None or op.phase in (backup_coord.PHASE_IDLE,
                                      backup_coord.PHASE_DONE,
                                      backup_coord.PHASE_FAILED):
            return None
        import time as time_mod
        return time_mod.monotonic() - op.last_progress

    def _epoch_path(self) -> str:
        return os.path.join(self.holder.path, "epoch.json")

    def _save_epoch(self) -> None:
        """Persist (epoch, membership) on every epoch transition —
        without it a restarted node resets to epoch 0 with its
        boot-config membership, silently mis-placing every slice and
        (post-fix) refusing every future resize's prepare (review
        finding)."""
        try:
            tmp = self._epoch_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"epoch": self.cluster.epoch,
                           "hosts": [n.host
                                     for n in self.cluster.nodes]}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._epoch_path())
        except OSError as e:
            self.logger.printf("epoch persist failed: %s", e)

    def _load_epoch(self) -> None:
        try:
            with open(self._epoch_path()) as f:
                d = json.load(f)
            epoch = int(d.get("epoch", 0))
            hosts = [str(h) for h in (d.get("hosts") or [])]
        except (OSError, ValueError, TypeError):
            return
        if epoch <= self.cluster.epoch or not hosts:
            return
        if self.host.endswith(":0"):
            # A ":0" bind re-resolves its port after this point, so
            # the persisted membership names a port this node no
            # longer answers on and cannot be stitched back — skip
            # adoption (ephemeral-port servers are test harness
            # territory; production binds are stable).
            return
        self.cluster.nodes = [Node(h) for h in hosts]
        self.cluster.epoch = epoch
        self.logger.printf(
            "restored placement epoch %d (%d members) from %s",
            epoch, len(hosts), self._epoch_path())

    def _moved_fn(self, moving: dict):
        """``moved(index, slice) -> bool`` over a captured moving-
        partition map — the executor's eager cache flush on a flip."""
        parts = frozenset(moving)
        partition = self.cluster.partition

        def moved(index: str, slice: int) -> bool:
            return partition(index, slice) in parts
        return moved

    def _apply_resize_message(self, m: ResizeMessage) -> None:
        """One node's side of the resize protocol. Every phase is
        idempotent — the coordinator retries control sends, and the
        gossip catch-up path replays them — and a node that missed
        earlier phases reconstructs them from the message itself (it
        carries the full old/new membership)."""
        cl = self.cluster
        ex = self.executor

        def _install() -> bool:
            if cl.resize is not None:
                if cl.resize.id != m.id:
                    raise PilosaError(
                        f"resize {cl.resize.id} already in flight;"
                        f" refusing {m.id}")
                return True
            last = self._last_resize
            if last is not None and last.get("id") == m.id:
                # This id already settled here (aborted or done): a
                # straggling control send racing the abort broadcast
                # — or a gossip replay — must never re-install it.
                return False
            if cl.epoch > m.epoch:
                # AHEAD of the message: a resize minted against a
                # past epoch (e.g. a coordinator that restarted
                # before converging). A silent 200 would fake the
                # all-ack while this node never installs — refuse
                # loudly; legitimate replays of SETTLED resizes were
                # already absorbed by the _last_resize guard above.
                raise PilosaError(
                    f"resize {m.id} minted at epoch {m.epoch} but"
                    f" this node is at {cl.epoch}")
            if cl.epoch < m.epoch:
                # This node is BEHIND (restarted at epoch 0 / missed
                # flips). A silent 200 here would count as the all-ack
                # the union-write guarantee rests on while the node
                # keeps routing writes old-placement-only — the
                # coordinator must see a FAILURE and retry until the
                # gossip catch-up brings the node forward (or abort).
                raise PilosaError(
                    f"node at placement epoch {cl.epoch}, resize"
                    f" {m.id} expects {m.epoch} — catching up")
            cl.install_resize(m.id, m.new_hosts)
            resize_mod.set_state_gauge("migrating")
            if ex is not None:
                ex.on_resize_change()
            self.logger.printf(
                "resize %s: installed (epoch %d, %s -> %s)", m.id,
                m.epoch, m.old_hosts, m.new_hosts)
            return True

        if m.phase == "prepare":
            _install()
            return
        if m.phase == "flip":
            if cl.epoch == m.epoch + 1:
                return  # already flipped (retry / catch-up replay)
            if not _install():
                return
            rs = cl.resize
            if rs is None or rs.id != m.id:
                return  # aborted concurrently on another thread
            moving = dict(rs.moving)
            try:
                flipped = cl.flip_epoch(m.id)
            except ValueError:
                return  # abort raced the flip: settled-id guard holds
            if flipped:
                self._save_epoch()
                resize_mod.set_state_gauge(resize_mod.PHASE_DRAINING)
                if ex is not None:
                    ex.on_resize_change(self._moved_fn(moving))
                self.logger.printf(
                    "resize %s: FLIPPED to epoch %d (%d moving"
                    " partitions)", m.id, cl.epoch, len(moving))
            return
        if m.phase == "finalize":
            if cl.resize is None and cl.epoch == m.epoch:
                # Missed prepare AND flip (restart/partition): replay
                # both from this message, then finalize below.
                if not _install():
                    return
            rs = cl.resize
            if rs is not None and rs.id == m.id:
                moving = dict(rs.moving)
                from ..cluster.topology import RESIZE_DRAINING
                try:
                    if rs.phase != RESIZE_DRAINING:
                        cl.flip_epoch(m.id)
                        if ex is not None:
                            ex.on_resize_change(self._moved_fn(moving))
                    cl.finalize_resize(m.id,
                                       grace_s=self.resize_grace_s)
                except ValueError:
                    return  # abort raced this application
                self._save_epoch()
                resize_mod.set_state_gauge(resize_mod.PHASE_IDLE)
                if ex is not None:
                    ex.on_resize_change()
                self._last_resize = {
                    "id": m.id, "outcome": "done",
                    "epochFrom": m.epoch, "old": m.old_hosts,
                    "new": m.new_hosts}
                self.logger.printf("resize %s: finalized (epoch %d)",
                                   m.id, cl.epoch)
            return
        if m.phase == "abort":
            # A live coordinator op for this id (an abort initiated
            # through ANOTHER node) must stop driving the protocol —
            # its later phases would otherwise be silently absorbed
            # by every node's settled-id guard and the journal would
            # record 'done' for a resize the cluster aborted.
            op = self.resize_op
            if op is not None and op.id == m.id:
                op.cancel()
            rs = cl.resize
            moving = dict(rs.moving) if rs is not None else {}
            aborted = cl.abort_resize(m.id)
            # Record the settled outcome even when nothing was
            # installed here (a node that missed prepare): a
            # straggling prepare/flip for this id must never install
            # it afterwards.
            self._last_resize = {
                "id": m.id, "outcome": "aborted",
                "epochFrom": m.epoch, "old": m.old_hosts,
                "new": m.new_hosts}
            if aborted:
                self._save_epoch()  # covers a post-flip revert
                resize_mod.set_state_gauge(resize_mod.PHASE_IDLE)
                if ex is not None:
                    ex.on_resize_change(self._moved_fn(moving))
                self.logger.printf("resize %s: aborted (epoch stays"
                                   " %d)", m.id, cl.epoch)
            return
        raise PilosaError(f"unknown resize phase: {m.phase!r}")

    # -- gossip piggyback: epoch/resize convergence --------------------------

    def resize_wire_state(self) -> dict:
        """Rides the gossip push/pull full-state exchange so a node
        that missed resize control sends (partitioned, restarted)
        converges on the cluster's placement epoch within one
        anti-entropy period."""
        out: dict = {"epoch": self.cluster.epoch}
        rs = self.cluster.resize
        if rs is not None:
            out["resize"] = rs.to_wire()
        if self._last_resize is not None:
            out["last"] = dict(self._last_resize)
        return out

    def apply_resize_wire_state(self, d: dict) -> None:
        """Converge toward a peer's epoch/resize knowledge. Only ever
        moves FORWARD (install → flip → finalize, or abort of the
        exact in-flight id) — a peer that is itself behind can never
        drag us back."""
        try:
            peer_epoch = int(d.get("epoch", 0))
        except (TypeError, ValueError):
            return
        cl = self.cluster
        rz = d.get("resize")
        last = d.get("last")

        def msg(phase: str, src: dict) -> ResizeMessage:
            return ResizeMessage(
                id=str(src.get("id", "")), phase=phase,
                epoch=int(src.get("epochFrom", peer_epoch - 1)),
                old_hosts=src.get("old") or [],
                new_hosts=src.get("new") or [])
        try:
            if (cl.resize is not None and rz is None and last
                    and last.get("id") == cl.resize.id):
                # The resize WE still carry has settled at the peer.
                if last.get("outcome") == "aborted":
                    self._apply_resize_message(msg("abort", last))
                elif last.get("outcome") == "done":
                    self._apply_resize_message(msg("finalize", last))
                return
            if rz is not None:
                if (rz.get("phase") == "draining"
                        and peer_epoch == cl.epoch + 1):
                    self._apply_resize_message(msg("flip", rz))
                elif (peer_epoch == cl.epoch and cl.resize is None):
                    self._apply_resize_message(msg("prepare", rz))
                return
            if peer_epoch > cl.epoch and last and int(
                    last.get("epochFrom", -1)) == cl.epoch:
                # Peer finalized a resize we never heard of at all.
                self._apply_resize_message(msg("finalize", last))
        except Exception as e:  # noqa: BLE001 - convergence best-effort
            self.logger.printf("resize gossip catch-up skipped: %s", e)

    # -- fleet observability (obs.federate; docs/OBSERVABILITY.md) -----------

    def local_debug_state(self) -> dict:
        """This node's block of the ``/debug/cluster`` rollup: the
        blackbox state, fleet-queryable — build identity, placement
        epoch, breaker states, SLO burn, WAL flusher health, resize
        phase, admission shape. Deliberately lighter than
        ``_blackbox_state`` (no thread dump, no generation map, no
        slow-log bodies): a fleet-wide fan-out must stay cheap on
        every leg."""
        from ..storage import wal as storage_wal
        out: dict = {"host": self.host,
                     "build": build_info(),
                     "epoch": self.cluster.epoch,
                     "admission": self.admission.snapshot(),
                     "wal": storage_wal.flusher_health(),
                     "quarantined": len(self.holder.quarantine)}
        if self.fault is not None:
            out["fault"] = self.fault.snapshot()
        if self.runtime is not None:
            rt = self.runtime.snapshot()
            if rt.get("slo") is not None:
                out["slo"] = rt["slo"]
            if rt.get("holder") is not None:
                out["holder"] = rt["holder"]
            if rt.get("deviceBlockCache"):
                out["deviceBlockCache"] = rt["deviceBlockCache"]
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.snapshot()
        if self.sentinel is not None:
            out["sentinel"] = self.sentinel.snapshot()
        if self.history is not None:
            out["history"] = self.history.stats()
        rs = self.cluster.resize
        out["resize"] = {"phase": (rs.phase if rs is not None
                                   else "idle"),
                         "inFlight": rs.to_wire()
                         if rs is not None else None}
        if self.resize_op is not None:
            out["resize"]["op"] = self.resize_op.status()
        if self.peer_builds:
            out["gossipBuilds"] = dict(self.peer_builds)
        return out

    # -- gossip piggyback: build identity (version-skew visibility) ----------

    def build_wire_state(self) -> dict:
        """Rides the gossip push/pull next to the resize state: each
        node's build identity, so a mixed-version fleet's skew is
        visible from ANY member during a rolling restart — even for
        peers an HTTP scrape can't currently reach."""
        return {"host": self.host, **build_info()}

    def apply_build_wire_state(self, d: dict) -> None:
        try:
            host = str(d.get("host", ""))
        except (TypeError, ValueError):
            return
        if not host or host == self.host:
            return
        self.peer_builds[host] = {
            k: str(d.get(k, "")) for k in ("version", "python", "jax",
                                           "backend")}

    # -- blackbox / watchdog wiring (obs subsystem) --------------------------

    def _gossip_age(self) -> Optional[float]:
        """Seconds of membership silence for the watchdog, or None
        when not observable (static membership, single node)."""
        ns = self.cluster.node_set if self.cluster is not None else None
        if ns is None or not hasattr(ns, "last_activity_age"):
            return None
        return ns.last_activity_age()

    def _blackbox_state(self) -> dict:
        """One whole-system snapshot for the flight recorder: the
        states an incident retro always wants and can never get after
        the fact — queues, breakers, generation knowledge, the WAL
        dirty set + flusher heartbeat, cache/runtime counters, recent
        slow queries, and a thread dump."""
        from ..storage import wal as storage_wal
        from ..utils.profiling import thread_dump
        out: dict = {"host": self.host,
                     "admission": self.admission.snapshot(),
                     "wal": storage_wal.flusher_health()}
        if self.fault is not None:
            out["fault"] = self.fault.snapshot()
        out["generations"] = self.gens.snapshot()
        reg = self.query_registry
        out["queries"] = {"active": reg.active()[:32],
                          "slow": reg.slow_queries()[-8:]}
        if self.runtime is not None:
            # The collector's last background sample (holder shape,
            # residency, compile-cache, SLO burn) — cheap to reuse.
            out["runtime"] = self.runtime.snapshot()
        if self.executor is not None:
            out["executor"] = {
                "deviceFallbacks": getattr(self.executor,
                                           "device_fallbacks", 0),
                "costModelVetoes": getattr(self.executor,
                                           "cost_vetoes", 0)}
            planner = getattr(self.executor, "planner", None)
            if planner is not None:
                # Decision totals + subresult-cache occupancy: "was
                # the planner rewriting when it went wrong" is a
                # first-hour retro question.
                out["planner"] = planner.snapshot()
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.snapshot()
        # Elastic resize state: phase, movement progress, epoch — the
        # one thing a mid-migration incident retro always asks first.
        rs = self.cluster.resize
        resize_block: dict = {"epoch": self.cluster.epoch,
                              "inFlight": rs.to_wire()
                              if rs is not None else None}
        if self.resize_op is not None:
            resize_block["op"] = self.resize_op.status()
        out["resize"] = resize_block
        # Storage integrity: quarantined fragments + scrub/repair
        # progress — the retro question after any wrong-answer scare.
        integrity_block: dict = {
            "quarantined": self.holder.quarantine.entries()[:32]}
        if self.scrubber is not None:
            integrity_block["scrub"] = self.scrubber.state()
        if self.repairer is not None:
            integrity_block["repair"] = self.repairer.state()
        out["integrity"] = integrity_block
        # Tiered storage: residency counts, watermarks, blocked cold
        # fetches — where did the working set live when it happened.
        if self.tier is not None:
            out["tier"] = self.tier.state()
        # Disaster recovery: the in-flight backup op + this node's
        # WAL-archiver lag — "was a backup running, and how much PITR
        # coverage was buffered" is the post-crash retro question.
        if self.backup_store is not None:
            backup_block: dict = {"configured": True}
            if self.backup_op is not None:
                backup_block["op"] = self.backup_op.status()
            if self.wal_archiver is not None:
                backup_block["walArchiver"] = self.wal_archiver.state()
            out["backup"] = backup_block
        try:
            out["threads"] = thread_dump()[:20000]
        except Exception:  # noqa: BLE001 - interpreter-internal API
            pass
        return out

    # -- slice announcements (view.go:236-246) -------------------------------

    def _on_create_slice(self, index: str, slice: int,
                         inverse: bool) -> None:
        try:
            self.broadcaster.send_async(pb.CreateSliceMessage(
                Index=index, Slice=slice, IsInverse=inverse))
        except Exception:  # noqa: BLE001 - announcements are best-effort
            pass

    # -- background loops ----------------------------------------------------

    def _loop(self, interval: float, fn, name: str = "loop") -> None:
        while not self._closing.wait(interval):
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - loops must survive errors
                self.logger.printf("%s error: %s", name, e)

    def _monitor_cache_flush(self) -> None:
        self._loop(CACHE_FLUSH_INTERVAL, self.holder.flush_caches,
                   "holder cache flush")

    def _monitor_max_slices(self) -> None:
        # Poll peers' /slices/max and adopt larger values
        # (server.go:216-252).
        self._loop(self.polling_interval, self.poll_max_slices,
                   "max slices poll")

    def poll_max_slices(self) -> None:
        for node in self.cluster.nodes:
            if node.host == self.host:
                continue
            client = self.client_for(node.host)
            for name, value in client.max_slices().items():
                idx = self.holder.index(name)
                if idx is not None:
                    idx.set_remote_max_slice(value)
            for name, value in client.max_slices(inverse=True).items():
                idx = self.holder.index(name)
                if idx is not None:
                    idx.set_remote_max_inverse_slice(value)

    _BREAKER_PROBE_INTERVAL = 1.0

    def _monitor_breaker_probes(self) -> None:
        """Active half-open probing: a peer behind an open circuit gets
        NO traffic (that is the point), so recovery cannot rely on
        query placement happening to route it a request — in many
        topologies it never would. This loop sends each probe-ready
        peer one cheap /version request; the fault-aware client takes
        the half-open probe slot, and the outcome closes or re-opens
        the breaker through the ordinary feed."""
        self._loop(self._BREAKER_PROBE_INTERVAL,
                   self.probe_open_breakers, "breaker probe")

    def probe_open_breakers(self) -> None:
        from ..errors import QueryDeadlineError
        for host in (self.fault.probe_targets()
                     if self.fault is not None else ()):
            try:
                # deadline_s clamps the probe's socket timeout: a
                # blackholed peer must not pin this loop for the
                # client's full 30 s default.
                self.client_for(host)._do("GET", "/version",
                                          deadline_s=2.0)
            except QueryDeadlineError:
                # The client deliberately does NOT feed budget-clamped
                # timeouts to the breaker (tight query deadlines must
                # not condemn healthy peers) — but the probe's 2 s IS
                # the probe's verdict: a peer that can't answer
                # /version in 2 s stays open.
                self.fault.record_rpc(host, False)
            except Exception:  # noqa: BLE001 - outcome fed the breaker
                pass

    def _monitor_anti_entropy(self) -> None:
        from .syncer import HolderSyncer

        def run():
            # server.go:182-214 logs the start and total duration of
            # every anti-entropy sweep.
            with self.logger.track("holder sync"):
                HolderSyncer(self.holder, self.host, self.cluster,
                             closing=self._closing,
                             client_factory=self._client_factory,
                             fault=self.fault,
                             logger=self.logger).sync_holder()

        self._loop(self.anti_entropy_interval, run, "anti-entropy")

    # -- BroadcastHandler (server.go:255-300) --------------------------------

    def receive_message(self, m) -> None:
        if isinstance(m, pb.CreateSliceMessage):
            idx = self.holder.index(m.Index)
            if idx is None:
                return
            if m.IsInverse:
                idx.set_remote_max_inverse_slice(m.Slice)
            else:
                idx.set_remote_max_slice(m.Slice)
        elif isinstance(m, pb.CreateIndexMessage):
            self.holder.create_index_if_not_exists(
                m.Index, IndexOptions.decode(m.Meta))
        elif isinstance(m, pb.DeleteIndexMessage):
            self.holder.delete_index(m.Index)
        elif isinstance(m, pb.CreateFrameMessage):
            idx = self.holder.index(m.Index)
            if idx is not None:
                opts = FrameOptions.decode(m.Meta)
                frame = idx.create_frame_if_not_exists(m.Frame, opts)
                # Field creation on an existing frame re-broadcasts the
                # full meta: register any fields this node lacks
                # (create_field is idempotent on a matching range).
                for fld in opts.fields or []:
                    frame.create_field(fld)
        elif isinstance(m, pb.DeleteFrameMessage):
            idx = self.holder.index(m.Index)
            if idx is not None:
                idx.delete_frame(m.Frame)
        elif isinstance(m, ResizeMessage):
            # Elastic resize control plane (cluster.resize): prepare /
            # flip / finalize / abort, delivered as direct acked POSTs
            # by the coordinator and replayed via gossip for
            # stragglers.
            self._apply_resize_message(m)
        elif isinstance(m, CancelQueryMessage):
            # Cluster-wide cancellation (sched subsystem): kill every
            # leg registered under this id on THIS node — the
            # coordinator's entry query and forwarded remote legs both
            # carry the same id.
            n = self.query_registry.cancel_local(
                m.id, reason="cancelled cluster-wide")
            if n:
                self.logger.printf("cancelled query %s (%d context%s)",
                                   m.id, n, "" if n == 1 else "s")
        else:
            raise ValueError(f"unexpected message: {m!r}")

    # -- StatusHandler (server.go:306-440) -----------------------------------

    def local_status(self) -> pb.NodeStatus:
        """This node's state as the wire type the gossip push/pull
        carries: schema with metas + the slice list this node owns per
        index (server.go:306-323, internal/private.proto NodeStatus)."""
        indexes = []
        for name in sorted(self.holder.indexes):
            idx = self.holder.indexes[name]
            max_slice = idx.max_slice()
            indexes.append(pb.Index(
                Name=name, Meta=idx.options.encode(), MaxSlice=max_slice,
                Frames=[pb.Frame(Name=fn,
                                 Meta=idx.frames[fn].options.encode())
                        for fn in sorted(idx.frames)],
                Slices=self.cluster.owns_slices(name, max_slice,
                                                self.host)))
        return pb.NodeStatus(Host=self.host, State=NODE_STATE_UP,
                             Indexes=indexes)

    def cluster_status(self) -> pb.ClusterStatus:
        """NodeStatus for every node: ours live, peers from the last
        status merge, membership deciding UP/DOWN (server.go:325-351)."""
        states = self.cluster.node_states()
        nodes = []
        for n in self.cluster.nodes:
            if n.host == self.host:
                nodes.append(self.local_status())
                continue
            ns = pb.NodeStatus()
            if n.status is not None:
                ns.CopyFrom(n.status)
            ns.Host = n.host
            ns.State = states.get(n.host, NODE_STATE_DOWN)
            nodes.append(ns)
        return pb.ClusterStatus(Nodes=nodes)

    def handle_remote_status(self, status: pb.NodeStatus) -> None:
        """Merge a peer's schema + owned-slice knowledge into ours
        (server.go:353-387 mergeRemoteStatus)."""
        node = self.cluster.node_by_host(status.Host)
        if node is not None:
            node.set_status(status)
        for idx_info in status.Indexes:
            idx = self.holder.create_index_if_not_exists(
                idx_info.Name, IndexOptions.decode(idx_info.Meta))
            remote_max = max([idx_info.MaxSlice] +
                             [int(s) for s in idx_info.Slices])
            idx.set_remote_max_slice(remote_max)
            for frame_info in idx_info.Frames:
                idx.create_frame_if_not_exists(
                    frame_info.Name, FrameOptions.decode(frame_info.Meta))


class _RoutingClient:
    """Executor transport that routes to whatever node is asked for
    (the executor passes the target node per call). deadline_aware:
    lifecycle kwargs (remaining budget + query id) pass straight
    through to the underlying pooled Client, which clamps socket
    timeouts/retries and stamps the fan-out headers."""

    deadline_aware = True
    generation_aware = True

    def __init__(self, server: Server):
        self.server = server

    def execute_query(self, node, index, query, slices, remote,
                      pod_local=False, deadline_s=None, query_id=None,
                      gens_out=None):
        # gens_out travels only when set — test fixtures fake the
        # pooled client with the pre-generations signature.
        kwargs = {"gens_out": gens_out} if gens_out is not None else {}
        return self.server.client_for(node.host).execute_query(
            node, index, query, slices, remote=remote,
            pod_local=pod_local, deadline_s=deadline_s,
            query_id=query_id, **kwargs)

    def generations(self, index, slices=None, host=None,
                    deadline_s=None):
        """The executor's cluster-cache validation probe, routed
        through the pooled per-host Client."""
        return self.server.client_for(host).generations(
            index, slices, deadline_s=deadline_s)
