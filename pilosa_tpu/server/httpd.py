"""Minimal threaded HTTP/1.1 server for the WSGI handler.

The reference serves each connection on a goroutine with net/http
(server.go:146): keep-alive connections, concurrent accept, ~µs-level
per-request overhead. The stdlib wsgiref server this replaces spoke
HTTP/1.0 (a fresh TCP connection AND a fresh thread per request) and
parsed requests through several Python layers — measured at ~1 K
requests/s, a 27× mismatch against the storage engine behind it
(benchmarks/RESULTS.md round 4, VERDICT r4 item 2).

Design:
- thread per CONNECTION (goroutine analogue), keep-alive by default,
  one tight request parser (find header end, split request line, scan
  the few headers the app reads).
- PIPELINING: every complete request already buffered is parsed before
  responding, and responses go out in one sendall.
- QUERY BATCH LANE: consecutive pipelined ``POST /index/{i}/query``
  requests (plain-PQL JSON mode, same index) execute as ONE combined
  executor call — the executor's mutate-batch run then turns a 1000-
  request SetBit burst into a handful of native batch crossings. Per-
  request response framing is preserved; any parse/execute error falls
  back to per-request dispatch, keeping error semantics identical.
"""

from __future__ import annotations

import io
import re
import socket
import sys
import threading

from ..utils import logger as logger_mod

_QUERY_PATH_RE = re.compile(r"^/index/([^/]+)/query$")

# Largest single request (header + body) accepted; matches the import
# path's 10M-bit buffers with headroom.
_MAX_REQUEST = 1 << 28

_STATUS_REASON = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 409: "Conflict",
                  429: "Too Many Requests",
                  500: "Internal Server Error",
                  501: "Not Implemented", 503: "Service Unavailable",
                  504: "Gateway Timeout"}


class _Request:
    __slots__ = ("method", "path", "qs", "headers", "body", "close")

    def __init__(self, method, path, qs, headers, body, close):
        self.method = method
        self.path = path
        self.qs = qs
        self.headers = headers  # dict, lower-cased keys
        self.body = body
        self.close = close


class HTTPServer:
    """Threaded HTTP/1.1 front door over a WSGI app."""

    def __init__(self, app, host: str, port: int,
                 logger=logger_mod.NOP, query_batcher=None):
        self.app = app
        self.logger = logger
        # query_batcher(index, [pql bodies]) -> list[response bytes] | None
        self.query_batcher = query_batcher
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(512)
        self.server_address = self._sock.getsockname()
        self._closing = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_mu = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def serve_forever(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Accepted sockets do NOT inherit SO_REUSEADDR on Linux;
            # without it, a lingering keep-alive connection in FIN_WAIT
            # blocks rebinding the port on restart (the reference's
            # net/http restarts fine for the same reason: Go sets
            # REUSEADDR on every socket).
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            with self._conns_mu:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="httpd-conn").start()

    def shutdown(self) -> None:
        self._closing.set()
        try:
            # A thread blocked in accept() pins the listening socket
            # past close() (close only drops the fd table entry);
            # shutdown() wakes the accept so the socket actually dies
            # and the port frees for restart.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def server_close(self) -> None:
        self.shutdown()
        with self._conns_mu:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- connection loop -----------------------------------------------------

    # Idle keep-alive connections release their thread + fd after this
    # long (the thread-per-connection model would otherwise pin one of
    # each per idle client forever; Go's net/http has the same knob in
    # IdleTimeout).
    IDLE_TIMEOUT_S = 120.0

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(self.IDLE_TIMEOUT_S)
        buf = bytearray()
        need = 0
        try:
            while not self._closing.is_set():
                if need and len(buf) < need:
                    # A partial request with known total size: keep
                    # receiving without re-parsing (re-scanning the
                    # buffer per 64 KB recv made multi-MB bodies
                    # quadratic in header finds).
                    try:
                        data = conn.recv(1 << 20)
                    except TimeoutError:
                        return
                    if not data:
                        return
                    buf += data
                    if len(buf) > _MAX_REQUEST:
                        conn.sendall(self._plain_response(
                            400, "request too large", close=True))
                        return
                    continue
                reqs, bad, need = self._drain_requests(buf)
                if bad:
                    # Serve the valid requests already parsed FIRST —
                    # the client must not read the 400 as the response
                    # to an earlier (valid, possibly mutating) request.
                    if reqs:
                        items, _ = self._process(reqs)
                        for item in items:
                            if isinstance(item, bytes):
                                conn.sendall(item)
                            else:
                                for chunk in item:
                                    if chunk:
                                        conn.sendall(chunk)
                    conn.sendall(self._plain_response(
                        400, "malformed request", close=True))
                    return
                if reqs:
                    items, close = self._process(reqs)
                    for item in items:
                        if isinstance(item, bytes):
                            conn.sendall(item)
                        else:  # streamed body: send chunk by chunk
                            for chunk in item:
                                if chunk:
                                    conn.sendall(chunk)
                    if close:
                        return
                    continue
                try:
                    data = conn.recv(1 << 16)
                except TimeoutError:
                    return  # idle past IDLE_TIMEOUT_S
                if not data:
                    return
                buf += data
                if len(buf) > _MAX_REQUEST:
                    conn.sendall(self._plain_response(
                        400, "request too large", close=True))
                    return
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_mu:
                self._conns.discard(conn)

    def _drain_requests(self, buf: bytearray):
        """Parse every complete request in ``buf`` (consuming them).
        Returns (requests, malformed, need): ``need`` is the total
        buffered size required to complete the trailing PARTIAL request
        (0 when unknown), so the receive loop can fill large bodies
        without re-parsing per recv."""
        reqs: list[_Request] = []
        while True:
            end = buf.find(b"\r\n\r\n")
            if end < 0:
                return reqs, False, 0
            head = bytes(buf[:end]).decode("latin-1")
            lines = head.split("\r\n")
            parts = lines[0].split(" ")
            if len(parts) != 3 or not parts[2].startswith("HTTP/1"):
                return reqs, True, 0
            method, target, proto = parts
            headers = {}
            for ln in lines[1:]:
                k, sep, v = ln.partition(":")
                if sep:
                    headers[k.lower()] = v.strip()
            if "chunked" in headers.get("transfer-encoding", ""):
                return reqs, True, 0  # like wsgiref: no chunked uploads
            try:
                length = int(headers.get("content-length") or 0)
            except ValueError:
                return reqs, True, 0
            total = end + 4 + length
            if length > _MAX_REQUEST:
                return reqs, False, 0  # rejected by the size guard
            if total > len(buf):
                return reqs, False, total  # body not fully buffered
            body = bytes(buf[end + 4:total])
            del buf[:total]
            path, _, qs = target.partition("?")
            close = (headers.get("connection", "").lower() == "close"
                     or proto == "HTTP/1.0")
            reqs.append(_Request(method, path, qs, headers, body, close))
            if close:
                return reqs, False, 0

    # -- request processing --------------------------------------------------

    def _process(self, reqs: list[_Request]) -> tuple[list, bool]:
        """Response items (bytes, or a generator for streamed bodies)
        for a pipelined group, batching query POST runs."""
        out: list = []
        close = False
        i = 0
        n = len(reqs)
        while i < n:
            run_index = self._batchable_index(reqs[i])
            if run_index is not None:
                j = i + 1
                while (j < n
                       and self._batchable_index(reqs[j]) == run_index):
                    j += 1
                if j - i >= 2 and self.query_batcher is not None:
                    bodies = [reqs[k].body.decode("latin-1")
                              for k in range(i, j)]
                    batched = self.query_batcher(run_index, bodies)
                    if batched is not None:
                        out.append(b"".join(
                            self._json_response(payload,
                                                reqs[i + k].close)
                            for k, payload in enumerate(batched)))
                        close = reqs[j - 1].close
                        i = j
                        continue
            resp, close = self._dispatch_wsgi(reqs[i])
            out.append(resp)
            i += 1
            if close:
                break
        return out, close

    def _batchable_index(self, req: _Request):
        """The index name when this request can join a query batch run,
        else None (protobuf bodies, explicit slices, columnAttrs, and
        remote/podLocal legs all need per-request handling)."""
        if req.method != "POST" or req.qs or req.close:
            return None
        m = _QUERY_PATH_RE.match(req.path)
        if m is None:
            return None
        if "protobuf" in req.headers.get("content-type", ""):
            return None
        if "protobuf" in req.headers.get("accept", ""):
            return None
        return m.group(1)

    def _dispatch_wsgi(self, req: _Request):
        environ = {
            "REQUEST_METHOD": req.method,
            "PATH_INFO": req.path,
            "QUERY_STRING": req.qs,
            "SERVER_PROTOCOL": "HTTP/1.1",
            "SERVER_NAME": self.server_address[0],
            "SERVER_PORT": str(self.server_address[1]),
            "CONTENT_TYPE": req.headers.get("content-type", ""),
            "CONTENT_LENGTH": str(len(req.body)),
            "wsgi.version": (1, 0),
            "wsgi.url_scheme": "http",
            "wsgi.input": io.BytesIO(req.body),
            "wsgi.errors": sys.stderr,
            "wsgi.multithread": True,
            "wsgi.multiprocess": False,
            "wsgi.run_once": False,
        }
        for k, v in req.headers.items():
            environ["HTTP_" + k.upper().replace("-", "_")] = v
        captured: dict = {}

        def start_response(status, headers, exc_info=None):
            captured["status"] = status
            captured["headers"] = headers

        body_iter = self.app(environ, start_response)
        status = captured.get("status", "500 Internal Server Error")
        headers = captured.get("headers", [])
        has_length = any(k.lower() == "content-length"
                         for k, _ in headers)
        head = [f"HTTP/1.1 {status}"]
        head.extend(f"{k}: {v}" for k, v in headers)
        if has_length:
            conn_hdr = "close" if req.close else "keep-alive"
            head.append(f"Connection: {conn_hdr}")
            head.append("")
            head.append("")
            parts = [("\r\n".join(head)).encode("latin-1")]
            parts.extend(body_iter)
            return b"".join(parts), req.close
        # Streamed response with unknown length: close-delimited (the
        # CSV export / tar download path — can be 100 MB+, never
        # buffered whole). Returned as a generator; the connection loop
        # sends chunk by chunk then closes.
        head.append("Connection: close")
        head.append("")
        head.append("")

        def stream():
            yield ("\r\n".join(head)).encode("latin-1")
            yield from body_iter
        return stream(), True

    # -- response builders ---------------------------------------------------

    @staticmethod
    def _json_response(payload, close: bool) -> bytes:
        """Frame one batch-lane payload: plain bytes = 200; a
        (status, bytes) tuple carries an error status."""
        status = 200
        if isinstance(payload, tuple):
            status, payload = payload
        conn_hdr = "close" if close else "keep-alive"
        reason = _STATUS_REASON.get(status, "Unknown")
        return (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: {conn_hdr}\r\n\r\n"
                ).encode("latin-1") + payload

    @staticmethod
    def _plain_response(status: int, msg: str, close: bool) -> bytes:
        body = (msg + "\n").encode()
        conn_hdr = "close" if close else "keep-alive"
        return (f"HTTP/1.1 {status} {_STATUS_REASON.get(status, '?')}\r\n"
                f"Content-Type: text/plain; charset=utf-8\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: {conn_hdr}\r\n\r\n").encode("latin-1") + body
