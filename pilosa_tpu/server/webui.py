"""Embedded browser console.

Reference: webui/index.html + webui/assets/main.js, compiled into the
binary via statik (handler.go:15,43-44,132-145). Feature parity: a PQL
query box targeting ``POST /index/{index}/query`` with an index dropdown
populated from ``/schema``, per-query wall-time display and a result
history, a cluster-status view over ``/status``, and the server version
from ``/version``. Re-implemented as one dependency-free page embedded in
this module (the Python analogue of statik embedding).
"""

PAGE = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>pilosa-tpu console</title>
<style>
  :root { --fg:#1a1c1e; --mut:#6b7075; --line:#d8dbde; --accent:#0b57d0;
          --ok:#1e7e34; --bad:#b3261e; --code:#f4f5f6; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.5 system-ui,sans-serif; color:var(--fg); }
  header { display:flex; align-items:baseline; gap:1rem; padding:.7rem 1.2rem;
           border-bottom:1px solid var(--line); }
  header h1 { font-size:1rem; margin:0; }
  header nav a { margin-right:.8rem; color:var(--accent); cursor:pointer;
                 text-decoration:none; }
  header nav a.active { font-weight:600; text-decoration:underline; }
  #version { margin-left:auto; color:var(--mut); }
  main { padding:1rem 1.2rem; max-width:70rem; }
  .pane { display:none; } .pane.active { display:block; }
  .row { display:flex; gap:.6rem; margin-bottom:.6rem; }
  select,textarea,button { font:inherit; padding:.35rem .5rem;
    border:1px solid var(--line); border-radius:4px; }
  textarea { flex:1; height:4.2rem; font-family:ui-monospace,monospace; }
  button { background:var(--accent); color:#fff; border:none;
           cursor:pointer; align-self:flex-start; }
  .entry { border:1px solid var(--line); border-radius:4px;
           margin-bottom:.8rem; }
  .entry .q { padding:.4rem .6rem; font-family:ui-monospace,monospace;
              background:var(--code); display:flex; }
  .entry .q em { margin-left:auto; color:var(--mut); font-style:normal; }
  .entry pre { margin:0; padding:.4rem .6rem; overflow-x:auto;
               font-size:.85rem; }
  .entry.err .q { color:var(--bad); }
  table { border-collapse:collapse; }
  td,th { border:1px solid var(--line); padding:.3rem .7rem;
          text-align:left; }
  .UP,.OK { color:var(--ok); } .DOWN { color:var(--bad); }
</style>
</head>
<body>
<header>
  <h1>pilosa-tpu</h1>
  <nav>
    <a id="nav-query" class="active">Query</a>
    <a id="nav-cluster">Cluster</a>
  </nav>
  <span id="version"></span>
</header>
<main>
  <section id="pane-query" class="pane active">
    <div class="row">
      <select id="index"></select>
      <textarea id="pql" placeholder='Count(Bitmap(frame="f", rowID=1))'
        ></textarea>
      <button id="run">Run &#9166;</button>
    </div>
    <div id="history"></div>
  </section>
  <section id="pane-cluster" class="pane">
    <table><thead><tr><th>Host</th><th>State</th><th>Indexes</th></tr>
    </thead><tbody id="status"></tbody></table>
  </section>
</main>
<script>
"use strict";
const $ = id => document.getElementById(id);
const getJSON = (path, cb) =>
  fetch(path).then(r => r.json()).then(cb).catch(() => {});

function show(pane) {
  for (const p of ["query", "cluster"]) {
    $("pane-" + p).classList.toggle("active", p === pane);
    $("nav-" + p).classList.toggle("active", p === pane);
  }
  if (pane === "cluster") refreshStatus();
}
$("nav-query").onclick = () => show("query");
$("nav-cluster").onclick = () => show("cluster");

function refreshSchema() {
  getJSON("/schema", s => {
    const sel = $("index"), cur = sel.value;
    sel.innerHTML = "";
    for (const ix of (s.indexes || []))
      sel.add(new Option(ix.name, ix.name, false, ix.name === cur));
  });
}
function refreshStatus() {
  getJSON("/status", s => {
    const tbody = $("status");
    tbody.replaceChildren();
    for (const n of ((s.status || {}).nodes || [])) {
      const tr = document.createElement("tr");
      const st = n.state || "?";
      for (const text of [n.host, st,
                          (n.indexes || []).map(i => i.name).join(", ")]) {
        const td = document.createElement("td");
        td.textContent = text;
        tr.appendChild(td);
      }
      tr.children[1].className = st;
      tbody.appendChild(tr);
    }
  });
}
function run() {
  const index = $("index").value, q = $("pql").value.trim();
  if (!index || !q) return;
  const t0 = performance.now();
  fetch("/index/" + encodeURIComponent(index) + "/query",
        {method: "POST", body: q})
    .then(r => r.json().then(body => ({ok: r.ok, body})))
    .then(({ok, body}) => record(q, body, ok, performance.now() - t0))
    .catch(e => record(q, {error: String(e)}, false,
                       performance.now() - t0));
  refreshSchema();
}
function record(q, body, ok, ms) {
  const div = document.createElement("div");
  div.className = "entry" + (ok ? "" : " err");
  const head = document.createElement("div");
  head.className = "q";
  head.textContent = q;
  const t = document.createElement("em");
  t.textContent = ms.toFixed(1) + " ms";
  head.appendChild(t);
  const pre = document.createElement("pre");
  pre.textContent = JSON.stringify(body, null, 2);
  div.append(head, pre);
  $("history").prepend(div);
}
$("run").onclick = run;
$("pql").addEventListener("keydown", e => {
  if (e.key === "Enter" && !e.shiftKey) { e.preventDefault(); run(); }
});

getJSON("/version", v => $("version").textContent =
  "v" + (v.version || "?"));
refreshSchema();
setInterval(() => {
  if ($("pane-cluster").classList.contains("active")) refreshStatus();
}, 5000);
</script>
</body>
</html>
"""


def page_bytes() -> bytes:
    return PAGE.encode()
