"""Embedded browser console, served from a static asset set.

Reference: webui/index.html + webui/assets/{main.js,style.css},
compiled into the binary via statik and served at ``/`` and
``/assets/{file}`` (handler.go:15,43-44,84,132-145). Same shape here:
``pilosa_tpu/server/assets/`` ships with the package (the Python
analogue of statik embedding — package data instead of a generated Go
file), and the handler mounts ``GET /`` → index.html plus
``GET /assets/{file}``.

Feature parity with the reference console (webui/assets/main.js):
a PQL REPL targeting ``POST /index/{index}/query`` with an index
dropdown from ``/schema``, per-query wall-time + result history +
ArrowUp/ArrowDown keyboard recall, a cluster-status view over
``/status``, the server version from ``/version`` — plus a schema
browser (indexes → frames → options) the reference links out for.
"""

from __future__ import annotations

import os

_ASSET_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "assets")

_CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".css": "text/css; charset=utf-8",
    ".js": "application/javascript; charset=utf-8",
    ".svg": "image/svg+xml",
    ".png": "image/png",
}


_cache: dict[str, tuple[bytes, str] | None] = {}


def asset(name: str) -> tuple[bytes, str] | None:
    """(bytes, content type) for one asset, or None when unknown;
    read once and served from memory after (the statik-embedding
    behavior this module mirrors).

    Names are single path segments only — the route pattern forbids
    ``/`` and this re-checks, so traversal cannot escape the dir."""
    if name in _cache:
        return _cache[name]
    if not name or "/" in name or "\\" in name or name.startswith("."):
        return None  # don't cache hostile names unboundedly
    path = os.path.join(_ASSET_DIR, name)
    if not os.path.isfile(path):
        return None
    ext = os.path.splitext(name)[1]
    ctype = _CONTENT_TYPES.get(ext, "application/octet-stream")
    with open(path, "rb") as f:
        got = (f.read(), ctype)
    _cache[name] = got
    return got


def page_bytes() -> bytes:
    """The console page (GET /)."""
    got = asset("index.html")
    if got is None:  # packaging error — fail loud, not blank
        raise FileNotFoundError("webui assets missing: index.html")
    return got[0]
