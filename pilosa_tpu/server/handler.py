"""HTTP API: the reference's full route table as a WSGI application.

Reference: handler.go (route table at handler.go:82-120). Content
negotiation between JSON and ``application/x-protobuf`` mirrors
handler.go:811-893; strict unknown-key validation of index/frame options
mirrors handler.go:299-351,577-610.

WSGI keeps the handler framework-free: tests call the app in-process
(no sockets), and server.py serves it with the stdlib threading WSGI
server — the Python analogue of the reference's net/http.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import re
import threading
from typing import Callable, Optional
from urllib.parse import parse_qs

import numpy as np

from .. import __version__
from ..cluster import generations as gens_mod
from ..cluster.broadcast import (NOP_BROADCASTER, CancelQueryMessage,
                                 unmarshal_message)
from ..errors import (FrameExistsError, IndexExistsError, PilosaError,
                      QueryCancelledError, QueryDeadlineError,
                      QueryKilledError, SliceUnavailableError,
                      validate_label)
from ..fault import diskfull as fault_diskfull
from ..obs import accounting as obs_accounting
from ..obs import capture as obs_capture
from ..obs import metrics as obs_metrics
from ..obs import profile as obs_profile
from ..obs import trace as obs_trace
from ..plan import record as plan_record
from ..sched import (KILL_POLICY, KILLED_BY_HEADER, LANE_ADMIN, LANE_READ,
                     LANE_WRITE, AdmissionFullError, QueryContext,
                     QueryRegistry)
from ..sched import context as sched_context
from ..models.frame import Field, FrameOptions
from ..models.index import IndexOptions
from ..pql import parser as pql
from ..proto import internal_pb2 as pb
from ..storage import wal as storage_wal
from ..storage.attrs import diff_blocks
from ..storage.bitmap import Bitmap
from ..utils import timequantum as tq
from ..utils.streams import CappedReader
from . import codec

_PROTOBUF = "application/x-protobuf"

# JSON keys accepted in POST /index and POST /frame options
# (handler.go:299-351 validates against the Go struct tags).
_VALID_INDEX_OPTIONS = {"columnLabel", "timeQuantum"}
_VALID_FRAME_OPTIONS = {"rowLabel", "inverseEnabled", "cacheType",
                        "cacheSize", "timeQuantum", "fields"}


class HTTPError(Exception):
    def __init__(self, status: int, message: str, headers=None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers or []


class Request:
    """Decoded WSGI request."""

    def __init__(self, environ: dict, vars: dict[str, str]):
        self.environ = environ
        self.vars = vars
        self.query = {k: v[0] for k, v in
                      parse_qs(environ.get("QUERY_STRING", "")).items()}

    @property
    def content_type(self) -> str:
        return self.environ.get("CONTENT_TYPE", "")

    @property
    def accept(self) -> str:
        return self.environ.get("HTTP_ACCEPT", "")

    def body(self) -> bytes:
        # Missing/invalid Content-Length reads as empty — an unbounded
        # read() on the live socket would block the worker thread.
        try:
            length = int(self.environ.get("CONTENT_LENGTH") or 0)
        except ValueError:
            length = 0
        stream = self.environ.get("wsgi.input")
        if stream is None or length <= 0:
            return b""
        return stream.read(length)

    def body_stream(self):
        """The request body as a bounded file-like, without buffering it
        — restores stream 128 MB+ fragment tars straight to disk."""
        try:
            length = int(self.environ.get("CONTENT_LENGTH") or 0)
        except ValueError:
            length = 0
        stream = self.environ.get("wsgi.input")
        if stream is None or length <= 0:
            return io.BytesIO(b"")
        return CappedReader(stream, length)

    def json(self) -> dict:
        raw = self.body()
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except ValueError as e:
            raise HTTPError(400, f"invalid JSON: {e}")

    def uint_param(self, name: str) -> int:
        v = self.query.get(name)
        if v is None or not v.isdigit():
            raise HTTPError(400, f"{name} required")
        return int(v)


class Response:
    def __init__(self, status: int = 200, body=b"",
                 content_type: str = "application/json",
                 headers=None):
        # body: bytes, or a readable file object (streamed in chunks —
        # used for fragment backups, which can be 128 MB+). headers:
        # extra (name, value) pairs (Retry-After, X-Pilosa-Query-Id).
        self.status = status
        self.body = body
        self.content_type = content_type
        self.headers = headers or []

    @staticmethod
    def json(obj, status: int = 200, headers=None) -> "Response":
        return Response(status, (json.dumps(obj) + "\n").encode(),
                        headers=headers)

    @staticmethod
    def proto(msg, status: int = 200, headers=None) -> "Response":
        return Response(status, msg.SerializeToString(), _PROTOBUF,
                        headers=headers)


def _export_csv_chunks(frag):
    """Vectorized, chunked CSV body: one chunk per roaring container, so
    a 128 MB+ fragment never sits in memory as text (the reference
    streams via csv.Writer over ForEachBit, handler.go:985-1025).

    The WSGI layer drains this generator after the handler returns, so
    it streams from Fragment.snapshot_value_chunks(): a point-in-time
    copy of the compressed container buffers taken under the fragment
    lock — concurrent mutations during the (possibly long) transfer
    can't tear a row mid-stream, and peak memory is bounded by the
    compressed fragment size, not the rendered text."""
    from .. import SLICE_WIDTH
    base = frag.slice * SLICE_WIDTH
    w = np.uint64(SLICE_WIDTH)
    for vals in frag.snapshot_value_chunks():
        rows = (vals // w).tolist()
        cols = (vals % w).tolist()
        yield "".join(f"{r},{base + c}\r\n"
                      for r, c in zip(rows, cols)).encode()


def _stream_chunks(f, chunk_size: int = 1 << 20):
    try:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                return
            yield chunk
    finally:
        f.close()


_STATUS_TEXT = {200: "OK", 400: "Bad Request",
                402: "Payment Required",  # cost-policy kill
                404: "Not Found",
                405: "Method Not Allowed", 406: "Not Acceptable",
                409: "Conflict", 412: "Precondition Failed",
                415: "Unsupported Media Type",
                429: "Too Many Requests",
                500: "Internal Server Error", 503: "Service Unavailable",
                504: "Gateway Timeout",
                507: "Insufficient Storage"}  # ENOSPC write-unready


# Import apply lanes: how many /import handlers may be in their APPLY
# stage at once, process-wide. The apply is mostly GIL-holding
# Python/numpy, so unbounded concurrent applies convoy on the GIL and
# run measurably SLOWER than the same blocks queued (1.4x at 4 lanes
# on 2 cores) — the pipelining win comes from decode/wire/WAL of
# other blocks overlapping an apply, which the gate never blocks.
# `pilosa_import_pipeline_depth` counts handlers in the stage
# (applying + gate-queued), so depth > lanes means the pipeline is
# feeding the gate faster than it drains.
_APPLY_LANES = max(1, int(os.environ.get(
    "PILOSA_TPU_IMPORT_APPLY_LANES", "1") or 1))
_APPLY_GATE = threading.BoundedSemaphore(_APPLY_LANES)


class Handler:
    """Router + handlers. Executor is any object with
    ``execute(index, query, slices, opt)`` — the mock seam used by the
    handler tests, mirroring the reference's Handler.Executor interface
    (handler.go:60-62)."""

    def __init__(self, holder, executor, cluster=None, host: str = "",
                 broadcaster=NOP_BROADCASTER, broadcast_handler=None,
                 status_handler=None, stats=None, client_factory=None,
                 pod=None, logger=None, admission=None, registry=None,
                 warmup=None, default_timeout_s: float = 0.0,
                 tracer=None, runtime=None, profiler=None, health=None,
                 accounting: bool = True, fault=None, sampler=None,
                 blackbox=None, watchdog=None, history=None,
                 sentinel=None, federator=None, tenants=None,
                 tenant_slo=None, scrubber=None, repairer=None,
                 tier=None, capture=None):
        from ..utils import logger as logger_mod
        self.logger = logger or logger_mod.NOP
        self.holder = holder
        self.executor = executor
        self.cluster = cluster
        self.host = host
        self.pod = pod  # parallel.pod.Pod when serving as a pod process
        self.broadcaster = broadcaster
        self.broadcast_handler = broadcast_handler
        self.status_handler = status_handler
        self.stats = stats
        # client_factory(host) -> cluster.client.Client; injected to keep
        # handler importable without the client (and mockable in tests).
        self.client_factory = client_factory
        # Query lifecycle (sched subsystem): admission=None means no
        # admission control (bare test handlers); the registry always
        # exists so /debug/queries works on any handler.
        self.admission = admission
        # Multi-tenant QoS (sched.tenants): the tenant registry
        # resolves every request's principal (header > index >
        # default), installs the cost-kill policy, and backs
        # /debug/tenants; tenant_slo is the per-tenant burn tracker
        # (obs.slo.TenantSLOTracker). None = tenant-blind (bare test
        # handlers; tenant metrics still record by index).
        self.tenants = tenants
        self.tenant_slo = tenant_slo
        self.registry = registry if registry is not None \
            else QueryRegistry(logger=self.logger)
        self.warmup = warmup
        self.default_timeout_s = default_timeout_s or 0.0
        # Observability (obs subsystem): a per-node tracer (disabled by
        # default — bare handlers still honor per-request ?trace=1) and
        # the runtime collector behind /status and /metrics freshness.
        self.tracer = tracer if tracer is not None \
            else obs_trace.Tracer(enabled=False)
        self.runtime = runtime
        # Tail sampling (obs.sampler): when wired, EVERY query gets
        # the span buffer and the keep decision runs at query end;
        # None (bare test handlers) keeps the ask-first behavior.
        self.sampler = sampler
        # Flight recorder + stall watchdog (obs.blackbox/obs.watchdog)
        # behind /debug/blackbox*; None serves empty state.
        self.blackbox = blackbox
        self.watchdog = watchdog
        # Fleet observability (obs.history / obs.sentinel /
        # obs.federate): the on-disk metric history behind
        # /debug/metrics/history, the regression sentinel behind
        # /debug/sentinel, and the federator behind /metrics/cluster +
        # /debug/cluster. A bare handler keeps a peerless federator so
        # the cluster routes serve single-node answers.
        self.history = history
        self.sentinel = sentinel
        # Storage integrity (storage.scrub / server.repair) behind
        # /debug/integrity; None (bare handlers) serves the holder's
        # quarantine registry alone.
        self.scrubber = scrubber
        self.repairer = repairer
        # Tiered storage (pilosa_tpu.tier) behind /debug/tier; None
        # (tiering off / bare handlers) serves a disabled stub.
        self.tier = tier
        # Workload capture (obs.capture.CaptureStore) behind
        # /debug/capture*; None (bare handlers) serves a disabled
        # status and captures nothing — the query path pays one
        # ``is not None`` check.
        self.capture = capture
        if federator is None:
            from ..obs.federate import Federator
            federator = Federator(host)
        self.federator = federator
        # Continuous profiler (obs.profile) behind /debug/pprof/flame —
        # the module default is NOT started, so bare handlers serve the
        # route with an empty ring and zero sampling overhead.
        self.profiler = profiler if profiler is not None \
            else obs_profile.get_profiler()
        # Readiness checks behind GET /health (obs.slo.HealthChecker);
        # built lazily from this handler's own wiring when not injected.
        self._health = health
        # Per-handler accounting gate ([metrics] accounting): scoped
        # here, not process-global, so in-process multi-server tests
        # can differ; obs_accounting.enabled() remains a second,
        # module-wide kill switch.
        self.accounting = accounting
        # Fault-tolerance state (fault.FaultManager) behind the
        # /status ``fault`` block; failpoint admin (/debug/failpoints)
        # talks to the process-global registry and works on bare
        # handlers too.
        self.fault = fault
        self.version = __version__
        # (method, regex, handler, admission lane, raw pattern)
        self._routes: list[tuple] = []
        self._add_routes()

    # -- routing -------------------------------------------------------------

    def _route(self, method: str, pattern: str, fn: Callable,
               lane: Optional[str] = None) -> None:
        # {name} segments become named groups matching one path segment.
        # ``lane`` routes the whole handler through that admission lane
        # (the query handler manages its own slot — deadline-aware, and
        # remote legs bypass — so it stays lane=None here). The raw
        # pattern is kept for introspection (the README route-table
        # sweep test walks it).
        regex = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        self._routes.append((method, re.compile(f"^{regex}$"), fn, lane,
                             pattern))

    def _add_routes(self) -> None:
        # Route table (reference handler.go:82-120).
        r = self._route
        r("GET", "/", self._handle_webui)
        r("GET", "/assets/{file}", self._handle_asset)
        r("GET", "/index", self._handle_get_schema)
        r("GET", "/index/{index}", self._handle_get_index)
        r("POST", "/index/{index}", self._handle_post_index,
          lane=LANE_ADMIN)
        r("DELETE", "/index/{index}", self._handle_delete_index,
          lane=LANE_ADMIN)
        r("POST", "/index/{index}/attr/diff", self._handle_index_attr_diff)
        r("POST", "/index/{index}/frame/{frame}", self._handle_post_frame,
          lane=LANE_ADMIN)
        r("DELETE", "/index/{index}/frame/{frame}",
          self._handle_delete_frame, lane=LANE_ADMIN)
        r("POST", "/index/{index}/query", self._handle_post_query)
        r("POST", "/index/{index}/frame/{frame}/attr/diff",
          self._handle_frame_attr_diff)
        r("POST", "/index/{index}/frame/{frame}/restore",
          self._handle_post_frame_restore, lane=LANE_ADMIN)
        r("PATCH", "/index/{index}/frame/{frame}/time-quantum",
          self._handle_patch_frame_time_quantum, lane=LANE_ADMIN)
        r("GET", "/index/{index}/frame/{frame}/views",
          self._handle_get_frame_views)
        r("GET", "/index/{index}/frame/{frame}/fields",
          self._handle_get_frame_fields)
        r("POST", "/index/{index}/frame/{frame}/field/{field}",
          self._handle_post_frame_field, lane=LANE_ADMIN)
        r("POST", "/index/{index}/frame/{frame}/field/{field}/import",
          self._handle_post_field_import, lane=LANE_WRITE)
        r("PATCH", "/index/{index}/time-quantum",
          self._handle_patch_index_time_quantum, lane=LANE_ADMIN)
        r("GET", "/cluster/resize", self._handle_get_cluster_resize)
        r("POST", "/cluster/resize", self._handle_post_cluster_resize,
          lane=LANE_ADMIN)
        r("GET", "/backup", self._handle_get_backup)
        r("POST", "/backup", self._handle_post_backup,
          lane=LANE_ADMIN)
        r("GET", "/debug/backup", self._handle_debug_backup)
        r("GET", "/debug/topology", self._handle_debug_topology)
        r("GET", "/debug/tenants", self._handle_debug_tenants)
        r("GET", "/debug/queries", self._handle_debug_queries)
        r("GET", "/debug/queries/slow", self._handle_debug_slow_queries)
        r("DELETE", "/debug/queries/{qid}", self._handle_delete_query)
        r("GET", "/debug/traces", self._handle_debug_traces)
        # /summary must register BEFORE the {qid} wildcard or the
        # wildcard swallows it.
        r("GET", "/debug/traces/summary",
          self._handle_debug_traces_summary)
        r("GET", "/debug/traces/{qid}", self._handle_debug_trace)
        r("GET", "/debug/blackbox", self._handle_debug_blackbox)
        r("POST", "/debug/blackbox/dump",
          self._handle_post_blackbox_dump)
        r("GET", "/debug/failpoints", self._handle_debug_failpoints)
        r("POST", "/debug/failpoints", self._handle_post_failpoints)
        r("GET", "/debug/integrity", self._handle_debug_integrity)
        r("POST", "/debug/integrity/scrub",
          self._handle_post_integrity_scrub)
        r("GET", "/debug/tier", self._handle_debug_tier)
        r("GET", "/debug/capture", self._handle_debug_capture)
        r("GET", "/debug/capture/records",
          self._handle_debug_capture_records)
        r("GET", "/debug/vars", self._handle_expvar)
        r("GET", "/debug/metrics/history",
          self._handle_metrics_history)
        r("GET", "/debug/cluster", self._handle_debug_cluster)
        r("GET", "/debug/plans", self._handle_debug_plans)
        r("GET", "/debug/sentinel", self._handle_debug_sentinel)
        r("GET", "/metrics", self._handle_metrics)
        r("GET", "/metrics/cluster", self._handle_metrics_cluster)
        r("GET", "/debug/pprof", self._handle_pprof_index)
        r("GET", "/debug/pprof/", self._handle_pprof_index)
        r("GET", "/debug/pprof/profile", self._handle_pprof_profile)
        r("GET", "/debug/pprof/threads", self._handle_pprof_threads)
        r("GET", "/debug/pprof/heap", self._handle_pprof_heap)
        r("POST", "/debug/pprof/heap", self._handle_pprof_heap_post)
        r("GET", "/debug/pprof/flame", self._handle_pprof_flame)
        r("GET", "/health", self._handle_health)
        r("GET", "/export", self._handle_get_export)
        r("GET", "/fragment/block/data", self._handle_fragment_block_data)
        r("GET", "/fragment/blocks", self._handle_fragment_blocks)
        r("GET", "/fragment/data", self._handle_get_fragment_data)
        r("POST", "/fragment/data", self._handle_post_fragment_data)
        r("POST", "/fragment/import",
          self._handle_post_fragment_import, lane=LANE_WRITE)
        r("GET", "/fragment/nodes", self._handle_fragment_nodes)
        r("GET", "/generations", self._handle_get_generations)
        r("POST", "/import", self._handle_post_import, lane=LANE_WRITE)
        r("GET", "/hosts", self._handle_get_hosts)
        r("GET", "/schema", self._handle_get_schema)
        r("GET", "/slices/max", self._handle_slice_max)
        r("GET", "/status", self._handle_get_status)
        r("GET", "/version", self._handle_get_version)
        r("POST", "/messages", self._handle_post_message)
        r("POST", "/pod/exec", self._handle_pod_exec)

    def __call__(self, environ, start_response):
        method = environ.get("REQUEST_METHOD", "GET")
        path = environ.get("PATH_INFO", "/")
        # HEAD serves through GET handlers with the body dropped —
        # net/http gives the reference this for free.
        head = method == "HEAD"
        if head:
            method = "GET"
        matched_path = False
        for m, regex, fn, lane, _pattern in self._routes:
            match = regex.match(path)
            if match is None:
                continue
            matched_path = True
            if m != method:
                continue
            try:
                if lane is None:
                    resp = fn(Request(environ, match.groupdict()))
                else:
                    # Tenant principal for the non-query lanes
                    # (imports, schema admin): header > {index} path
                    # segment > default — resolved BEFORE the slot is
                    # taken, so the stride/quota accounting charges
                    # the right tenant from the first byte.
                    vars_ = match.groupdict()
                    tenant = (environ.get("HTTP_X_PILOSA_TENANT", "")
                              or vars_.get("index", ""))
                    with self._admitted(lane, tenant=tenant):
                        resp = fn(Request(environ, vars_))
            except HTTPError as e:
                resp = Response(e.status, (e.message + "\n").encode(),
                                "text/plain; charset=utf-8",
                                headers=e.headers)
            except PilosaError as e:
                resp = Response(400, (str(e) + "\n").encode(),
                                "text/plain; charset=utf-8")
            except Exception as e:  # noqa: BLE001 - surface as 500
                self.logger.printf("http error: %s %s: %s", method, path, e)
                resp = Response(500, (str(e) + "\n").encode(),
                                "text/plain; charset=utf-8")
            break
        else:
            status = 405 if matched_path else 404
            resp = Response(status,
                            (_STATUS_TEXT[status] + "\n").encode(),
                            "text/plain; charset=utf-8")
        status_line = (
            f"{resp.status} {_STATUS_TEXT.get(resp.status, 'Unknown')}")
        extra = list(getattr(resp, "headers", ()) or ())
        if isinstance(resp.body, bytes):
            start_response(status_line,
                           [("Content-Type", resp.content_type),
                            ("Content-Length", str(len(resp.body)))]
                           + extra)
            return [] if head else [resp.body]
        # Streamed body: file object (chunked reads) or a generator of
        # byte chunks (CSV export) — either way, never buffered whole.
        start_response(status_line,
                       [("Content-Type", resp.content_type)] + extra)
        if hasattr(resp.body, "read"):
            return _stream_chunks(resp.body)
        return resp.body

    # -- meta ----------------------------------------------------------------

    def _handle_webui(self, req: Request) -> Response:
        # Embedded console (reference webui/ + statik, handler.go:132-145).
        from .webui import page_bytes
        return Response(200, page_bytes(), "text/html; charset=utf-8")

    def _handle_asset(self, req: Request) -> Response:
        # Static console assets (reference handler.go:84 /assets/{file}).
        from .webui import asset
        got = asset(req.vars["file"])
        if got is None:
            raise HTTPError(404, "asset not found")
        body, ctype = got
        return Response(200, body, ctype)

    def _handle_get_version(self, req: Request) -> Response:
        return Response.json({"version": self.version})

    def _handle_get_hosts(self, req: Request) -> Response:
        nodes = self.cluster.nodes if self.cluster else []
        return Response.json([{"host": n.host,
                               "internalHost": n.internal_host}
                              for n in nodes])

    def _handle_get_status(self, req: Request) -> Response:
        # Cold-start warmup state (sched.warmup) and the runtime
        # collector sample (obs.runtime — holder/residency sizes,
        # compile-cache hit/miss counters) ride the JSON forms.
        warm = self.warmup.to_json() if self.warmup is not None else None
        runtime = (self.runtime.snapshot()
                   if self.runtime is not None else None)
        fault = self.fault.snapshot() if self.fault is not None else None
        # Build identity (the JSON face of pilosa_build_info): version,
        # python, jax, backend — same block on every status form.
        from ..obs.runtime import build_info
        build = build_info()
        watchdog = (self.watchdog.snapshot()
                    if self.watchdog is not None else None)
        if self.status_handler is not None:
            cs = self.status_handler.cluster_status()  # pb.ClusterStatus
            if _PROTOBUF in req.accept:
                return Response.proto(cs)
            out = {"status": {"nodes": [
                {"host": ns.Host, "state": ns.State,
                 "indexes": [{"name": ix.Name,
                              "maxSlice": ix.MaxSlice,
                              "slices": list(ix.Slices),
                              "frames": [{"name": f.Name}
                                         for f in ix.Frames]}
                             for ix in ns.Indexes]}
                for ns in cs.Nodes]}}
            out["build"] = build
            if warm is not None:
                out["warmup"] = warm
            if runtime is not None:
                out["runtime"] = runtime
            if fault is not None:
                out["fault"] = fault
            if watchdog is not None:
                out["watchdog"] = watchdog
            return Response.json(out)
        states = self.cluster.node_states() if self.cluster else {}
        out = {"status": {"Nodes": [
            {"Host": h, "State": s} for h, s in sorted(states.items())]}}
        out["build"] = build
        if warm is not None:
            out["warmup"] = warm
        if runtime is not None:
            out["runtime"] = runtime
        if fault is not None:
            out["fault"] = fault
        if watchdog is not None:
            out["watchdog"] = watchdog
        return Response.json(out)

    def _handle_expvar(self, req: Request) -> Response:
        snap = self.stats.snapshot() if hasattr(self.stats, "snapshot") \
            else {}
        # Device-path observability: HBM residency cache + fallback
        # counters (reference exposes runtime internals the same way
        # via expvar, handler.go:1287-1300).
        from ..parallel import residency
        snap = dict(snap)
        snap["deviceBlockCache"] = residency.device_cache().snapshot()
        fallbacks = getattr(self.executor, "device_fallbacks", None)
        if fallbacks is not None:
            # Authoritative value for the stats pipeline's
            # "deviceFallback" counter (executor._note_device_fallback)
            # — one name, one source.
            snap["deviceFallback"] = fallbacks
        vetoes = getattr(self.executor, "cost_vetoes", None)
        if vetoes is not None:
            snap["costModelVetoes"] = vetoes
        model = getattr(self.executor, "cost_model", None)
        if model is not None:
            snap["costModel"] = {"syncS": model.cal.sync_s,
                                 "hostBps": model.cal.host_bps,
                                 "margin": model.margin,
                                 "drift": model.drift_snapshot()}
        return Response.json(snap)

    # -- profiling (reference handler.go:30,99 mounts net/http/pprof) --------

    def _handle_pprof_index(self, req: Request) -> Response:
        return Response(
            200, b"profile: sampled CPU profile (?seconds=N, default 5)\n"
                 b"threads: stack dump of all live threads\n"
                 b"flame: continuous-profiler folded stacks"
                 b" (?query=<id> filters to one query;"
                 b" speedscope/flamegraph.pl-loadable)\n"
                 b"heap: tracemalloc allocation sites (?n=N, default"
                 b" 30); GET is read-only, POST ?op=start|stop"
                 b" arms/disarms\n",
            "text/plain; charset=utf-8")

    def _handle_pprof_heap(self, req: Request) -> Response:
        """Read-only heap report. Arming/disarming tracemalloc mutates
        interpreter-wide state, so it lives on POST ?op=start|stop."""
        from ..utils.profiling import heap_report
        try:
            top_n = int(req.query.get("n", "30"))
        except ValueError:
            raise HTTPError(400, "invalid n")
        return Response(200,
                        heap_report(max(1, min(top_n, 500))).encode(),
                        "text/plain; charset=utf-8")

    def _handle_pprof_heap_post(self, req: Request) -> Response:
        """Arm/disarm tracemalloc: POST ?op=start | ?op=stop (the
        mutating halves of the old GET contract)."""
        from ..utils.profiling import heap_start, heap_stop
        op = req.query.get("op", "start")
        if op == "start":
            body = heap_start()
        elif op == "stop":
            body = heap_stop()
        else:
            raise HTTPError(400, f"invalid op: {op} (start|stop)")
        return Response(200, body.encode(), "text/plain; charset=utf-8")

    def _handle_pprof_flame(self, req: Request) -> Response:
        """Continuous-profiler export: collapsed-stack text aggregated
        over the bounded sample ring (load into speedscope or
        flamegraph.pl). ``?query=<id>`` filters to the samples tagged
        with that query id; ``?since=<dur>`` keeps only recent
        samples."""
        since_s = 0.0
        if req.query.get("since"):
            from ..utils.config import parse_duration
            try:
                since_s = parse_duration(req.query["since"])
            except ValueError:
                raise HTTPError(400, "invalid since")
        body = self.profiler.flame(query=req.query.get("query", ""),
                                   since_s=since_s)
        return Response(200, body.encode(), "text/plain; charset=utf-8")

    def _handle_health(self, req: Request) -> Response:
        """READINESS (not liveness): 200 only when this node can
        actually serve — holder open, gossip converged, admission not
        saturated, data dir writable. Load balancers poll this;
        /version remains the liveness probe."""
        from ..obs.slo import HealthChecker
        if self._health is None:
            self._health = HealthChecker(holder=self.holder,
                                         cluster=self.cluster,
                                         admission=self.admission,
                                         host=self.host)
        ready, checks = self._health.check()
        return Response.json(
            {"status": "ok" if ready else "unhealthy",
             "checks": checks},
            status=200 if ready else 503)

    def _handle_pprof_profile(self, req: Request) -> Response:
        from ..utils.profiling import sample_profile
        import math
        try:
            seconds = float(req.query.get("seconds", "5"))
        except ValueError:
            raise HTTPError(400, "invalid seconds")
        if not math.isfinite(seconds):
            raise HTTPError(400, "invalid seconds")
        seconds = min(max(seconds, 0.1), 120.0)
        return Response(200, sample_profile(seconds).encode(),
                        "text/plain; charset=utf-8")

    def _handle_pprof_threads(self, req: Request) -> Response:
        from ..utils.profiling import thread_dump
        return Response(200, thread_dump().encode(),
                        "text/plain; charset=utf-8")

    def _handle_get_schema(self, req: Request) -> Response:
        return Response.json({"indexes": self.holder.schema()})

    def _handle_slice_max(self, req: Request) -> Response:
        inverse = req.query.get("inverse") == "true"
        ms = (self.holder.max_inverse_slices() if inverse
              else self.holder.max_slices())
        if _PROTOBUF in req.accept:
            return Response.proto(pb.MaxSlicesResponse(MaxSlices=ms))
        return Response.json({"maxSlices": ms})

    # -- index CRUD ----------------------------------------------------------

    def _handle_get_index(self, req: Request) -> Response:
        idx = self.holder.index(req.vars["index"])
        if idx is None:
            raise HTTPError(404, "index not found")
        return Response.json({"index": {"name": idx.name}})

    @staticmethod
    def _validate_options(body: dict, valid: set[str]) -> dict:
        # handler.go:299-351: any unknown key is an error.
        for k in body:
            if k != "options":
                raise HTTPError(400, f"Unknown key: {k}")
        options = body.get("options", {})
        if not isinstance(options, dict):
            raise HTTPError(400, "options is not map")
        for k in options:
            if k not in valid:
                raise HTTPError(400, f"Unknown key: {k}:{options[k]}")
        return options

    def _handle_post_index(self, req: Request) -> Response:
        name = req.vars["index"]
        opts = self._validate_options(req.json(), _VALID_INDEX_OPTIONS)
        options = IndexOptions(
            column_label=opts.get("columnLabel", "columnID"),
            time_quantum=tq.parse_time_quantum(opts.get("timeQuantum", "")))
        validate_label(options.column_label)
        try:
            self.holder.create_index(name, options)
        except IndexExistsError as e:
            raise HTTPError(409, str(e))
        self.broadcaster.send_sync(pb.CreateIndexMessage(
            Index=name, Meta=options.encode()))
        return Response.json({})

    def _handle_delete_index(self, req: Request) -> Response:
        name = req.vars["index"]
        self.holder.delete_index(name)
        self.broadcaster.send_sync(pb.DeleteIndexMessage(Index=name))
        return Response.json({})

    def _handle_patch_index_time_quantum(self, req: Request) -> Response:
        q = tq.parse_time_quantum(req.json().get("timeQuantum", ""))
        idx = self.holder.index(req.vars["index"])
        if idx is None:
            raise HTTPError(404, "index not found")
        idx.set_time_quantum(q)
        return Response.json({})

    # -- frame CRUD ----------------------------------------------------------

    def _handle_post_frame(self, req: Request) -> Response:
        index_name, frame_name = req.vars["index"], req.vars["frame"]
        opts = self._validate_options(req.json(), _VALID_FRAME_OPTIONS)
        idx = self.holder.index(index_name)
        if idx is None:
            raise HTTPError(404, "index not found")
        options = FrameOptions(
            row_label=opts.get("rowLabel", "rowID"),
            inverse_enabled=bool(opts.get("inverseEnabled", False)),
            cache_type=opts.get("cacheType", "lru"),
            cache_size=int(opts.get("cacheSize", 50000)),
            time_quantum=tq.parse_time_quantum(opts.get("timeQuantum", "")),
            fields=self._parse_fields_option(opts.get("fields")))
        try:
            idx.create_frame(frame_name, options)
        except FrameExistsError as e:
            raise HTTPError(409, str(e))
        self.broadcaster.send_sync(pb.CreateFrameMessage(
            Index=index_name, Frame=frame_name, Meta=options.encode()))
        return Response.json({})

    def _handle_delete_frame(self, req: Request) -> Response:
        index_name, frame_name = req.vars["index"], req.vars["frame"]
        idx = self.holder.index(index_name)
        if idx is None:
            return Response.json({})
        idx.delete_frame(frame_name)
        self.broadcaster.send_sync(pb.DeleteFrameMessage(
            Index=index_name, Frame=frame_name))
        return Response.json({})

    def _handle_patch_frame_time_quantum(self, req: Request) -> Response:
        q = tq.parse_time_quantum(req.json().get("timeQuantum", ""))
        frame = self.holder.frame(req.vars["index"], req.vars["frame"])
        if frame is None:
            raise HTTPError(404, "frame not found")
        frame.set_time_quantum(q)
        return Response.json({})

    def _handle_get_frame_views(self, req: Request) -> Response:
        frame = self.holder.frame(req.vars["index"], req.vars["frame"])
        if frame is None:
            raise HTTPError(404, "frame not found")
        return Response.json({"views": sorted(frame.views)})

    # -- BSI integer fields --------------------------------------------------

    @staticmethod
    def _parse_fields_option(raw) -> Optional[list[Field]]:
        if raw is None:
            return None
        if not isinstance(raw, list):
            raise HTTPError(400, "fields is not a list")
        out = []
        for o in raw:
            if not isinstance(o, dict) or "name" not in o:
                raise HTTPError(400, f"invalid field: {o!r}")
            for k in o:
                if k not in ("name", "min", "max"):
                    raise HTTPError(400, f"Unknown key: {k}:{o[k]}")
            try:
                out.append(Field(name=o["name"],
                                 min=int(o.get("min", 0)),
                                 max=int(o.get("max", 0))))
            except (TypeError, ValueError) as e:
                raise HTTPError(400, str(e))
        return out

    def _handle_get_frame_fields(self, req: Request) -> Response:
        frame = self.holder.frame(req.vars["index"], req.vars["frame"])
        if frame is None:
            raise HTTPError(404, "frame not found")
        return Response.json(
            {"fields": [f.to_json() for f in frame.fields()]})

    def _handle_post_frame_field(self, req: Request) -> Response:
        """Create one BSI field on an existing frame (body:
        {"min": N, "max": M}); re-broadcasts the frame meta so peers
        register it too."""
        index_name, frame_name = req.vars["index"], req.vars["frame"]
        frame = self.holder.frame(index_name, frame_name)
        if frame is None:
            raise HTTPError(404, "frame not found")
        body = req.json()
        for k in body:
            if k not in ("min", "max"):
                raise HTTPError(400, f"Unknown key: {k}:{body[k]}")
        try:
            field = Field(name=req.vars["field"],
                          min=int(body.get("min", 0)),
                          max=int(body.get("max", 0)))
        except (TypeError, ValueError) as e:
            raise HTTPError(400, str(e))
        frame.create_field(field)
        self.broadcaster.send_sync(pb.CreateFrameMessage(
            Index=index_name, Frame=frame_name,
            Meta=frame.options.encode()))
        return Response.json({})

    def _handle_post_field_import(self, req: Request) -> Response:
        """Bulk field-value import: protobuf ImportValueRequest (one
        slice, owner-checked like /import) or the JSON convenience
        form {"columns": [...], "values": [...]}. The JSON form
        requires EVERY touched slice to be owned by this host (412
        otherwise, nothing applied) — clients spanning owners must
        split per slice like cluster.client.import_field_values."""
        index_name, frame_name = req.vars["index"], req.vars["frame"]
        field_name = req.vars["field"]
        if req.content_type == _PROTOBUF:
            ireq = pb.ImportValueRequest.FromString(req.body())
            if (ireq.Index, ireq.Frame, ireq.Field) != (
                    index_name, frame_name, field_name):
                raise HTTPError(400, "import target mismatch")
            cols = np.fromiter(ireq.ColumnIDs, np.uint64,
                               len(ireq.ColumnIDs))
            vals = np.fromiter(ireq.Values, np.int64, len(ireq.Values))
            if self.cluster is not None and not self.cluster.owns_fragment(
                    self.host, index_name, ireq.Slice):
                raise HTTPError(412, f"host does not own slice"
                                     f" {self.host}-{index_name}"
                                     f" slice:{ireq.Slice}")
        else:
            body = req.json()
            cols = np.asarray(body.get("columns", []), dtype=np.uint64)
            vals = np.asarray(body.get("values", []), dtype=np.int64)
            if self.cluster is not None and len(cols):
                from .. import SLICE_WIDTH
                for slice in np.unique(cols // np.uint64(
                        SLICE_WIDTH)).tolist():
                    if not self.cluster.owns_fragment(
                            self.host, index_name, slice):
                        raise HTTPError(
                            412, f"host does not own slice"
                                 f" {self.host}-{index_name}"
                                 f" slice:{slice}")
        if len(cols) != len(vals):
            raise HTTPError(400, "import array length mismatch")
        frame = self.holder.frame(index_name, frame_name)
        if frame is None:
            raise HTTPError(404, "frame not found")
        if frame.field(field_name) is None:
            raise HTTPError(404, "field not found")
        frame.import_field_values(field_name, cols, vals)
        storage_wal.barrier_all()  # commit before the 200
        obs_metrics.IMPORT_BITS.labels("field_values").inc(len(cols))
        if req.content_type == _PROTOBUF:
            return Response.proto(pb.ImportResponse())
        return Response.json({})

    # -- query lifecycle (sched subsystem; docs/SCHEDULING.md) ---------------

    def _query_timeout_s(self, req: Request) -> Optional[float]:
        """Deadline budget for this request: ``X-Pilosa-Deadline``
        (remaining seconds — the cluster fan-out form, so a peer
        inherits what is LEFT of the coordinator's budget) wins over
        ``?timeout=`` (Go-style duration, the client-facing form),
        which wins over the configured default. None = unbounded."""
        hdr = self.environ_header(req, "HTTP_X_PILOSA_DEADLINE")
        if hdr:
            try:
                return max(float(hdr), 0.001)
            except ValueError:
                raise HTTPError(400, f"invalid X-Pilosa-Deadline: {hdr}")
        arg = req.query.get("timeout")
        if arg:
            from ..utils.config import parse_duration
            try:
                return max(parse_duration(arg), 0.001)
            except ValueError:
                raise HTTPError(400, f"invalid timeout: {arg}")
        return self.default_timeout_s or None

    @staticmethod
    def environ_header(req: Request, key: str) -> str:
        return req.environ.get(key, "")

    def _check_writable(self, lane: str) -> None:
        """Disk-full graceful degradation (fault.diskfull): while the
        node is write-unready after ENOSPC, writes answer 507 +
        Retry-After INSTEAD of being admitted into a doomed WAL
        append — reads and admin keep serving. The throttled probe
        inside write_ready() is also the auto-recovery path."""
        if lane != LANE_WRITE:
            return
        if fault_diskfull.write_ready():
            return
        st = fault_diskfull.default()
        raise HTTPError(
            507, "insufficient storage: node is write-unready after"
                 " ENOSPC (reads still serving; retry after space"
                 " frees)",
            headers=[("Retry-After", str(st.retry_after_s()))])

    def _admit(self, lane: str, ctx=None, tenant: str = ""):
        """Acquire an execution slot (None admission = unlimited, for
        bare test handlers). AdmissionFullError maps to 429 with the
        controller's Retry-After estimate — computed per lane, and per
        tenant-lane when the rejection was the tenant's own quota; a
        deadline that expires while QUEUED maps like any other expiry
        (504) — the query never occupied a slot."""
        self._check_writable(lane)
        if self.admission is None:
            return None
        try:
            return self.admission.acquire(lane, ctx,
                                          tenant=tenant or None)
        except AdmissionFullError as e:
            if self.stats is not None:
                self.stats.count("queriesRejected", 1)
            obs_metrics.ADMISSION_REJECTED.labels(lane).inc()
            if e.tenant:
                # Tenant-scoped shed: only the offending tenant 429s,
                # and its chargeback row says so. note_shed owns the
                # TENANT_SHED increment (one site, metric + registry
                # counter in lockstep); the direct inc covers bare
                # handlers with no registry.
                if self.tenants is not None:
                    self.tenants.note_shed(e.tenant, lane)
                else:
                    obs_metrics.TENANT_SHED.labels(e.tenant,
                                                   lane).inc()
            raise HTTPError(
                429, f"too many requests: {e}",
                headers=[("Retry-After",
                          str(int(e.retry_after_s)))])

    @contextlib.contextmanager
    def _admitted(self, lane: str, tenant: str = ""):
        """Slot-scoped admission for the non-query lanes (imports ride
        ``write``, schema mutations ``admin``), under the resolved
        tenant principal."""
        slot = self._admit(lane, tenant=tenant)
        try:
            yield
        finally:
            if slot is not None:
                slot.release()

    def _handle_debug_queries(self, req: Request) -> Response:
        out = {"queries": self.registry.active(),
               "slow": self.registry.slow_queries()}
        if self.admission is not None:
            out["admission"] = self.admission.snapshot()
        return Response.json(out)

    def _handle_debug_tenants(self, req: Request) -> Response:
        """The multi-tenant operator view (sched.tenants): per tenant
        — policy + effective weight, penalty-box state, in-flight /
        queued / served / shed / killed, cache residency, and the
        latest SLO burn rates. One merged row per tenant; the
        ``writeReady`` block rides along since a write-unready node
        sheds every tenant's writes at once."""
        rows: dict[str, dict] = {}

        def row(name: str) -> dict:
            return rows.setdefault(name, {})

        if self.tenants is not None:
            for name, snap in self.tenants.snapshot().items():
                row(name).update(snap)
        if self.admission is not None:
            adm = self.admission.snapshot()
            for name, snap in (adm.get("tenants") or {}).items():
                row(name).update(snap)
        if self.tenants is not None:
            # Unknown-but-active tenants (indexes with no [tenants.*]
            # entry) ride the default policy — their rows say which
            # policy actually governs them instead of showing nothing.
            for name, r in rows.items():
                if "policy" not in r:
                    score = self.tenants.penalty_score(name)
                    r["policy"] = self.tenants.policy(name).to_json()
                    r["effectiveWeight"] = round(
                        self.tenants.effective_weight(name), 4)
                    r["penaltyScore"] = round(score, 4)
                    r["inPenaltyBox"] = score > 0.0
                    r.setdefault("killed", 0)
                    r.setdefault("shed", 0)
        usage_fn = getattr(self.executor, "tenant_cache_usage", None)
        if callable(usage_fn):
            for name, snap in usage_fn().items():
                row(name)["cache"] = snap
        if self.tenant_slo is not None:
            for name, snap in self.tenant_slo.last().items():
                row(name)["slo"] = snap
        return Response.json({
            "tenants": rows,
            "writeReady": fault_diskfull.default().snapshot(),
        })

    def _handle_delete_query(self, req: Request) -> Response:
        """Cancel one query CLUSTER-WIDE: flip the local cancel flag
        (every executor layer checks it cooperatively) and broadcast a
        CancelQueryMessage so peers cancel the legs registered under
        the same id. ``?local=true`` limits to this node (the form the
        broadcast receiver itself applies, and a debugging escape
        hatch)."""
        qid = req.vars["qid"]
        n = self.registry.cancel_local(qid)
        if req.query.get("local") != "true":
            try:
                self.broadcaster.send_async(CancelQueryMessage(qid))
            except Exception as e:  # noqa: BLE001 - best-effort fan-out
                self.logger.printf("cancel broadcast failed: %s", e)
        return Response.json({"id": qid, "cancelled": n})

    # -- observability (obs subsystem; docs/OBSERVABILITY.md) ----------------

    def _handle_debug_slow_queries(self, req: Request) -> Response:
        """The slow-query log over HTTP: recent entries with per-stage
        timings and the query/trace id (PR 2's log was stderr-only —
        unusable without grepping server logs)."""
        return Response.json({"slow": self.registry.slow_queries()})

    def _handle_metrics(self, req: Request) -> Response:
        """Prometheus text exposition of the process registry. Only
        the CHEAP admission gauges refresh at scrape time; the heavy
        samplers (the O(fragments) holder walk, compile/residency
        snapshots) stay on the runtime collector's background cadence
        — a scrape must not get slower as the index grows."""
        # Content negotiation: an OpenMetrics scraper gets exemplars
        # (the trace/query id riding each latency bucket); everyone
        # else keeps the plain 0.0.4 exposition byte-for-byte (the
        # same body the federation legs scrape — one implementation).
        if "application/openmetrics-text" in req.accept:
            self._refresh_scrape_gauges()
            body = obs_metrics.default_registry().render(
                openmetrics=True).encode()
            return Response(
                200, body,
                "application/openmetrics-text; version=1.0.0;"
                " charset=utf-8")
        return Response(200, self._local_metrics_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")

    # -- fleet observability (obs.federate / obs.history / obs.sentinel) -----

    def _refresh_scrape_gauges(self) -> None:
        """Only the CHEAP admission gauges refresh at scrape time; the
        heavy samplers stay on the runtime collector's cadence."""
        if self.admission is not None:
            adm = self.admission.snapshot()
            obs_metrics.ADMISSION_IN_FLIGHT.set(adm.get("inFlight", 0))
            for lane, depth in (adm.get("queued") or {}).items():
                obs_metrics.ADMISSION_QUEUE_DEPTH.labels(lane).set(
                    depth)
            tif = getattr(self.admission, "tenant_in_flight", None)
            if callable(tif):
                now = tif()
                # Zero stale children first: the controller pops a
                # tenant's key when its count drains, so a gauge set
                # only from present keys would report the last busy
                # value forever.
                for labels, _child in \
                        obs_metrics.TENANT_INFLIGHT._label_dicts():
                    t = labels.get("tenant", "")
                    if t and t not in now:
                        obs_metrics.TENANT_INFLIGHT.labels(t).set(0)
                for tenant, n in now.items():
                    obs_metrics.TENANT_INFLIGHT.labels(tenant).set(n)
        usage_fn = getattr(self.executor, "tenant_cache_usage", None)
        if callable(usage_fn):
            usage = usage_fn()
            for labels, _child in \
                    obs_metrics.TENANT_CACHE_BYTES._label_dicts():
                t = labels.get("tenant", "")
                if t and t not in usage:
                    obs_metrics.TENANT_CACHE_BYTES.labels(t).set(0)
            for tenant, ent in usage.items():
                obs_metrics.TENANT_CACHE_BYTES.labels(tenant).set(
                    ent.get("bytes", 0))

    def _local_metrics_text(self) -> str:
        """The local 0.0.4 exposition exactly as /metrics serves it —
        also the body the /metrics/cluster local leg merges."""
        self._refresh_scrape_gauges()
        return obs_metrics.default_registry().render()

    def _partial_or_503(self, req: Request, missing: list[str],
                        headers: list) -> None:
        """The federation partial contract (docs/OBSERVABILITY.md):
        unreachable peers fail the request unless ``?partial=1``, in
        which case the merged answer is served and the missing nodes
        ride ``X-Pilosa-Partial-Nodes``."""
        if not missing:
            return
        if req.query.get("partial") != "1":
            raise HTTPError(
                503, "federation incomplete; unreachable nodes: "
                     + ",".join(missing)
                     + " (retry with ?partial=1 for a marked partial"
                       " rollup)")
        headers.append(("X-Pilosa-Partial-Nodes", ",".join(missing)))

    def _handle_metrics_cluster(self, req: Request) -> Response:
        """Cluster-wide Prometheus exposition: ONE bounded parallel
        scrape of every peer's /metrics (pooled clients — an open
        breaker fails a dead peer's leg fast), merged at query time:
        counters sum, histograms merge, gauges stay per-node labeled
        ``{node}``. The Monarch shape: history lives at the leaf,
        aggregation happens when the question is asked."""
        from ..obs import federate as obs_federate
        fed = self.federator

        def fetch(host: str) -> dict:
            client = fed.client_for(host)
            return obs_federate.parse_exposition(
                client.metrics_text(host=host,
                                    deadline_s=fed.peer_timeout_s))

        results, missing = fed.fan_out(
            fetch,
            lambda: obs_federate.parse_exposition(
                self._local_metrics_text()))
        headers: list = []
        self._partial_or_503(req, missing, headers)
        body = obs_federate.render_merged(
            obs_federate.merge_node_families(results)).encode()
        headers.append(("X-Pilosa-Federated-Nodes",
                        str(len(results))))
        return Response(200, body,
                        "text/plain; version=0.0.4; charset=utf-8",
                        headers=headers)

    def _handle_debug_cluster(self, req: Request) -> Response:
        """The fleet rollup: every node's local debug block (build
        info, placement epoch, breaker states, SLO burn, WAL flusher
        health, resize phase — the blackbox state, fleet-wide), plus
        a version-skew verdict. ``?local=1`` answers just this node's
        block (the internal leg the coordinator fans out)."""
        state_fn = getattr(self.status_handler, "local_debug_state",
                           None)

        def local() -> dict:
            if state_fn is not None:
                return state_fn()
            from ..obs.runtime import build_info
            return {"host": self.host, "build": build_info()}

        if req.query.get("local") == "1":
            return Response.json(local())
        fed = self.federator

        def fetch(host: str) -> dict:
            client = fed.client_for(host)
            return client.debug_cluster_local(
                host=host, deadline_s=fed.peer_timeout_s)

        results, missing = fed.fan_out(fetch, local)
        headers: list = []
        self._partial_or_503(req, missing, headers)
        versions: dict[str, str] = {}
        for host, block in results.items():
            versions[host] = str(
                (block.get("build") or {}).get("version", ""))
        # Gossip-learned builds cover nodes a scrape can't reach (the
        # rolling-restart window where skew matters most).
        local_block = results.get(self.host) or {}
        for host, build in (local_block.get("gossipBuilds")
                            or {}).items():
            versions.setdefault(host, str(build.get("version", "")))
        distinct = {v for v in versions.values() if v}
        return Response.json(
            {"coordinator": self.host,
             "nodes": results,
             "missing": missing,
             "versions": versions,
             "versionSkew": len(distinct) > 1},
            headers=headers)

    def _handle_metrics_history(self, req: Request) -> Response:
        """The on-disk metric history (obs.history) as JSON series:
        ``?family=`` selects a family (and its derived ``:p50``/
        ``:p99``/``:rate`` forms), ``?label=k=v[,k=v]`` filters,
        ``?window=``/``?step=`` pick the trailing window and
        resolution hint. ``?scope=cluster`` asks every node the same
        question and returns the series with per-node attribution."""
        from ..utils.config import parse_duration
        family = req.query.get("family", "")
        window_s, step_s = 3600.0, 0.0
        try:
            if req.query.get("window"):
                window_s = parse_duration(req.query["window"])
            if req.query.get("step"):
                step_s = parse_duration(req.query["step"])
        except ValueError:
            raise HTTPError(400, "invalid window/step")
        label_filter: dict = {}
        for pair in (req.query.get("label") or "").split(","):
            if not pair:
                continue
            k, sep, v = pair.partition("=")
            if not sep:
                raise HTTPError(400, f"invalid label filter: {pair!r}")
            label_filter[k] = v

        def local() -> dict:
            if self.history is None:
                return {"family": family, "series": [],
                        "enabled": False}
            return self.history.series(
                family, label_filter or None, window_s, step_s)

        if req.query.get("scope") != "cluster":
            out = local()
            out.setdefault("enabled", self.history is not None)
            return Response.json(out)
        fed = self.federator

        def fetch(host: str) -> dict:
            client = fed.client_for(host)
            return client.metrics_history(
                family=family, label=req.query.get("label", ""),
                window=req.query.get("window", ""),
                step=req.query.get("step", ""), host=host,
                deadline_s=fed.peer_timeout_s)

        results, missing = fed.fan_out(fetch, local)
        headers: list = []
        self._partial_or_503(req, missing, headers)
        series = []
        for host in sorted(results):
            for s in results[host].get("series") or []:
                series.append({**s, "node": host})
        return Response.json(
            {"family": family, "scope": "cluster",
             "windowS": window_s, "missing": missing,
             "series": series},
            headers=headers)

    def _handle_debug_capture(self, req: Request) -> Response:
        """Workload-capture status (obs.capture): mode, sampling,
        redaction policy, cursor, and the ring's byte accounting. A
        handler without a capture store answers disabled."""
        cap = self.capture
        if cap is None:
            return Response.json({"enabled": False, "mode": "off"})
        out = cap.status()
        out["enabled"] = cap.enabled
        return Response.json(out)

    def _handle_debug_capture_records(self, req: Request) -> Response:
        """Paged capture export: ``?since=<seq>`` (exclusive cursor)
        + ``?limit=`` pages the local ring oldest-first; the next
        page's cursor is the returned ``next``. ``?scope=cluster``
        fans out to every node and merges the streams by arrival
        wall-clock (obs.capture.merge_streams) — the merged form
        benchmarks/replay.py re-issues."""
        try:
            since = int(req.query.get("since", "0"))
            limit = int(req.query.get("limit", "500"))
        except ValueError:
            raise HTTPError(400, "invalid since/limit")

        def local() -> dict:
            cap = self.capture
            recs = cap.export(since=since, limit=limit) \
                if cap is not None else []
            return {"node": self.host, "records": recs,
                    "next": recs[-1]["seq"] if recs else since}

        if req.query.get("scope") != "cluster":
            return Response.json(local())
        fed = self.federator

        def fetch(host: str) -> dict:
            client = fed.client_for(host)
            return client.capture_records(
                since=since, limit=limit, host=host,
                deadline_s=fed.peer_timeout_s)

        results, missing = fed.fan_out(fetch, local)
        headers: list = []
        self._partial_or_503(req, missing, headers)
        merged = obs_capture.merge_streams(
            [r.get("records") or [] for r in results.values()])
        return Response.json(
            {"scope": "cluster", "records": merged,
             "nodes": sorted(results), "missing": missing},
            headers=headers)

    def _handle_debug_plans(self, req: Request) -> Response:
        """The bounded per-fingerprint plan store (plan.store): hit
        counts, latency p50/p99, est-vs-actual drift, and the last
        observed plan per normalized query shape; ``?limit=N`` bounds
        the listing (hottest fingerprints first). The planner's own
        state (decision totals, subresult cache) rides along."""
        try:
            limit = max(1, int(req.query.get("limit", "64")))
        except ValueError:
            raise HTTPError(400, "invalid limit")
        out = {"enabled": plan_record.enabled()}
        ex = self.executor
        store = getattr(ex, "plan_store", None)
        if store is not None:
            out.update(store.snapshot(limit=limit))
        planner = getattr(ex, "planner", None)
        if planner is not None:
            out["planner"] = planner.snapshot()
        return Response.json(out)

    def _handle_debug_sentinel(self, req: Request) -> Response:
        """The regression sentinel's state: recent findings, active
        conditions, and the rule thresholds (obs.sentinel)."""
        out: dict = {"enabled": self.sentinel is not None}
        if self.sentinel is not None:
            out.update(self.sentinel.snapshot())
        return Response.json(out)

    def _handle_debug_traces(self, req: Request) -> Response:
        """The in-memory ring by default; ``?source=disk`` lists the
        PERSISTED kept traces (tail sampler's segment ring — survives
        restarts), ``?reason=<keep-reason>`` filters either source,
        ``?limit=N&offset=M`` page through the listing (newest first,
        default limit 100) — so the disk ring is browsable without
        streaming every kept trace."""
        from ..obs import sampler as obs_sampler
        reason = req.query.get("reason", "")
        try:
            limit = max(1, int(req.query.get("limit", "100")))
            offset = max(0, int(req.query.get("offset", "0")))
        except ValueError:
            raise HTTPError(400, "invalid limit/offset")
        if req.query.get("source") == "disk":
            disk = self.sampler.disk if self.sampler is not None \
                else None
            traces: list[dict] = []
            matched = 0
            if disk is not None:
                for record in disk.scan():
                    if reason and record.get("reason") != reason:
                        continue
                    matched += 1
                    if matched <= offset:
                        continue
                    if len(traces) < limit:
                        traces.append(
                            obs_sampler.record_summary(record))
                        # Keep counting past the page: ``total`` tells
                        # the pager whether another page exists.
            out = {"enabled": self.tracer.enabled, "source": "disk",
                   "traces": traces, "offset": offset, "limit": limit,
                   "total": matched}
            if disk is not None:
                out["disk"] = disk.stats()
            return Response.json(out)
        traces = self.tracer.traces()
        if reason:
            traces = [t for t in traces if t.get("reason") == reason]
        return Response.json({"enabled": self.tracer.enabled,
                              "tail": self.sampler is not None,
                              "offset": offset, "limit": limit,
                              "total": len(traces),
                              "traces": traces[offset:offset + limit]})

    def _handle_debug_traces_summary(self, req: Request) -> Response:
        """Keep-reason roll-up over both stores: how many kept traces
        per reason in the in-memory ring and the on-disk segment ring
        — the browse-entry point before paging /debug/traces."""
        ring: dict[str, int] = {}
        for t in self.tracer.traces():
            r = t.get("reason") or "unkept"
            ring[r] = ring.get(r, 0) + 1
        disk_counts: dict[str, int] = {}
        out: dict = {"ring": ring, "disk": disk_counts}
        disk = self.sampler.disk if self.sampler is not None else None
        if disk is not None:
            for record in disk.scan():
                r = str(record.get("reason") or "unknown")
                disk_counts[r] = disk_counts.get(r, 0) + 1
            out["diskStats"] = disk.stats()
        return Response.json(out)

    # -- failpoint admin (fault subsystem; docs/FAULT_TOLERANCE.md) ----------

    def _handle_debug_failpoints(self, req: Request) -> Response:
        """The armed-failpoint schedule + the seed that replays it."""
        from ..fault import failpoints as fp
        return Response.json(fp.default().snapshot())

    def _handle_post_failpoints(self, req: Request) -> Response:
        """Arm/disarm failpoints at runtime. Body forms:
        ``{"site": "rpc.send", "spec": "error(0.5)"}`` or the bulk
        ``{"failpoints": {"rpc.send": "error", "wal.append": "off"}}``.
        Spec "off" disarms; an unknown site or malformed spec is 400
        with nothing armed."""
        from ..fault import failpoints as fp
        body = req.json()
        updates: dict = {}
        if "failpoints" in body:
            if not isinstance(body["failpoints"], dict):
                raise HTTPError(400, "failpoints is not a map")
            updates.update(body["failpoints"])
        if "site" in body:
            updates[body["site"]] = body.get("spec", "off")
        if not updates:
            raise HTTPError(400, "no failpoints given")
        reg = fp.default()
        # Validate everything before arming anything: a bulk update
        # must not half-apply.
        for site, spec in updates.items():
            if site not in fp.SITES:
                raise HTTPError(400, f"unknown failpoint site: {site}")
            try:
                fp.parse_spec(site, str(spec))
            except ValueError as e:
                raise HTTPError(400, str(e))
        for site, spec in updates.items():
            reg.arm(site, str(spec))
            self.logger.printf("failpoint %s: %s (seed %d)", site,
                               spec or "off", reg.seed)
        return Response.json(reg.snapshot())

    def _handle_debug_integrity(self, req: Request) -> Response:
        """Storage-integrity state: quarantined fragments (what, why,
        since when), scrub pass progress/totals, repair totals, and
        the per-fragment footer coverage summary (how much of the
        fleet's bytes actually carry checksums — vintage files read
        fine but scrub blind)."""
        covered = vintage = 0
        iter_fragments = getattr(self.holder, "iter_fragments", None)
        for frag in (iter_fragments() if iter_fragments else ()):
            storage = getattr(frag, "storage", None)
            if storage is not None and getattr(storage, "footer",
                                               None) is not None:
                covered += 1
            else:
                vintage += 1
        registry = getattr(self.holder, "quarantine", None)
        out: dict = {
            "quarantined": registry.entries() if registry is not None
            else [],
            "coverage": {"footered": covered, "vintage": vintage}}
        if self.scrubber is not None:
            out["scrub"] = self.scrubber.state()
        if self.repairer is not None:
            out["repair"] = self.repairer.state()
        return Response.json(out)

    def _handle_debug_tier(self, req: Request) -> Response:
        """Tiered-storage state (pilosa_tpu.tier): per-tier fragment
        and byte counts, resident bytes vs budget/watermarks,
        per-tenant residency, transition totals, blocked cold fetches,
        and the blob store summary. ``?entries=1`` appends the
        per-fragment ledger (optionally filtered ``&tier=cold``);
        ``?pass=1`` runs one manager pass inline and includes its
        summary (operator spot checks, chaos tests)."""
        if self.tier is None:
            return Response.json({"enabled": False})
        out = self.tier.state()
        if req.query.get("pass") == "1":
            out["pass"] = self.tier.pass_once()
        if req.query.get("entries") == "1":
            out["entries"] = self.tier.entries(
                req.query.get("tier", ""))[:1024]
        return Response.json(out)

    def _handle_post_integrity_scrub(self, req: Request) -> Response:
        """Trigger an immediate scrub pass. ``?sync=1`` runs the pass
        inline and returns its summary (operator spot checks, chaos
        tests); the default just wakes the background thread."""
        if self.scrubber is None:
            raise HTTPError(503, "no scrubber on this node")
        if req.query.get("sync") == "1":
            return Response.json(self.scrubber.pass_once())
        self.scrubber.trigger()
        return Response.json({"triggered": True})

    def _handle_debug_trace(self, req: Request) -> Response:
        """One trace as Chrome trace-event JSON (open in perfetto);
        ``?format=spans`` returns the raw span list instead. A miss in
        the in-memory ring falls back to the tail sampler's disk ring
        (``?source=disk`` skips the ring and goes straight there), so
        a persisted trace stays addressable after a restart."""
        trace = None
        if req.query.get("source") != "disk":
            trace = self.tracer.get(req.vars["qid"])
        if trace is None and self.sampler is not None \
                and self.sampler.disk is not None:
            from ..obs import sampler as obs_sampler
            qid = req.vars["qid"]
            for record in self.sampler.disk.scan():
                if record.get("id") == qid:
                    trace = obs_sampler.record_to_trace(record)
                    break
        if trace is None:
            raise HTTPError(404, "trace not found")
        if req.query.get("format") == "spans":
            return Response.json(
                {"id": trace.id, "reason": trace.keep_reason,
                 "spans": [s.to_json() for s in trace.spans()]})
        return Response.json(trace.to_chrome())

    def _handle_debug_blackbox(self, req: Request) -> Response:
        """Flight-recorder state: ring/dump stats plus the most recent
        snapshots (``?limit=N``, default 8) and the watchdog's trip
        record — the read side of docs/OBSERVABILITY.md's blackbox."""
        try:
            limit = max(0, int(req.query.get("limit", "8")))
        except ValueError:
            raise HTTPError(400, "invalid limit")
        out: dict = {"enabled": self.blackbox is not None}
        if self.blackbox is not None:
            out.update(self.blackbox.stats())
            snaps = []
            if limit:
                for rec in self.blackbox.ring.scan():
                    snaps.append(rec)
                    if len(snaps) >= limit:
                        break
            out["recent"] = snaps
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.snapshot()
        return Response.json(out)

    def _handle_post_blackbox_dump(self, req: Request) -> Response:
        """Force a full flight-recorder dump (cause ``api``) — the
        operator's "capture everything NOW" button."""
        if self.blackbox is None:
            raise HTTPError(404, "no blackbox recorder")
        path = self.blackbox.dump("api")
        if path is None:
            raise HTTPError(500, "blackbox dump failed")
        return Response.json({"dumped": path})

    # -- query ---------------------------------------------------------------

    def _handle_post_query(self, req: Request) -> Response:
        index_name = req.vars["index"]
        proto_out = _PROTOBUF in req.accept

        def error_resp(status, msg, headers=None):
            if proto_out:
                return Response.proto(pb.QueryResponse(Err=msg), status,
                                      headers=headers)
            return Response.json({"error": msg}, status,
                                 headers=headers)

        # Read request (handler.go:811-870).
        if req.content_type == _PROTOBUF:
            preq = pb.QueryRequest.FromString(req.body())
            query_str = preq.Query
            slices = list(preq.Slices)
            column_attrs = preq.ColumnAttrs
            remote = preq.Remote
        else:
            query_str = req.body().decode()
            try:
                slices = [int(s)
                          for s in req.query.get("slices", "").split(",")
                          if s != ""]
            except ValueError:
                return error_resp(400, "invalid slice argument")
            column_attrs = req.query.get("columnAttrs") == "true"
            remote = False

        import time as time_mod
        parse_wall = time_mod.time()
        parse_t0 = time_mod.perf_counter()
        try:
            query = pql.parse(query_str)
        except PilosaError as e:
            return error_resp(400, str(e))
        parse_s = time_mod.perf_counter() - parse_t0

        if req.query.get("plan") == "1" and not remote:
            # EXPLAIN-only: plan the query without executing. The
            # response mirrors ?profile=1's plan block with empty
            # results — estimates and decisions but no actuals.
            try:
                tree = self.executor.explain(index_name, query,
                                             slices or None)
            except PilosaError as e:
                return error_resp(400, str(e))
            return Response.json({"results": [], "plan": tree})

        # Lifecycle: classify the lane, build the QueryContext (remote
        # legs inherit the coordinator's id + remaining budget via
        # headers), admit, register for /debug/queries visibility.
        from ..executor import _WRITE_CALLS, ExecOptions
        lane = (LANE_WRITE
                if any(c.name in _WRITE_CALLS for c in query.calls)
                else LANE_READ)
        # Tenant principal (sched.tenants): the X-Pilosa-Tenant header
        # on forwarded legs (the coordinator's principal), the index
        # otherwise — resolved BEFORE admission so the stride/quota
        # accounting and the 429 counters charge the right tenant.
        tenant = (self.environ_header(req, "HTTP_X_PILOSA_TENANT")
                  or index_name)
        ctx = QueryContext(
            pql=query_str, index=index_name, lane=lane,
            timeout_s=self._query_timeout_s(req),
            id=self.environ_header(req, "HTTP_X_PILOSA_QUERY_ID") or None,
            remote=remote, node=self.host, tenant=tenant)
        ctx.stages["parse"] = parse_s
        # ?profile=1 asks for EXPLAIN ANALYZE: the executor fills in
        # exact per-node actual cardinalities (it pays one count()
        # walk per planned call) on top of the always-on wall times.
        ctx.profile = req.query.get("profile") == "1"
        # Resource accounting (obs.accounting): every query gets a cost
        # ledger — container ops by kind, device bytes, compile ms, RPC
        # bytes — unless accounting is switched off. Remote legs keep
        # their own ledger AND piggyback it back for stitching.
        if self.accounting:
            obs_accounting.attach(ctx, node=self.host)
        # Slow-query kill policy: when this tenant's policy has cost
        # ceilings, every ctx.check() (the stage boundaries, on EVERY
        # node this query touches) compares the live ledger against
        # them — a breach kills cluster-wide via the cancel broadcast.
        if self.tenants is not None:
            self.tenants.install(ctx)
        # Distributed tracing (obs.trace): traced when this node's
        # tracer is on, the request opts in (?trace=1), or a
        # coordinator asked this forwarded leg to trace itself
        # (X-Pilosa-Trace) — remote legs piggyback their spans back on
        # the response for stitching. With tail sampling wired
        # (obs.sampler — the server default), EVERY query buffers
        # spans and the keep decision runs at query end instead.
        trace = None
        trace_requested = (
            self.tracer.enabled or req.query.get("trace") == "1"
            or (remote and self.environ_header(
                req, "HTTP_X_PILOSA_TRACE") == "1"))
        if trace_requested or self.sampler is not None:
            trace = self.tracer.start(ctx, node=self.host)
            trace.add_span("parse", parse_wall, parse_s)
        # Query latency label set: one call name when the query is
        # homogeneous, "multi" otherwise (bounded cardinality).
        call_names = {c.name for c in query.calls}
        call_label = call_names.pop() if len(call_names) == 1 else "multi"

        def _resp_headers() -> list:
            # The id rides every response; a traced REMOTE leg also
            # piggybacks its spans — on error responses too, since a
            # failing leg is exactly the one the coordinator's
            # stitched trace must not be missing. The cost ledger rides
            # the same way: a compact roll-up on EVERY response
            # (X-Pilosa-Stats) and, on remote legs, the full per-node
            # tree (X-Pilosa-Cost) for the coordinator to stitch.
            hs = [("X-Pilosa-Query-Id", ctx.id)]
            if remote:
                # Generation tokens ride every internal leg's response
                # (cluster.generations): the coordinator's map learns
                # this node's current per-fragment (uid, generation)
                # state for the served slices — the fact its remote
                # result-cache keys validate against.
                gh = self._generations_header(index_name, slices)
                if gh is not None:
                    hs.append(gh)
            if trace is not None and remote:
                hs.append((obs_trace.SPANS_HEADER, trace.spans_json()))
            if ctx.cost is not None:
                hs.append((obs_accounting.STATS_HEADER,
                           json.dumps(ctx.cost.summary(),
                                      separators=(",", ":"))))
                if remote:
                    hs.append((obs_accounting.COST_HEADER,
                               ctx.cost.wire_json(dict(ctx.stages))))
            if ctx.plan is not None and remote:
                # Remote legs piggyback their plan for the
                # coordinator to stitch (the cost-tree contract).
                hs.append((plan_record.PLAN_HEADER,
                           ctx.plan.wire_json()))
            if ctx.result_digest:
                # The canonical result digest (obs.capture): set at
                # query end on success, so error responses (digest
                # would be meaningless) skip the header.
                hs.append((obs_capture.DIGEST_HEADER,
                           ctx.result_digest))
            return hs
        # Register BEFORE admission so queued queries are visible at
        # /debug/queries and cancellable while they wait (a DELETE or
        # an expiring deadline dequeues them without ever holding a
        # slot). Forwarded legs were admitted once at their
        # coordinator; re-admitting them here could deadlock a
        # saturated cluster (every node holding a slot while waiting
        # on a peer's slot).
        slot = None
        err: Optional[BaseException] = None
        exec_opt = None
        self.registry.register(ctx)
        try:
            if not remote:
                with ctx.stage("admission"):
                    slot = self._admit(lane, ctx)
            ctx.state = "running"
            # Degraded reads (?partial=1, fault subsystem): slices
            # with no reachable replica are skipped and reported in
            # X-Pilosa-Partial instead of failing the whole query.
            # Coordinator-only: a forwarded leg answers strictly so
            # its coordinator decides the degradation policy.
            exec_opt = ExecOptions(
                remote=remote,
                pod_local=req.query.get("podLocal") == "true",
                ctx=ctx,
                partial=(req.query.get("partial") == "1"
                         and not remote),
                missing_slices=[])
            with ctx.stage("execute"):
                results = self.executor.execute(
                    index_name, query, slices or None, exec_opt)
            if lane == LANE_WRITE:
                # Commit barrier before the ack: every mutation this
                # query applied has its WAL record durable (per the
                # fsync policy) when the response goes out. Concurrent
                # write queries coalesce into one leader flush per
                # touched WAL (storage.wal group commit). Bound as the
                # thread's current query so a wal.append failpoint hit
                # during THIS query's barrier flags its context for
                # the tail sampler (the barrier covers its records).
                with ctx.stage("commit"), sched_context.use(ctx):
                    storage_wal.barrier_all()
        except HTTPError as e:  # 429 from _admit / 507 write-unready
            err = e
            raise
        except QueryDeadlineError as e:
            err = e
            return error_resp(504, str(e),
                              headers=_resp_headers())
        except QueryKilledError as e:
            # Cost-policy kill (sched.tenants): a DISTINCT status so
            # clients tell a budget kill from an operator cancel, with
            # the policy named in the header contract.
            err = e
            hs = _resp_headers()
            hs.append((KILLED_BY_HEADER, KILL_POLICY))
            return error_resp(402, str(e), headers=hs)
        except QueryCancelledError as e:
            err = e
            return error_resp(409, str(e),
                              headers=_resp_headers())
        except storage_wal.WalError as e:
            # A commit barrier that failed on a FULL disk answers 507
            # + Retry-After (fault.diskfull already flipped the node
            # write-unready at the WAL site) — a retryable condition,
            # not a 500 crash-loop. Any other WAL failure stays a 500.
            err = e
            if not fault_diskfull.write_ready(probe=False):
                hs = _resp_headers()
                hs.append(("Retry-After", str(
                    fault_diskfull.default().retry_after_s())))
                return error_resp(
                    507, "insufficient storage: write not durable"
                         f" ({e})", headers=hs)
            self.logger.printf("query commit barrier failed: %s", e)
            return error_resp(500, str(e), headers=_resp_headers())
        except SliceUnavailableError as e:
            # No reachable (or trustworthy — storage quarantine) copy
            # of a touched slice anywhere: a 503 retryable condition,
            # not a 400 client error. ``?partial=1`` keeps the
            # degraded-answer contract instead (X-Pilosa-Partial).
            err = e
            return error_resp(503, f"slice unavailable: {e}",
                              headers=_resp_headers())
        except PilosaError as e:
            err = e
            return error_resp(400, str(e), headers=_resp_headers())
        except Exception as e:  # noqa: BLE001 - surfaced in response
            err = e
            self.logger.printf("query error: index=%s query=%.120s: %s",
                               index_name, query_str, e)
            return error_resp(500, str(e), headers=_resp_headers())
        finally:
            if slot is not None:
                slot.release()
            if isinstance(err, HTTPError):
                status = err.status
            elif isinstance(err, QueryDeadlineError):
                status = 504
            elif isinstance(err, QueryKilledError):
                status = 402
            elif isinstance(err, QueryCancelledError):
                status = 409
            elif (isinstance(err, storage_wal.WalError)
                  and not fault_diskfull.write_ready(probe=False)):
                status = 507
            elif isinstance(err, SliceUnavailableError):
                status = 503
            elif isinstance(err, PilosaError):
                status = 400
            elif err is not None:
                status = 500
            else:
                status = 200
            # Tail-sampling keep decision (obs.sampler), BEFORE the
            # registry finishes the context so the slow-log entry can
            # cross-link the kept trace (traceKept / traceKeepReason).
            # Explicitly-requested traces ([trace] enabled, ?trace=1,
            # a coordinator-asked leg) keep unconditionally.
            if trace is not None:
                if ctx.cost is not None:
                    # Cost roll-up as span args: the perfetto view of
                    # this query carries its resource ledger.
                    trace.add_span("query_cost", ctx.started_wall, 0.0,
                                   tags=ctx.cost.summary())
                if ctx.plan is not None and (ctx.plan.sample
                                             or ctx.plan.analyze):
                    # Kept traces carry the plan fingerprint and the
                    # decision summary — a slow trace names the plan
                    # that produced it (/debug/plans has the tree).
                    # Sampled out on most plan-memo hits (the ≤2%
                    # overhead budget); fresh plans always carry it.
                    tags = {"fingerprint": ctx.plan.fingerprint}
                    tags.update(ctx.plan.decision_summary())
                    trace.add_span("query_plan", ctx.started_wall,
                                   0.0, tags=tags)
                reason = None
                if self.sampler is not None:
                    partial = bool(exec_opt is not None
                                   and exec_opt.partial
                                   and exec_opt.missing_slices)
                    reason = self.sampler.decide(
                        ctx, err=err, status=status, partial=partial)
                # Explicit keeps: [trace] enabled and ?trace=1 always;
                # a coordinator-asked remote leg only when no sampler
                # runs here — with tail sampling on, EVERY leg carries
                # the header (the spans piggyback back either way), so
                # auto-keeping would persist every healthy remote leg
                # on every peer. The peer's own tail decision keeps
                # the interesting legs.
                if reason is None and (
                        self.tracer.enabled
                        or req.query.get("trace") == "1"
                        or (trace_requested and remote
                            and self.sampler is None)):
                    reason = "requested"
                if trace.keep_reason:
                    # Already force-kept mid-flight (watchdog): it IS
                    # in the ring/disk — report that, don't re-enter,
                    # whatever the end-of-query decision said.
                    reason = trace.keep_reason
                elif reason is not None:
                    # keep() claims atomically: a watchdog force-keep
                    # racing this exact window wins and we report ITS
                    # reason instead of double-entering the ring/disk.
                    if self.tracer.keep(trace, reason=reason):
                        if self.sampler is not None:
                            self.sampler.persist(trace, reason,
                                                 ctx=ctx)
                    else:
                        reason = trace.keep_reason or reason
                ctx.trace_kept = reason is not None
                ctx.keep_reason = reason or ""
            if (ctx.plan is not None and not remote
                    and (ctx.plan.sample or ctx.plan.analyze)):
                # Per-fingerprint aggregation behind /debug/plans —
                # coordinator-only so a fleet of remote legs does not
                # multiply one query into N rows. Fresh plans and a
                # 1-in-16 slice of memo hits record (an unbiased
                # duration reservoir); the rest skip the bookkeeping.
                est = actual = None
                for root in ctx.plan.roots:
                    if (root.est_rows is not None
                            and root.actual_rows is not None):
                        est, actual = root.est_rows, root.actual_rows
                        break
                try:
                    self.executor.plan_store.record(
                        ctx.plan.fingerprint, ctx.plan.to_tree,
                        ctx.elapsed(), pql=query_str,
                        est_rows=est, actual_rows=actual)
                except Exception:  # noqa: BLE001 - observability only
                    pass
            # Canonical result digest (obs.capture): the value of
            # X-Pilosa-Result-Digest and the shadow-diff comparison
            # key. Coordinator-only (a remote leg's partial results
            # are not a client-visible answer) and success-only.
            if err is None and not remote:
                try:
                    ctx.result_digest = obs_capture.result_digest(
                        [codec.result_to_json(r) for r in results])
                except Exception:  # noqa: BLE001 - observability only
                    pass
            # Workload capture (obs.capture): append the replayable
            # record BEFORE registry.finish so the slow-log entry
            # cross-links the capture id. Disabled mode costs one
            # attribute read (the nop path the overhead guard proves).
            cap = self.capture
            if (cap is not None and cap.enabled and not remote
                    and cap.should_capture(ctx.lane)):
                opts = {}
                if req.query.get("timeout"):
                    opts["timeout"] = req.query["timeout"]
                if req.query.get("partial") == "1":
                    opts["partial"] = True
                ctx.capture_id = cap.add(
                    "query", query_str, index_name, ctx.tenant,
                    ctx.lane, ctx.id, status, ctx.elapsed(),
                    digest=ctx.result_digest,
                    plan=(ctx.plan.fingerprint
                          if ctx.plan is not None else ""),
                    opts=opts or None,
                    wall=ctx.started_wall, mono=ctx.started)
            self.registry.finish(ctx, error=err)
            # Latency histogram + outcome counter, labeled by call
            # type / lane / status (obs.metrics) — recorded for every
            # outcome, including 429/504/409 error returns.
            labels = (call_label, ctx.lane, str(status))
            # The latency observation carries the query id as an
            # OpenMetrics exemplar: "p99 regressed" comes with a trace
            # id to open (rendered only on OpenMetrics scrapes).
            obs_metrics.QUERY_SECONDS.labels(*labels).observe(
                ctx.elapsed(), exemplar={"trace_id": ctx.id})
            obs_metrics.QUERIES_TOTAL.labels(*labels).inc()
            # Per-tenant chargeback (sched.tenants): client-facing
            # latency/outcome on the COORDINATOR only (a remote leg
            # re-observing would double count the fleet roll-up);
            # cost units on EVERY node — each node's ledger holds its
            # own local work, so per-node increments sum correctly.
            tlabel = ctx.tenant or "default"
            if not remote:
                obs_metrics.TENANT_QUERY_SECONDS.labels(
                    tlabel).observe(ctx.elapsed())
                obs_metrics.TENANT_QUERIES.labels(
                    tlabel, str(status)).inc()
            cost = ctx.cost
            if cost is not None:
                rpc_b = sum(v["bytesOut"] + v["bytesIn"]
                            for v in cost.rpc.values())
                for resource, amount in (
                        ("container_ops",
                         sum(cost.container_ops.values())),
                        ("words_scanned", cost.words_scanned),
                        ("bits_written", cost.bits_written),
                        ("device_bytes", cost.device_bytes),
                        ("rpc_bytes", rpc_b),
                        ("queue_wait_ms",
                         int(ctx.stages.get("admission", 0.0) * 1e3)),
                        # Wall microseconds: the universal chargeback
                        # unit — kernel-fused paths can legitimately
                        # do zero container algebra, but every leg
                        # burns wall time on its node.
                        ("wall_us", int(ctx.elapsed() * 1e6))):
                    if amount:
                        obs_metrics.TENANT_COST_UNITS.labels(
                            tlabel, resource).inc(amount)

        # Optional column-attribute join (handler.go:208-227).
        attr_sets = []
        if column_attrs:
            idx = self.holder.index(index_name)
            arrs = [r.bits() for r in results if isinstance(r, Bitmap)]
            ids = (np.unique(np.concatenate(arrs)).tolist()
                   if arrs else [])
            for id in ids:
                attrs = idx.column_attr_store.attrs(id)
                if attrs:
                    attr_sets.append((id, attrs))

        # The id rides every response so clients can correlate with
        # /debug/queries (and DELETE a long-running follow-up); remote
        # legs piggyback spans (the encode span below is local-only).
        qid_hdr = _resp_headers()
        if exec_opt.partial and exec_opt.missing_slices:
            # The degraded-result contract: the client SEES which
            # slices are missing from these results.
            qid_hdr.append(("X-Pilosa-Partial", ",".join(
                str(s) for s in sorted(exec_opt.missing_slices))))
            obs_metrics.PARTIAL_RESULTS.inc()
        with ctx.stage("encode"):
            if proto_out:
                return Response.proto(
                    codec.encode_query_response(results, attr_sets),
                    headers=qid_hdr)
            payload = codec.query_response_json(results, attr_sets)
            if req.query.get("profile") == "1" and ctx.cost is not None:
                # EXPLAIN ANALYZE for PQL: the merged per-node,
                # per-stage cost tree rides inline with the results
                # (remote legs' ledgers arrived as stitched children).
                payload["profile"] = ctx.cost.to_tree(dict(ctx.stages))
            if req.query.get("profile") == "1" and ctx.plan is not None:
                # The chosen plan with per-node est-vs-actual rows and
                # wall time, remote legs stitched in from
                # X-Pilosa-Plan headers.
                payload["plan"] = ctx.plan.to_tree()
            return Response.json(payload, headers=qid_hdr)

    # -- attr diff (anti-entropy) --------------------------------------------

    def _attr_diff(self, store, req: Request) -> Response:
        body = req.json()
        blocks = codec.blocks_from_json(body.get("blocks", []))
        attrs = {}
        for block_id in diff_blocks(store.blocks(), blocks):
            for id, m in store.block_data(block_id).items():
                attrs[str(id)] = m
        return Response.json({"attrs": attrs})

    def _handle_index_attr_diff(self, req: Request) -> Response:
        idx = self.holder.index(req.vars["index"])
        if idx is None:
            raise HTTPError(404, "index not found")
        return self._attr_diff(idx.column_attr_store, req)

    def _handle_frame_attr_diff(self, req: Request) -> Response:
        frame = self.holder.frame(req.vars["index"], req.vars["frame"])
        if frame is None:
            raise HTTPError(404, "frame not found")
        return self._attr_diff(frame.row_attr_store, req)

    # -- import / export -----------------------------------------------------

    def _handle_post_import(self, req: Request) -> Response:
        # Protobuf endpoint at reference parity (handler.go:896-906),
        # plus the raw-array sidecar format our own client negotiates
        # (proto/rawimport.py): protobuf varint-decodes every u64,
        # which was the measured wire-import bound; raw decodes as
        # np.frombuffer views.
        from ..proto import rawimport
        if req.content_type not in (_PROTOBUF, rawimport.CONTENT_TYPE):
            raise HTTPError(415, "Unsupported media type")
        # Strict 406 BEFORE body parsing, at reference parity for
        # protobuf callers; the raw sidecar also tolerates its own
        # type as Accept (pod-internal requests mirror Content-Type
        # into Accept) — the response is protobuf either way.
        if req.accept != _PROTOBUF and not (
                req.content_type == rawimport.CONTENT_TYPE
                and req.accept == rawimport.CONTENT_TYPE):
            raise HTTPError(406, "Not acceptable")
        # Per-stage instrumentation (VERDICT r5 weak #3: "decode and
        # apply serialize" was prose — now the decode-vs-apply split is
        # a recorded histogram plus cost fields on the response).
        import time as time_mod
        decode_t0 = time_mod.perf_counter()
        wire_bytes = 0
        positions = None
        if req.content_type == rawimport.CONTENT_TYPE:
            body = req.body()
            wire_bytes = len(body)
            try:
                (index_name, frame_name, slice, rows, cols,
                 ts_ns, positions) = rawimport.decode(body)
            except ValueError as e:
                raise HTTPError(400, str(e))
        elif req.content_type == _PROTOBUF:
            body = req.body()
            wire_bytes = len(body)
            ireq = pb.ImportRequest.FromString(body)
            index_name, frame_name, slice = \
                ireq.Index, ireq.Frame, ireq.Slice
            n = len(ireq.RowIDs)
            rows = np.fromiter(ireq.RowIDs, np.uint64, n)
            cols = np.fromiter(ireq.ColumnIDs, np.uint64,
                               len(ireq.ColumnIDs))
            ts_ns = (np.fromiter(ireq.Timestamps, np.int64,
                                 len(ireq.Timestamps))
                     if ireq.Timestamps else None)
        decode_s = time_mod.perf_counter() - decode_t0
        obs_metrics.IMPORT_STAGE_SECONDS.labels("decode").observe(
            decode_s)
        if positions is not None:
            # Presorted positions form (rawimport v2): the sort is the
            # CLIENT's job, so sortedness is a contract, not a hint —
            # add_many would silently re-sort, but an unsorted body
            # means a broken client and the 400 keeps the wire
            # contract honest. One vectorized strictness pass.
            if len(positions) > 1 and not bool(
                    np.all(positions[:-1] < positions[1:])):
                raise HTTPError(
                    400, "raw-import positions not sorted-unique")
            n_bits = len(positions)
        elif len(rows) != len(cols) or (
                ts_ns is not None and len(ts_ns) != len(rows)):
            raise HTTPError(400, "import array length mismatch")
        else:
            n_bits = len(rows)
        if self.cluster is not None and not self.cluster.owns_fragment(
                self.host, index_name, slice):
            raise HTTPError(412, f"host does not own slice"
                                 f" {self.host}-{index_name}"
                                 f" slice:{slice}")
        idx = self.holder.index(index_name)
        if idx is None:
            raise HTTPError(404, "index not found")
        frame = idx.frame(frame_name)
        if frame is None:
            raise HTTPError(404, "frame not found")
        import datetime as dt
        if ts_ns is not None and ts_ns.any():
            timestamps = [
                dt.datetime.fromtimestamp(ts / 1e9, dt.timezone.utc)
                .replace(tzinfo=None) if ts else None
                for ts in ts_ns.tolist()]
        else:
            # A non-empty ALL-ZERO Timestamps list collapses to
            # timestamps=None here, where the reference (handler.go)
            # builds a per-bit slice of nils. End state is identical —
            # frame.import_bits treats a per-bit None exactly like no
            # timestamp — but any future PER-BIT timestamp semantics
            # must re-check this edge (ADVICE r5 #4).
            timestamps = None
        pod_view = req.query.get("podView")
        if pod_view is not None and pod_view not in ("standard", "inverse"):
            raise HTTPError(400, f"invalid podView: {pod_view}")
        if positions is not None and (
                frame.inverse_enabled or pod_view == "inverse"
                or (self.pod is not None and self.pod.is_coordinator
                    and pod_view is None)):
            # The positions form is the standard-view fast lane; a
            # frame that also needs the inverse transpose (or a pod
            # split by row slice) wants (row, col) pairs —
            # reconstruct them (three vector ops) and take the
            # generic path below.
            from .. import SLICE_WIDTH
            W = np.uint64(SLICE_WIDTH)
            rows = positions // W
            cols = np.uint64(slice) * W + (positions % W)
            positions = None
        apply_t0 = time_mod.perf_counter()
        # Pipeline depth: concurrent /import handlers in their apply
        # stage. >1 means a later block's decode (another connection
        # thread) overlapped this apply — the pipelined wire-import
        # path observable as a gauge.
        obs_metrics.IMPORT_PIPELINE_DEPTH.inc()
        try:
            with _APPLY_GATE:
                if positions is not None:
                    # Writable copy: frombuffer views of the request
                    # body are read-only, and container merges may
                    # keep slices of the batch vector alive — aliasing
                    # those to the HTTP body would pin whole request
                    # buffers in the holder.
                    frame.import_slice_positions(slice,
                                                 np.array(positions))
                elif (self.pod is not None and self.pod.is_coordinator
                        and pod_view is None):
                    self._pod_import(index_name, frame_name, slice,
                                     rows, cols, ts_ns, idx, frame,
                                     timestamps)
                else:
                    frame.import_bits(rows, cols, timestamps,
                                      views=pod_view)
        finally:
            obs_metrics.IMPORT_PIPELINE_DEPTH.inc(-1)
        apply_s = time_mod.perf_counter() - apply_t0
        obs_metrics.IMPORT_STAGE_SECONDS.labels("apply").observe(
            apply_s)
        # Commit barrier before the 200: fragment import lanes barrier
        # their own WAL, but a time-view fan-out (or a pod split) may
        # leave sibling fragments' records pending — the ack covers
        # them all, coalesced with concurrent imports' barriers.
        storage_wal.barrier_all()
        obs_metrics.IMPORT_BITS.labels("bits").inc(n_bits)
        # Workload capture (obs.capture): the import ack is a state
        # mutation replay must reproduce, so writes record in every
        # non-off mode (should_capture never samples the write lane).
        cap = self.capture
        if cap is not None and cap.enabled \
                and cap.should_capture(LANE_WRITE):
            tenant = (self.environ_header(req, "HTTP_X_PILOSA_TENANT")
                      or index_name)
            cap.add("import", "", index_name, tenant, LANE_WRITE, "",
                    200, decode_s + apply_s,
                    bits=n_bits, slice=int(slice),
                    frame=frame_name)
        # Cost fields ride the response: decode vs apply wall time and
        # the wire/bit volumes (the snapshot leg, when one triggers,
        # lands in the same histogram from the fragment).
        stats = json.dumps(
            {"decodeMs": round(decode_s * 1e3, 3),
             "applyMs": round(apply_s * 1e3, 3),
             "wireBytes": wire_bytes, "bits": n_bits},
            separators=(",", ":"))
        hs = [(obs_accounting.STATS_HEADER, stats)]
        # The import ack carries the written slice's fresh generation
        # tokens: an importing coordinator's map invalidates its
        # cached results for this slice on the ack itself, no extra
        # round trip (cluster.generations wire contract).
        gh = self._generations_header(index_name, [slice])
        if gh is not None:
            hs.append(gh)
        return Response.proto(pb.ImportResponse(), headers=hs)

    def _pod_import(self, index_name, frame_name, slice, rows, cols,
                    ts_ns, idx, frame, timestamps) -> None:
        """Split an import within the pod (parallel.pod placement):
        standard + time views live on the owner of the column slice;
        inverse views group by row slice, one leg per owning process.
        Owner resolution runs per UNIQUE row slice (a few jump-hash
        calls) and the bits group by owner in one vectorized pass —
        this was the last per-bit Python loop on an import path."""
        from .. import SLICE_WIDTH
        from ..utils.arrays import group_by_key
        pod = self.pod
        n = len(rows)
        if ts_ns is None:
            ts_ns = np.zeros(n, dtype=np.int64)

        owner = pod.owner_pid(slice)
        if owner == pod.pid:
            frame.import_bits(rows, cols, timestamps, views="standard")
        else:
            self._pod_forward_import(owner, index_name, frame_name,
                                     slice, rows, cols, ts_ns,
                                     "standard")
            idx.set_remote_max_slice(slice)

        if not frame.inverse_enabled or not n:
            return
        rslice = rows // np.uint64(SLICE_WIDTH)
        uniq_slices = np.unique(rslice)
        pid_arr = np.fromiter(
            (pod.owner_pid(int(s)) for s in uniq_slices.tolist()),
            np.int64, len(uniq_slices))
        pids = pid_arr[np.searchsorted(uniq_slices, rslice)]
        for pid, rs, cs, ii, sl in group_by_key(
                pids, rows, cols, np.arange(n), rslice):
            if pid == pod.pid:
                sub_ts = ([timestamps[i] for i in ii.tolist()]
                          if timestamps else None)
                frame.import_bits(rs, cs, sub_ts, views="inverse")
            else:
                self._pod_forward_import(
                    pid, index_name, frame_name, slice, rs, cs,
                    ts_ns[ii], "inverse")
                idx.set_remote_max_inverse_slice(int(sl.max()))

    def _pod_forward_import(self, pid: int, index: str, frame: str,
                            slice: int, rows, cols, ts_ns,
                            view: str) -> None:
        # Pod-internal legs are always us-to-us: raw arrays, no
        # negotiation needed.
        from ..proto import rawimport
        ts = np.asarray(ts_ns)
        body = rawimport.encode(index, frame, slice,
                                np.asarray(rows, dtype=np.uint64),
                                np.asarray(cols, dtype=np.uint64),
                                ts if ts.any() else None)
        self.pod.forward_raw(pid, "POST", f"/import?podView={view}",
                             body, rawimport.CONTENT_TYPE)

    def _handle_get_export(self, req: Request) -> Response:
        if req.accept != "text/csv":
            raise HTTPError(406, "Not acceptable")
        slice = req.uint_param("slice")
        index = req.query.get("index", "")
        if self.cluster is not None and not self.cluster.owns_fragment(
                self.host, index, slice):
            raise HTTPError(412, f"host does not own slice {self.host}"
                                 f"-{index} slice:{slice}")
        frag = self.holder.fragment(index, req.query.get("frame", ""),
                                    req.query.get("view", ""), slice)
        if frag is None:
            return Response(200, b"", "text/csv")
        return Response(200, _export_csv_chunks(frag), "text/csv")

    # -- fragment endpoints --------------------------------------------------

    def _fragment_from_query(self, req: Request):
        slice = req.uint_param("slice")
        return self.holder.fragment(req.query.get("index", ""),
                                    req.query.get("frame", ""),
                                    req.query.get("view", ""), slice)

    def _handle_fragment_nodes(self, req: Request) -> Response:
        slice = req.uint_param("slice")
        index = req.query.get("index", "")
        nodes = (self.cluster.fragment_nodes(index, slice)
                 if self.cluster else [])
        return Response.json([{"host": n.host,
                               "internalHost": n.internal_host}
                              for n in nodes])

    @staticmethod
    def _refuse_quarantined(frag) -> None:
        """Storage integrity: a quarantined fragment's copy (corrupt,
        or the fresh near-empty replacement awaiting repair) must not
        feed a peer's anti-entropy vote, a resize diff, or a backup —
        409 so remote consumers skip this node and sweep again after
        repair. The local repairer bypasses HTTP (server.repair's
        in-process target adapter)."""
        if frag is not None and getattr(frag, "quarantined", False):
            raise HTTPError(409, "fragment quarantined: "
                                 + frag.quarantine_reason)

    def _handle_fragment_blocks(self, req: Request) -> Response:
        frag = self._fragment_from_query(req)
        if frag is None:
            raise HTTPError(404, "fragment not found")
        self._refuse_quarantined(frag)
        return Response.json({"blocks": codec.blocks_to_json(frag.blocks())})

    def _handle_fragment_block_data(self, req: Request) -> Response:
        breq = pb.BlockDataRequest.FromString(req.body())
        frag = self.holder.fragment(breq.Index, breq.Frame, breq.View,
                                    breq.Slice)
        if frag is None:
            raise HTTPError(404, "fragment not found")
        self._refuse_quarantined(frag)
        ps = frag.block_data(breq.Block)
        return Response.proto(pb.BlockDataResponse(
            RowIDs=[int(r) for r in ps.row_ids],
            ColumnIDs=[int(c) for c in ps.column_ids]))

    def _handle_get_fragment_data(self, req: Request) -> Response:
        frag = self._fragment_from_query(req)
        if frag is None:
            raise HTTPError(404, "fragment not found")
        self._refuse_quarantined(frag)
        if req.query.get("snapshot") == "1":
            # The backup coordinator's per-fragment barrier: fold the
            # WAL into a fresh footered snapshot so the streamed body
            # verifies standalone and carries no op tail. A CLEAN
            # fragment (empty op tail, footered file — the tier
            # demote path's condition) skips the rewrite+fsync: the
            # on-disk file already IS that snapshot, and repeated
            # backup passes must not pay (or contend on) a full
            # rewrite per fragment.
            try:
                clean = False
                try:
                    frag.wal_barrier()
                    clean = (frag.storage.op_n == 0
                             and getattr(frag.storage, "footer",
                                         None) is not None)
                except storage_wal.WalError:
                    clean = False  # torn pending tail: fold it
                if not clean:
                    frag.snapshot(sync=True, reason="backup")
            except OSError as e:
                raise HTTPError(500, f"snapshot failed: {e}")
        # Spool to disk above 8 MB so concurrent 128 MB+ backups don't
        # each hold the whole archive in memory.
        import tempfile
        spool = tempfile.SpooledTemporaryFile(max_size=8 << 20)
        frag.write_to(spool)
        spool.seek(0)
        return Response(200, spool, "application/octet-stream")

    # -- generation tokens (cluster.generations) -----------------------------

    def _owned_slices(self, index_name: str) -> list[int]:
        """The slices this node would report tokens for when a caller
        names none: every locally-owned slice of the index."""
        idx = self.holder.index(index_name)
        if idx is None:
            return []
        max_slice = idx.max_slice()
        if self.cluster is None:
            return list(range(max_slice + 1))
        return [int(s) for s in self.cluster.owns_slices(
            index_name, max_slice, self.host)]

    def _generations_header(self, index_name: str,
                            slices) -> Optional[tuple]:
        """(header, payload) with this node's current tokens for the
        served slices, or None when there is nothing to report. Never
        raises — a token header must not fail the response that
        carries it."""
        try:
            if self.holder is None or self.holder.index(index_name) \
                    is None:
                return None
            if not slices:
                slices = self._owned_slices(index_name)
            if not slices:
                return None
            tokens = gens_mod.local_tokens(self.holder, index_name,
                                           slices)
            return (gens_mod.GENERATIONS_HEADER,
                    gens_mod.encode_wire(index_name, tokens))
        except Exception:  # noqa: BLE001 - advisory header only
            return None

    def _handle_get_generations(self, req: Request) -> Response:
        """The coordinator result cache's validation probe: current
        per-fragment (uid, generation) tokens for the named slices
        (default: every locally-owned slice). A cheap read — no locks
        beyond the holder maps — so a validation round-trip costs
        ~RTT, not a query."""
        index_name = req.query.get("index", "")
        if not index_name:
            raise HTTPError(400, "index required")
        if self.holder.index(index_name) is None:
            raise HTTPError(404, "index not found")
        raw = req.query.get("slices", "")
        try:
            slices = [int(s) for s in raw.split(",") if s != ""]
        except ValueError:
            raise HTTPError(400, "invalid slices argument")
        if not slices:
            slices = self._owned_slices(index_name)
        tokens = gens_mod.local_tokens(self.holder, index_name, slices)
        return Response.json({
            "index": index_name, "host": self.host,
            "tokens": {str(s): {k: [v[0], v[1]] for k, v in m.items()}
                       for s, m in tokens.items()}})

    # -- elastic resize (cluster.resize; docs/CLUSTER_RESIZE.md) -------------

    def _resize_server(self):
        """The Server behind the resize control surface; bare test
        handlers (no status_handler / no start_resize) answer 503."""
        s = self.status_handler
        if s is None or not hasattr(s, "start_resize"):
            raise HTTPError(503, "no resize coordinator on this node")
        return s

    def _handle_get_cluster_resize(self, req: Request) -> Response:
        out: dict = {"epoch": self.cluster.epoch
                     if self.cluster is not None else 0}
        rs = self.cluster.resize if self.cluster is not None else None
        out["installed"] = rs.to_wire() if rs is not None else None
        s = self.status_handler
        op = getattr(s, "resize_op", None)
        out["op"] = op.status() if op is not None else None
        return Response.json(out)

    def _handle_post_cluster_resize(self, req: Request) -> Response:
        """Start (or abort) an online resize with THIS node as
        coordinator. Body: {"hosts": [target membership]} |
        {"add": "h:p"} | {"remove": "h:p"} | {"abort": true}."""
        server = self._resize_server()
        body = req.json()
        if body.get("abort"):
            status = server.abort_resize()
            if status is None:
                raise HTTPError(409, "no resize in flight")
            return Response.json({"op": status})
        current = [n.host for n in self.cluster.nodes]
        if body.get("hosts"):
            target = [str(h) for h in body["hosts"]]
        elif body.get("add"):
            h = str(body["add"])
            if h in current:
                raise HTTPError(400, f"{h} already a member")
            target = current + [h]
        elif body.get("remove"):
            h = str(body["remove"])
            if h not in current:
                raise HTTPError(400, f"{h} not a member")
            if len(current) == 1:
                raise HTTPError(400, "cannot remove the last node")
            target = [x for x in current if x != h]
        else:
            raise HTTPError(400, "hosts, add, remove, or abort"
                                 " required")
        try:
            coord = server.start_resize(target)
        except PilosaError as e:
            raise HTTPError(409, str(e))
        return Response.json({"op": coord.status()}, status=202)

    # -- backup control surface (backup.coordinator) --------------------------

    def _backup_server(self):
        """The Server behind the backup control surface; bare test
        handlers (no status_handler / no start_backup) answer 503."""
        s = self.status_handler
        if s is None or not hasattr(s, "start_backup"):
            raise HTTPError(503, "no backup coordinator on this node")
        return s

    def _handle_get_backup(self, req: Request) -> Response:
        """The in-flight (or last finished) backup this node
        coordinates, plus whether an archive is configured."""
        s = self.status_handler
        op = getattr(s, "backup_op", None)
        return Response.json({
            "configured": getattr(s, "backup_store", None) is not None,
            "op": op.status() if op is not None else None})

    def _handle_post_backup(self, req: Request) -> Response:
        """Start (or abort) a cluster backup with THIS node as
        coordinator. Body: {"kind": "full"|"incremental"} |
        {"abort": true}."""
        server = self._backup_server()
        body = req.json()
        if body.get("abort"):
            status = server.abort_backup()
            if status is None:
                raise HTTPError(409, "no backup in flight")
            return Response.json({"op": status})
        kind = str(body.get("kind", "full"))
        if kind not in ("full", "incremental"):
            raise HTTPError(400, f"unknown backup kind {kind!r}")
        try:
            coord = server.start_backup(kind)
        except PilosaError as e:
            raise HTTPError(409, str(e))
        return Response.json({"op": coord.status()}, status=202)

    def _handle_debug_backup(self, req: Request) -> Response:
        """Archive introspection: committed backups (lineage, sizes),
        WAL-archive coverage per node, this node's archiver state, and
        the in-flight op — the first stop of any is-my-data-safe
        check."""
        s = self.status_handler
        store = getattr(s, "backup_store", None)
        out: dict = {"configured": store is not None}
        op = getattr(s, "backup_op", None)
        out["op"] = op.status() if op is not None else None
        archiver = getattr(s, "wal_archiver", None)
        out["walArchiver"] = (archiver.state()
                              if archiver is not None else None)
        if store is not None:
            from ..backup import archive as backup_archive
            out["backups"] = [
                {"id": m["id"], "kind": m.get("kind"),
                 "parent": m.get("parent"), "t": m.get("t"),
                 "coordinator": m.get("coordinator"),
                 "epoch": m.get("epoch"),
                 "fragments": len(m.get("fragments", []))}
                for m in backup_archive.list_backups(store)]
            wal: dict = {}
            for _key, node, seq in backup_archive.list_wal_segments(
                    store):
                ent = wal.setdefault(node,
                                     {"segments": 0, "maxSeq": -1})
                ent["segments"] += 1
                ent["maxSeq"] = max(ent["maxSeq"], seq)
            out["walSegments"] = wal
        return Response.json(out)

    def _handle_debug_topology(self, req: Request) -> Response:
        """Placement introspection: the epoch, the membership, every
        index's per-slice owner map, and the in-flight resize state —
        the first thing a mis-routed-query investigation needs."""
        if self.cluster is None:
            return Response.json({"epoch": 0, "nodes": [],
                                  "indexes": {}, "resize": None})
        cl = self.cluster
        rs = cl.resize
        out: dict = {
            "epoch": cl.epoch,
            "partitionN": cl.partition_n,
            "replicaN": cl.replica_n,
            "nodes": [n.host for n in cl.nodes],
            "resize": rs.to_wire() if rs is not None else None,
        }
        indexes: dict = {}
        if self.holder is not None:
            for name in sorted(self.holder.indexes):
                idx = self.holder.indexes[name]
                hi = max(idx.max_slice(), idx.max_inverse_slice())
                owners = {}
                moving = []
                for s in range(hi + 1):
                    owners[str(s)] = [n.host for n in
                                      cl.fragment_nodes(name, s)]
                    if cl.moving_slice(name, s) is not None:
                        moving.append(s)
                entry: dict = {"maxSlice": idx.max_slice(),
                               "owners": owners}
                if moving:
                    entry["movingSlices"] = moving
                indexes[name] = entry
        out["indexes"] = indexes
        return Response.json(out)

    def _handle_post_fragment_import(self, req: Request) -> Response:
        """Additive per-fragment positions import — the resize
        streamer's push lane (cluster.client.fragment_import). Unlike
        POST /fragment/data it never replaces content (concurrent
        double-writes land between a block-diff read and this push);
        unlike /import it applies to the EXACT (frame, view) fragment
        so inverse and time views migrate faithfully. Body: LE u64
        slice-local positions (row*SLICE_WIDTH + col%SLICE_WIDTH)."""
        index_name = req.query.get("index", "")
        frame_name = req.query.get("frame", "")
        view = req.query.get("view", "")
        slice = req.uint_param("slice")
        if not index_name or not frame_name or not view:
            raise HTTPError(400, "index, frame, and view required")
        if self.cluster is not None and not self.cluster.owns_fragment(
                self.host, index_name, slice):
            raise HTTPError(412, f"host does not own slice"
                                 f" {self.host}-{index_name}"
                                 f" slice:{slice}")
        frame = self.holder.frame(index_name, frame_name)
        if frame is None:
            raise HTTPError(404, "frame not found")
        body = req.body()
        if len(body) % 8:
            raise HTTPError(400, "positions body not 8-byte aligned")
        positions = np.frombuffer(body, dtype="<u8")
        v = frame.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(slice)
        if len(positions):
            # Writable sorted copy: frombuffer views of the HTTP body
            # are read-only and may alias the request buffer.
            frag.import_positions(np.sort(positions))
        storage_wal.barrier_all()
        obs_metrics.IMPORT_BITS.labels("resizeStream").inc(
            len(positions))
        hs = []
        gh = self._generations_header(index_name, [slice])
        if gh is not None:
            hs.append(gh)
        return Response.json({"accepted": int(len(positions))},
                             headers=hs)

    def _handle_post_fragment_data(self, req: Request) -> Response:
        slice = req.uint_param("slice")
        frame = self.holder.frame(req.query.get("index", ""),
                                  req.query.get("frame", ""))
        if frame is None:
            raise HTTPError(404, "frame not found")
        view = frame.create_view_if_not_exists(req.query.get("view", ""))
        frag = view.create_fragment_if_not_exists(slice)
        # Spool the body to a bounded temp file BEFORE read_from: the
        # restore swaps storage under the fragment lock, which must be
        # held at disk speed, not for a slow client's whole upload —
        # and an aborted upload then never reaches the storage swap.
        import shutil
        import tempfile
        with tempfile.SpooledTemporaryFile(max_size=1 << 24) as spool:
            shutil.copyfileobj(req.body_stream(), spool, 1 << 20)
            spool.seek(0)
            frag.read_from(spool)
        return Response.json({})

    def _handle_post_frame_restore(self, req: Request) -> Response:
        # Pull every owned slice of a frame from a remote cluster
        # (handler.go:1180-1266).
        index_name, frame_name = req.vars["index"], req.vars["frame"]
        host = req.query.get("host")
        if not host:
            raise HTTPError(400, "host required")
        if self.client_factory is None:
            raise HTTPError(500, "no client factory configured")
        client = self.client_factory(host)
        max_slices = client.max_slices()
        frame = self.holder.frame(index_name, frame_name)
        if frame is None:
            raise HTTPError(404, "frame not found")
        views = client.frame_views(index_name, frame_name)
        for slice in range(max_slices.get(index_name, 0) + 1):
            if self.cluster is not None and not self.cluster.owns_fragment(
                    self.host, index_name, slice):
                continue
            for view_name in views:
                view = frame.create_view_if_not_exists(view_name)
                frag = view.create_fragment_if_not_exists(slice)
                rd = client.backup_slice(index_name, frame_name, view_name,
                                         slice)
                if rd is None:
                    continue
                try:
                    frag.read_from(rd)
                finally:
                    rd.close()
        return Response.json({})

    # -- pod work items (parallel.pod) ---------------------------------------

    def _handle_pod_exec(self, req: Request) -> Response:
        if self.pod is None:
            raise HTTPError(404, "not a pod process")
        return Response.json(self.pod.run_item(req.json()))

    # -- broadcast ingest ----------------------------------------------------

    def _handle_post_message(self, req: Request) -> Response:
        if self.broadcast_handler is None:
            raise HTTPError(404, "no broadcast handler")
        self.broadcast_handler.receive_message(
            unmarshal_message(req.body()))
        return Response.json({})
