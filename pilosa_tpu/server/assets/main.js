/* pilosa-tpu console: PQL REPL with keyboard history, cluster status,
 * and a schema browser — the same information surface the reference's
 * console exposes (query + timing + history + index dropdown +
 * cluster view), plus frame options per index from /schema. */
"use strict";
const $ = id => document.getElementById(id);
const getJSON = (path, cb) =>
  fetch(path).then(r => r.json()).then(cb).catch(() => {});

const PANES = ["query", "cluster", "schema"];
function show(pane) {
  for (const p of PANES) {
    $("pane-" + p).classList.toggle("active", p === pane);
    $("nav-" + p).classList.toggle("active", p === pane);
  }
  if (pane === "cluster") refreshStatus();
  if (pane === "schema") refreshSchemaPane();
}
for (const p of PANES) $("nav-" + p).onclick = () => show(p);

/* ---- console ---- */
const history = [];       // submitted queries, oldest first
let histIdx = 0;          // cursor for ArrowUp/ArrowDown recall
let histDraft = "";

function refreshIndexes() {
  getJSON("/schema", s => {
    const sel = $("index"), cur = sel.value;
    sel.innerHTML = "";
    for (const ix of (s.indexes || []))
      sel.add(new Option(ix.name, ix.name, false, ix.name === cur));
  });
}

function run() {
  const index = $("index").value, q = $("pql").value.trim();
  if (!index || !q) return;
  history.push(q);
  histIdx = history.length;
  const t0 = performance.now();
  fetch("/index/" + encodeURIComponent(index) + "/query",
        {method: "POST", body: q})
    .then(r => r.json().then(body => ({ok: r.ok, body})))
    .then(({ok, body}) => record(q, body, ok, performance.now() - t0))
    .catch(e => record(q, {error: String(e)}, false,
                       performance.now() - t0));
  $("pql").value = "";
  refreshIndexes();
}

function record(q, body, ok, ms) {
  const div = document.createElement("div");
  div.className = "entry" + (ok ? "" : " err");
  const head = document.createElement("div");
  head.className = "q";
  head.textContent = q;
  const t = document.createElement("em");
  t.textContent = ms.toFixed(1) + " ms";
  head.appendChild(t);
  const pre = document.createElement("pre");
  pre.textContent = JSON.stringify(body, null, 2);
  div.append(head, pre);
  $("history").prepend(div);
}

$("run").onclick = run;
$("pql").addEventListener("keydown", e => {
  if (e.key === "Enter" && !e.shiftKey) { e.preventDefault(); run(); }
  else if (e.key === "ArrowUp" && histIdx > 0) {
    if (histIdx === history.length) histDraft = $("pql").value;
    histIdx--;
    $("pql").value = history[histIdx];
    e.preventDefault();
  } else if (e.key === "ArrowDown" && histIdx < history.length) {
    histIdx++;
    $("pql").value = histIdx === history.length ? histDraft
                                                : history[histIdx];
    e.preventDefault();
  }
});

/* ---- cluster ---- */
function refreshStatus() {
  getJSON("/status", s => {
    const tbody = $("status");
    tbody.replaceChildren();
    for (const n of ((s.status || {}).nodes || [])) {
      const tr = document.createElement("tr");
      const st = n.state || "?";
      for (const text of [n.host, st,
                          (n.indexes || []).map(i => i.name).join(", ")]) {
        const td = document.createElement("td");
        td.textContent = text;
        tr.appendChild(td);
      }
      tr.children[1].className = st;
      tbody.appendChild(tr);
    }
  });
}

/* ---- schema browser ---- */
function refreshSchemaPane() {
  getJSON("/schema", s => {
    const root = $("schema");
    root.replaceChildren();
    for (const ix of (s.indexes || [])) {
      const box = document.createElement("div");
      box.className = "schema-index";
      const name = document.createElement("div");
      name.className = "name";
      name.textContent = ix.name;
      const small = document.createElement("small");
      small.textContent = (ix.frames || []).length + " frame(s)";
      name.appendChild(small);
      name.onclick = () => box.classList.toggle("closed");
      const frames = document.createElement("div");
      frames.className = "frames";
      const table = document.createElement("table");
      const head = document.createElement("tr");
      for (const h of ["frame", "rowLabel", "cacheType", "cacheSize",
                       "inverseEnabled", "timeQuantum"]) {
        const th = document.createElement("th");
        th.textContent = h;
        head.appendChild(th);
      }
      table.appendChild(head);
      for (const fr of (ix.frames || [])) {
        const tr = document.createElement("tr");
        const o = fr.options || {};
        for (const v of [fr.name, o.rowLabel, o.cacheType, o.cacheSize,
                         o.inverseEnabled, o.timeQuantum]) {
          const td = document.createElement("td");
          td.textContent = v === undefined ? "—" : String(v);
          tr.appendChild(td);
        }
        table.appendChild(tr);
      }
      frames.appendChild(table);
      box.append(name, frames);
      root.appendChild(box);
    }
    if (!root.children.length)
      root.textContent = "no indexes yet — create one via the API or " +
        'POST /index/{name}';
  });
}

/* ---- boot ---- */
getJSON("/version", v => $("version").textContent =
  "v" + (v.version || "?"));
refreshIndexes();
setInterval(() => {
  if ($("pane-cluster").classList.contains("active")) refreshStatus();
}, 5000);
