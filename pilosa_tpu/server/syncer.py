"""Anti-entropy: checksum-driven repair of attributes and fragments.

Reference: holder.go:364-562 (HolderSyncer) + fragment.go:1301-1481
(FragmentSyncer). Walks the whole schema; for each index/frame it pulls
attribute diffs from peers by 100-id block checksums; for each owned
(view, slice) it compares 100-row SHA1 block checksums across replicas,
pulls differing blocks, runs the majority-consensus MergeBlock
(fragment.merge_block), applies local diffs, and pushes each peer's
diffs back as SetBit/ClearBit PQL.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .. import SLICE_WIDTH
from ..cluster.client import Client, ClientError
from ..errors import FragmentNotFoundError, FrameNotFoundError
from ..fault import failpoints as _fp
from ..models.view import VIEW_STANDARD
from ..storage.fragment import PairSet
from ..utils import logger as logger_mod


class HolderSyncer:
    def __init__(self, holder, host: str, cluster,
                 closing: Optional[threading.Event] = None,
                 client_factory=Client, logger=logger_mod.NOP,
                 fault=None):
        self.holder = holder
        self.host = host
        self.cluster = cluster
        self.closing = closing or threading.Event()
        self.client_factory = client_factory
        self.logger = logger
        # fault.FaultManager: peers whose circuit breaker is open are
        # skipped for the whole pass (they get repaired when they
        # return) instead of blocking anti-entropy on dead-peer
        # timeouts — the 60-minute soak's sweep must survive a down
        # replica.
        self.fault = fault

    def is_closing(self) -> bool:
        return self.closing.is_set()

    def _peers(self):
        # would_allow, not allow: this is a pure filter — consuming
        # the half-open probe slot here would starve the client's own
        # gate of it when the sync RPC actually goes out.
        return [n for n in self.cluster.nodes
                if n.host != self.host
                and (self.fault is None
                     or self.fault.would_allow(n.host))]

    # -- whole-holder walk (holder.go:385-436) -------------------------------

    def sync_holder(self) -> None:
        # Only STANDARD views are consensus-merged: the push-back repair
        # is SetBit/ClearBit PQL, which writes through the frame and so
        # regenerates inverse/time views consistently. Merging raw block
        # data into a transposed or time-scoped fragment would corrupt it
        # (the reference pulls only ViewStandard data for the same
        # reason, fragment.go:1425).
        for di in self.holder.schema():
            if self.is_closing():
                return
            self.sync_index(di["name"])
            for fi in di["frames"]:
                if self.is_closing():
                    return
                self.sync_frame(di["name"], fi["name"])
                if not any(v["name"] == VIEW_STANDARD
                           for v in fi["views"]):
                    continue
                max_slice = self.holder.index(di["name"]).max_slice()
                for slice in range(max_slice + 1):
                    # READ authority, not the write-accept union: an
                    # old owner inside the post-resize grace window
                    # still owns_fragment a moved slice, but its
                    # frozen copy must never VOTE in the consensus
                    # merge — majority with a stale voter can push
                    # ClearBits of acked writes or resurrect cleared
                    # bits (review finding, same class as the
                    # executor cache gates).
                    if not self.cluster.read_allowed(
                            self.host, di["name"], slice):
                        continue
                    # Elastic resize: a moving slice's target copy is
                    # legitimately incomplete mid-migration — feeding
                    # it into the majority-consensus merge could push
                    # CLEARS of not-yet-streamed bits back to the
                    # source. The resize streamer owns these fragments
                    # until the flip settles; anti-entropy resumes on
                    # the next sweep after finalize.
                    if self.cluster.moving_slice(di["name"],
                                                 slice) is not None:
                        continue
                    if self.is_closing():
                        return
                    self.sync_fragment(di["name"], fi["name"],
                                       VIEW_STANDARD, slice)

    # -- attribute sync (holder.go:439-528) ----------------------------------

    def _sync_attr_store(self, store, fetch_diff) -> None:
        # Blocks are recomputed after every merge so the next peer diffs
        # against current state (holder.go:466-478).
        blocks = store.blocks()
        for node in self._peers():
            client = self.client_factory(node.host)
            try:
                m = fetch_diff(client, blocks)
            except (FrameNotFoundError, FragmentNotFoundError):
                continue  # not created remotely yet
            except ClientError as e:
                # A dead/unreachable peer must not abort the whole
                # sweep — the remaining peers still get their repair.
                # The failed RPC already fed the breaker (when the
                # client is fault-aware), so the NEXT store skips the
                # peer without paying this timeout again.
                self.logger.printf("sync: skipping peer %s: %s",
                                   node.host, e)
                continue
            if not m:
                continue
            store.set_bulk_attrs(m)
            blocks = store.blocks()

    def sync_index(self, index: str) -> None:
        idx = self.holder.index(index)
        if idx is None:
            return
        self._sync_attr_store(
            idx.column_attr_store,
            lambda c, blocks: c.column_attr_diff(index, blocks))

    def sync_frame(self, index: str, frame: str) -> None:
        f = self.holder.frame(index, frame)
        if f is None:
            return
        self._sync_attr_store(
            f.row_attr_store,
            lambda c, blocks: c.row_attr_diff(index, frame, blocks))

    # -- fragment sync (holder.go:531-562) -----------------------------------

    def sync_fragment(self, index: str, frame: str, view: str,
                      slice: int) -> None:
        f = self.holder.frame(index, frame)
        if f is None:
            raise FrameNotFoundError(frame)
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(slice)
        if frag.quarantined:
            # Storage integrity: a quarantined local copy must not
            # VOTE in the consensus merge — majority with a corrupt
            # (or freshly-reset, near-empty) voter could push
            # ClearBits of acked writes to healthy replicas. The
            # repairer owns this fragment; anti-entropy resumes on
            # the sweep after it clears.
            return
        FragmentSyncer(frag, self.host, self.cluster, self.closing,
                       self.client_factory, logger=self.logger,
                       fault=self.fault).sync_fragment()


class FragmentSyncer:
    def __init__(self, fragment, host: str, cluster,
                 closing: Optional[threading.Event] = None,
                 client_factory=Client, logger=logger_mod.NOP,
                 fault=None):
        self.fragment = fragment
        self.host = host
        self.cluster = cluster
        self.closing = closing or threading.Event()
        self.client_factory = client_factory
        self.logger = logger
        self.fault = fault

    def is_closing(self) -> bool:
        return self.closing.is_set()

    def _replica_peers(self, nodes):
        """The replica owners this pass will actually talk to: open
        circuits are skipped — a dead replica is repaired by the sweep
        AFTER it returns; blocking this sweep on its timeouts starves
        every healthy fragment behind it in the schema walk."""
        out = []
        for node in nodes:
            if node.host != self.host and self.fault is not None \
                    and not self.fault.would_allow(node.host):
                self.logger.printf(
                    "sync: skipping open-circuit peer %s for"
                    " %s/%s/%d", node.host, self.fragment.index,
                    self.fragment.frame, self.fragment.slice)
                continue
            out.append(node)
        return out

    def sync_fragment(self) -> None:
        """Compare per-block checksums across the replica set; merge any
        differing block (fragment.go:1322-1399)."""
        f = self.fragment
        if getattr(f, "quarantined", False):
            return  # must not vote (see HolderSyncer.sync_fragment)
        nodes = self._replica_peers(
            self.cluster.fragment_nodes(f.index, f.slice))
        if len(nodes) <= 1:
            return

        block_sets: list[list[tuple[int, bytes]]] = []
        sync_nodes: list = []
        for node in nodes:
            if node.host == self.host:
                block_sets.append(f.blocks())
                sync_nodes.append(node)
                continue
            client = self.client_factory(node.host)
            try:
                blocks = client.fragment_blocks(f.index, f.frame, f.view,
                                                f.slice, host=node.host)
            except FragmentNotFoundError:
                blocks = []
            except ClientError as e:
                # Unreachable mid-pass: drop the peer from THIS
                # fragment's consensus (its RPC failure fed the
                # breaker; later fragments skip it up front).
                self.logger.printf("sync: skipping peer %s: %s",
                                   node.host, e)
                continue
            block_sets.append(blocks)
            sync_nodes.append(node)
            if self.is_closing():
                return
        if len(sync_nodes) <= 1:
            return
        self._sync_nodes = sync_nodes

        # Zip the sorted block lists; sync any id whose checksums differ
        # or that is missing somewhere.
        idxs = [0] * len(block_sets)
        while True:
            block_id = None
            for bs, i in zip(block_sets, idxs):
                if i < len(bs) and (block_id is None or bs[i][0] < block_id):
                    block_id = bs[i][0]
            if block_id is None:
                break
            checksums = []
            for k, (bs, i) in enumerate(zip(block_sets, idxs)):
                if i < len(bs) and bs[i][0] == block_id:
                    checksums.append(bs[i][1])
                    idxs[k] += 1
                else:
                    checksums.append(None)
            if all(c == checksums[0] for c in checksums):
                continue
            self.sync_block(block_id)

    def sync_block(self, block_id: int) -> None:
        """Pull the block from every peer, merge by majority consensus,
        push per-peer diffs back as PQL (fragment.go:1403-1481)."""
        f = self.fragment
        nodes = getattr(self, "_sync_nodes", None)
        if nodes is None:
            nodes = self._replica_peers(
                self.cluster.fragment_nodes(f.index, f.slice))
        pair_sets: list[PairSet] = []
        clients: list = []
        for node in nodes:
            if node.host == self.host:
                continue
            if self.is_closing():
                return
            client = self.client_factory(node.host)
            # Only the standard view blocks are consensus-merged.
            try:
                rows, cols = client.block_data(f.index, f.frame,
                                               VIEW_STANDARD, f.slice,
                                               block_id, host=node.host)
            except ClientError as e:
                self.logger.printf("sync: skipping peer %s: %s",
                                   node.host, e)
                continue
            clients.append(client)
            pair_sets.append(PairSet(rows, cols))

        if self.is_closing():
            return
        sets, clears = f.merge_block(block_id, pair_sets)
        self.logger.printf(
            "sync block %s/%s/%s/%d block=%d: pushing sets=%d clears=%d",
            f.index, f.frame, f.view, f.slice, block_id,
            sum(len(s.column_ids) for s in sets),
            sum(len(c.column_ids) for c in clears))

        base = f.slice * SLICE_WIDTH
        for client, set_ps, clear_ps in zip(clients, sets, clears):
            if not len(set_ps.column_ids) and not len(clear_ps.column_ids):
                continue
            lines = []
            for r, c in zip(set_ps.row_ids, set_ps.column_ids):
                lines.append(f'SetBit(frame="{f.frame}", rowID={int(r)},'
                             f' columnID={base + int(c)})')
            for r, c in zip(clear_ps.row_ids, clear_ps.column_ids):
                lines.append(f'ClearBit(frame="{f.frame}", rowID={int(r)},'
                             f' columnID={base + int(c)})')
            if self.is_closing():
                return
            try:
                client.execute_query(None, f.index, "\n".join(lines),
                                     remote=False)
            except ClientError as e:
                # The peer died between pull and push-back: its repair
                # waits for the next sweep; local + other peers' merges
                # already landed.
                self.logger.printf("sync: push-back to %s failed: %s",
                                   client.host, e)


class _PrefixPush:
    """Torn-stream adapter for the ``resize.stream`` failpoint: torn
    mode hands this "writer" a byte PREFIX of the block's encoded u64
    positions; we push the whole positions that fit in it, so a torn
    injection leaves a genuine partial block on the target — exactly
    the state a crashed stream leaves — which the idempotent re-diff
    must then converge."""

    def __init__(self, push_fn, positions: np.ndarray):
        self.push_fn = push_fn
        self.positions = positions

    def write(self, data: bytes) -> None:
        n = len(data) // 8
        if n > 0:
            self.push_fn(self.positions[:n])


class FragmentStreamer:
    """Directed fragment migration for elastic resize
    (docs/CLUSTER_RESIZE.md): reuses the FragmentSyncer block-diff
    protocol (per-block SHA1 checksums via GET /fragment/blocks,
    changed-block pulls via the block-data wire), but instead of the
    consensus merge it pushes SETS-ONLY source→target through the
    additive ``POST /fragment/import`` lane:

    - sets-only because during migration every ClearBit double-writes
      to both copies (a bit absent on the source is already absent on
      the target), while a bit present on the target but not yet on
      the source can only be an in-flight double-write racing the diff
      read — clearing it would drop an acked write;
    - additive import (never the replace-style /fragment/data restore)
      because concurrent double-writes land between the diff read and
      the push, and a whole-fragment replace would clobber them;
    - per-block pushes bound memory, give the ``resize.stream``
      failpoint its injection granularity (error / delay / torn /
      partition-by-target-host), and let the pacing hook breathe
      between blocks.

    The push is idempotent (re-adding set bits is a no-op), so a torn
    or crashed stream recovers by simply re-running the diff.
    """

    def __init__(self, client_factory=Client, logger=logger_mod.NOP,
                 fault=None, pace_s: float = 0.0, on_block=None):
        self.client_factory = client_factory
        self.logger = logger
        # fault.FaultManager: the stream defers to the breaker state —
        # a target (or source) behind an open circuit pauses the
        # migration instead of hammering a struggling peer (the PR-5
        # health/breaker machinery paces the stream).
        self.fault = fault
        self.pace_s = pace_s
        # on_block(bits, nbytes): per-BLOCK progress callback — the
        # resize coordinator feeds its status/watchdog heartbeat from
        # it, so a long fragment's progress is visible while it
        # streams, not only after.
        self.on_block = on_block
        self.bits_pushed = 0
        self.bytes_pushed = 0

    def wait_allowed(self, host: str, closing=None,
                     timeout_s: float = 30.0) -> bool:
        """Block until the peer's circuit allows traffic (half-open
        probe windows count), up to ``timeout_s``."""
        if self.fault is None:
            return True
        deadline = None
        import time as _time
        while not self.fault.would_allow(host):
            if deadline is None:
                deadline = _time.monotonic() + timeout_s
            elif _time.monotonic() > deadline:
                return False
            if closing is not None and closing.is_set():
                return False
            _time.sleep(0.1)
        return True

    def stream_fragment(self, index: str, frame: str, view: str,
                        slice: int, source_host: str,
                        target_host: str) -> tuple[int, int]:
        """One fragment source→target; returns (bits, bytes) pushed.
        Zero bits pushed on a re-run is the convergence signal the
        coordinator's diff-until-clean loop keys on."""
        src = self.client_factory(source_host)
        tgt = self.client_factory(target_host)
        try:
            src_blocks = src.fragment_blocks(index, frame, view, slice,
                                             host=source_host)
        except FragmentNotFoundError:
            return (0, 0)  # nothing at the source: nothing to move
        try:
            tgt_blocks = dict(tgt.fragment_blocks(index, frame, view,
                                                  slice,
                                                  host=target_host))
        except FragmentNotFoundError:
            tgt_blocks = {}
        bits = nbytes = 0
        for block_id, checksum in src_blocks:
            if tgt_blocks.get(block_id) == checksum:
                continue
            rows, cols = src.block_data(index, frame, view, slice,
                                        block_id, host=source_host)
            if not len(rows):
                continue
            positions = np.unique(
                np.asarray(rows, dtype=np.uint64)
                * np.uint64(SLICE_WIDTH)
                + np.asarray(cols, dtype=np.uint64)
                % np.uint64(SLICE_WIDTH))

            def push(p, _tgt=tgt, _host=target_host):
                _tgt.fragment_import(index, frame, view, slice, p,
                                     host=_host)

            if _fp.ACTIVE is not None:
                # Torn mode pushes a PREFIX of this block, then raises
                # — the mid-stream crash shape.
                _fp.ACTIVE.hit("resize.stream", host=target_host,
                               writer=_PrefixPush(push, positions),
                               data=positions.tobytes())
            push(positions)
            block_bits = len(positions)
            block_bytes = block_bits * 8
            bits += block_bits
            nbytes += block_bytes
            self.bits_pushed += block_bits
            self.bytes_pushed += block_bytes
            from ..obs import metrics as obs_metrics
            obs_metrics.RESIZE_STREAM_BYTES.inc(block_bytes)
            if self.on_block is not None:
                self.on_block(block_bits, block_bytes)
            if self.pace_s:
                import time as _time
                _time.sleep(self.pace_s)
        return (bits, nbytes)
