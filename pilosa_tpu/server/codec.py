"""Wire codecs shared by the HTTP handler and the node-to-node client.

Converts between runtime objects (storage.Bitmap, cache.Pair, attr dicts)
and the protobuf wire types (proto/internal.proto, field-number-compatible
with the reference's internal/public.proto) plus the reference's JSON
shapes (handler.go:1307-1397, bitmap.go:220-233, cache.go:292-293).
"""

from __future__ import annotations

import base64
from typing import Optional

import numpy as np

from .. import SLICE_WIDTH
from ..proto import internal_pb2 as pb
from ..storage import roaring
from ..utils.arrays import group_by_key
from ..storage.attrs import (ATTR_TYPE_BOOL, ATTR_TYPE_FLOAT, ATTR_TYPE_INT,
                             ATTR_TYPE_STRING)
from ..storage.bitmap import Bitmap
from ..storage.bsi import ValCount
from ..storage.cache import Pair


# -- attrs --------------------------------------------------------------------

def encode_attr(key: str, v) -> pb.Attr:
    a = pb.Attr(Key=key)
    if isinstance(v, bool):
        a.Type, a.BoolValue = ATTR_TYPE_BOOL, v
    elif isinstance(v, str):
        a.Type, a.StringValue = ATTR_TYPE_STRING, v
    elif isinstance(v, int):
        a.Type, a.IntValue = ATTR_TYPE_INT, v
    elif isinstance(v, float):
        a.Type, a.FloatValue = ATTR_TYPE_FLOAT, v
    return a


def encode_attr_list(m: dict) -> list[pb.Attr]:
    return [encode_attr(k, m[k]) for k in sorted(m)]


def decode_attr_list(attrs) -> dict:
    m = {}
    for a in attrs:
        if a.Type == ATTR_TYPE_STRING:
            m[a.Key] = a.StringValue
        elif a.Type == ATTR_TYPE_INT:
            m[a.Key] = a.IntValue
        elif a.Type == ATTR_TYPE_BOOL:
            m[a.Key] = a.BoolValue
        elif a.Type == ATTR_TYPE_FLOAT:
            m[a.Key] = a.FloatValue
    return m


# -- bitmap / pairs -----------------------------------------------------------

def encode_bitmap(bm: Bitmap) -> pb.Bitmap:
    return pb.Bitmap(Bits=bm.bits().tolist(),
                     Attrs=encode_attr_list(bm.attrs))


def decode_bitmap(msg: pb.Bitmap) -> Bitmap:
    """Rebuild the segmented result bitmap from the wire bit list in
    bulk (one roaring build per slice, not one add per bit)."""
    bm = Bitmap()
    if msg.Bits:
        cols = np.fromiter(msg.Bits, dtype=np.uint64, count=len(msg.Bits))
        for slice, group in group_by_key(cols // np.uint64(SLICE_WIDTH),
                                         cols):
            bm.add_segment(roaring.Bitmap.from_sorted(group), slice,
                           writable=True)
    bm.attrs = decode_attr_list(msg.Attrs)
    return bm


def encode_pairs(pairs: list[Pair]) -> list[pb.Pair]:
    return [pb.Pair(Key=p.id, Count=p.count) for p in pairs]


def decode_pairs(msgs) -> list[Pair]:
    return [Pair(m.Key, m.Count) for m in msgs]


# -- query request / response -------------------------------------------------

def encode_query_request(query: str, slices: Optional[list[int]] = None,
                         column_attrs: bool = False, remote: bool = False
                         ) -> bytes:
    return pb.QueryRequest(Query=query, Slices=slices or [],
                           ColumnAttrs=column_attrs,
                           Remote=remote).SerializeToString()


def encode_query_result(result) -> pb.QueryResult:
    out = pb.QueryResult()
    if isinstance(result, Bitmap):
        out.Bitmap.CopyFrom(encode_bitmap(result))
    elif isinstance(result, ValCount):
        out.ValCount.Val = result.value
        out.ValCount.Count = result.count
    elif isinstance(result, list):
        out.Pairs.extend(encode_pairs(result))
    elif isinstance(result, bool):
        out.Changed = result
    elif isinstance(result, int):
        out.N = result
    return out


def encode_query_response(results: list, column_attr_sets=None,
                          err: str = "") -> pb.QueryResponse:
    resp = pb.QueryResponse(Err=err)
    for r in results:
        resp.Results.append(encode_query_result(r))
    for id, attrs in (column_attr_sets or []):
        resp.ColumnAttrSets.append(
            pb.ColumnAttrSet(ID=id, Attrs=encode_attr_list(attrs)))
    return resp


def decode_query_results(resp: pb.QueryResponse, call_names: list[str]
                         ) -> list:
    """Decode per-call results by call name (executor.go:1058-1080)."""
    out = []
    for name, res in zip(call_names, resp.Results):
        if name == "TopN":
            out.append(decode_pairs(res.Pairs))
        elif name == "Count":
            out.append(int(res.N))
        elif name in ("SetBit", "ClearBit", "SetFieldValue"):
            out.append(bool(res.Changed))
        elif name in ("Sum", "Min", "Max"):
            out.append(ValCount(int(res.ValCount.Val),
                                int(res.ValCount.Count)))
        elif name in ("SetRowAttrs", "SetColumnAttrs"):
            out.append(None)
        else:
            out.append(decode_bitmap(res.Bitmap))
    return out


# -- JSON shapes --------------------------------------------------------------

def result_to_json(result):
    if isinstance(result, Bitmap):
        return result.to_json()
    if isinstance(result, ValCount):
        return result.to_json()  # {"value": ..., "count": ...}
    if isinstance(result, list):  # pairs
        return [{"id": p.id, "count": p.count} for p in result]
    return result  # int, bool, or None


def query_response_json(results: list, column_attr_sets=None,
                        err: str = "") -> dict:
    out = {}
    if results:
        out["results"] = [result_to_json(r) for r in results]
    if column_attr_sets:
        out["columnAttrs"] = [
            {"id": id, **({"attrs": attrs} if attrs else {})}
            for id, attrs in column_attr_sets]
    if err:
        out["error"] = err
    return out


def blocks_to_json(blocks: list[tuple[int, bytes]]) -> list[dict]:
    """FragmentBlock JSON: checksum bytes base64 like Go's []byte
    (fragment.go:1270-1273)."""
    return [{"id": bid, "checksum": base64.b64encode(chk).decode()}
            for bid, chk in blocks]


def blocks_from_json(objs: list[dict]) -> list[tuple[int, bytes]]:
    return [(o["id"], base64.b64decode(o["checksum"])) for o in objs]
