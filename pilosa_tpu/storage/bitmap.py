"""Query-result Bitmap: a cluster-wide bitmap as sorted per-slice segments.

Reference: bitmap.go. A query result spans many slices; each segment wraps a
roaring bitmap of absolute column positions for one slice and stays sharded —
ops zip two segment lists by slice, and the final bit-list is only
materialized on demand (JSON encoding or .bits()).
"""

from __future__ import annotations

import bisect
from typing import Iterable, Optional

import numpy as np

from .. import SLICE_WIDTH
from . import roaring


class BitmapSegment:
    """One slice's worth of a result bitmap (reference bitmap.go:296-392).

    ``writable=False`` marks data shared with mmap'd storage; mutation
    copies first (the roaring containers carry their own mapped flags, so
    this is enforced at container granularity).
    """

    __slots__ = ("data", "slice", "writable", "_n")

    def __init__(self, data: roaring.Bitmap, slice: int, writable: bool):
        self.data = data
        self.slice = slice
        self.writable = writable
        self._n: Optional[int] = None

    def count(self) -> int:
        if self._n is None:
            self._n = self.data.count()
        return self._n

    def set_bit(self, col: int) -> bool:
        changed = self.data.add(col)
        if changed and self._n is not None:
            self._n += 1
        return changed

    def clear_bit(self, col: int) -> bool:
        changed = self.data.remove(col)
        if changed and self._n is not None:
            self._n -= 1
        return changed

    def _binary(self, other: "BitmapSegment", fn) -> "BitmapSegment":
        return BitmapSegment(fn(self.data, other.data), self.slice, True)

    def intersect(self, o):
        return self._binary(o, lambda a, b: a.intersect(b))

    def union(self, o):
        return self._binary(o, lambda a, b: a.union(b))

    def difference(self, o):
        return self._binary(o, lambda a, b: a.difference(b))

    def intersection_count(self, o) -> int:
        return self.data.intersection_count(o.data)


def _zip_segments(a: list[BitmapSegment], b: list[BitmapSegment]):
    """Merge-iterate two slice-sorted segment lists
    (reference bitmap.go:394-437)."""
    i = j = 0
    while i < len(a) or j < len(b):
        if j >= len(b) or (i < len(a) and a[i].slice < b[j].slice):
            yield a[i], None
            i += 1
        elif i >= len(a) or b[j].slice < a[i].slice:
            yield None, b[j]
            j += 1
        else:
            yield a[i], b[j]
            i += 1
            j += 1


class Bitmap:
    """Segmented result bitmap with attached row attributes."""

    def __init__(self, *bits: int):
        self.segments: list[BitmapSegment] = []
        self.attrs: dict = {}
        for v in bits:
            self.set_bit(v)

    # -- segment management

    def _segment(self, slice: int, create: bool) -> Optional[BitmapSegment]:
        i = bisect.bisect_left([s.slice for s in self.segments], slice)
        if i < len(self.segments) and self.segments[i].slice == slice:
            return self.segments[i]
        if not create:
            return None
        seg = BitmapSegment(roaring.Bitmap(), slice, True)
        self.segments.insert(i, seg)
        return seg

    def add_segment(self, data: roaring.Bitmap, slice: int,
                    writable: bool = False) -> None:
        i = bisect.bisect_left([s.slice for s in self.segments], slice)
        self.segments.insert(i, BitmapSegment(data, slice, writable))

    # -- point ops

    def set_bit(self, col: int) -> bool:
        return self._segment(col // SLICE_WIDTH, True).set_bit(col)

    def clear_bit(self, col: int) -> bool:
        seg = self._segment(col // SLICE_WIDTH, False)
        return seg.clear_bit(col) if seg else False

    # -- set algebra (zip by slice)

    def intersect(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for s0, s1 in _zip_segments(self.segments, other.segments):
            if s0 is not None and s1 is not None:
                out.segments.append(s0.intersect(s1))
        return out

    def union(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for s0, s1 in _zip_segments(self.segments, other.segments):
            if s0 is None:
                out.segments.append(s1)
            elif s1 is None:
                out.segments.append(s0)
            else:
                out.segments.append(s0.union(s1))
        return out

    def difference(self, other: "Bitmap") -> "Bitmap":
        out = Bitmap()
        for s0, s1 in _zip_segments(self.segments, other.segments):
            if s0 is None:
                continue
            out.segments.append(s0 if s1 is None else s0.difference(s1))
        return out

    def intersection_count(self, other: "Bitmap") -> int:
        n = 0
        for s0, s1 in _zip_segments(self.segments, other.segments):
            if s0 is not None and s1 is not None:
                n += s0.intersection_count(s1)
        return n

    def merge(self, other: "Bitmap") -> None:
        """In-place union used by the map-reduce bitmap reducer."""
        merged = self.union(other)
        self.segments = merged.segments

    # -- access

    def count(self) -> int:
        return sum(s.count() for s in self.segments)

    def bits(self) -> np.ndarray:
        """All absolute column positions, sorted, u64."""
        parts = [s.data.values() for s in self.segments]
        parts = [p for p in parts if len(p)]
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def to_json(self) -> dict:
        return {"attrs": self.attrs, "bits": self.bits().tolist()}


def union_all(bitmaps: Iterable[Bitmap]) -> Bitmap:
    out = Bitmap()
    for b in bitmaps:
        out.merge(b)
    return out
