"""Builder/loader for the real CPython extension (native/fastmutate.c).

The per-op mutate hot path needs a compiled crossing with no ctypes
per-call floor (VERDICT r5 #1): this module compiles
``pilosa_tpu/native/fastmutate.c`` against the running interpreter's
headers + numpy's C API on first use, caches the .so keyed by source
hash (same per-machine scheme as storage.native), and loads it as a
genuine extension module. Everything degrades gracefully:

- ``PILOSA_TPU_NATIVE_EXT=0`` — escape hatch, never build or load;
- no toolchain / headers / build failure — silently fall back (the
  pure-Python mutate paths are the permanent fallback, and the
  extension itself bails per-op on anything unusual);
- big-endian hosts — disabled (the extension builds little-endian wire
  records and reads ``<u2``/``<u4``/``<u8`` buffers as host ints).

``EXT`` is the loaded module or None; the roaring hot paths read it as
one module-attribute load per op. ``load()`` triggers the build (called
from Fragment.open and the test session's conftest hook).
"""

from __future__ import annotations

import hashlib
import importlib.machinery
import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "fastmutate.c")
_MOD_NAME = "pilosa_fastmutate"

EXT = None
_tried = False
_lock = threading.Lock()


def _so_path() -> str:
    # Keyed by source hash + interpreter tag: the module links against
    # this exact CPython ABI, and -march=native makes it per-machine
    # (same rationale as storage.native._so_path).
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    tag = sysconfig.get_config_var("SOABI") or "abi"
    from ..utils import cache_dir
    cache = cache_dir()
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"{_MOD_NAME}-{digest}-{tag}.so")


def _build(so: str) -> None:
    import numpy as np
    py_inc = sysconfig.get_paths()["include"]
    cmd = ["g++", "-O2", "-march=native", "-shared", "-fPIC",
           "-I" + py_inc, "-I" + np.get_include(),
           "-o", so + ".tmp", _SRC]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(so + ".tmp", so)


def load():
    """Build (cached) + load the extension; returns the module or
    None. Idempotent and thread-safe; failures latch to None."""
    global EXT, _tried
    if _tried:
        return EXT
    with _lock:
        if _tried:
            return EXT
        try:
            if (os.environ.get("PILOSA_TPU_NATIVE_EXT", "1") == "0"
                    or sys.byteorder != "little"):
                EXT = None
            else:
                so = _so_path()
                if not os.path.exists(so):
                    _build(so)
                loader = importlib.machinery.ExtensionFileLoader(
                    _MOD_NAME, so)
                spec = importlib.util.spec_from_file_location(
                    _MOD_NAME, so, loader=loader)
                mod = importlib.util.module_from_spec(spec)
                loader.exec_module(mod)
                EXT = mod
        except Exception:
            EXT = None
        _tried = True
        return EXT


def available() -> bool:
    return load() is not None
