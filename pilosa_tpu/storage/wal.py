"""Group-committed write-ahead log: buffered appends, one leader flush.

The vintage write path paid one unbuffered ``write()`` syscall per op
record — on syscall-expensive hosts (gVisor, 9p, network filesystems)
that single call IS the per-op SetBit budget, and under concurrent
imports every writer paid it (plus its own snapshot fsync)
independently. This module is the classic database group commit
applied to the fragment op-log:

- ``append(blob)`` copies the record(s) into an in-memory pending
  buffer and returns a **sequence number** (the byte offset the record
  ends at). No syscall. Appends are serialized by the owning
  fragment's mutation lock plus this object's own lock, so sequence
  order IS file order.
- ``flush(seq)`` blocks until everything up to ``seq`` is in the OS
  (and fsynced, per policy). The first waiter becomes the **leader**:
  it swaps the pending buffer out, issues ONE ``write()`` (and at most
  one ``fsync``) for the whole batch, then wakes every follower whose
  records the batch covered. Writers that arrive mid-flush land in the
  next batch — concurrent commit barriers coalesce with no artificial
  delay, and a lone writer pays exactly one syscall, same as before.
- A shared background flusher bounds how long un-barriered records can
  linger in userspace (``PILOSA_TPU_WAL_WINDOW_MS``, default 2 ms): a
  WAL that sits in a process buffer indefinitely is not a WAL.

Durability contract (documented in docs/STORAGE.md): a mutation is
**acked** when its commit barrier returns — the serving layer calls
``barrier_all()`` before acknowledging any write request, so the
HTTP-level contract is exactly the vintage one (acked ⇒ record in the
OS, surviving process death) with the syscalls amortized across every
record the batch covers. The fsync policy upgrades that to power-loss
durability:

    PILOSA_TPU_WAL_FSYNC=none    (default) flush = write(); fsync only
                                 at snapshot — the vintage contract
    PILOSA_TPU_WAL_FSYNC=group   commit barriers fsync the batch: acked
                                 ⇒ on stable storage, one fsync per
                                 leader flush regardless of writer count
    PILOSA_TPU_WAL_FSYNC=always  every leader flush fsyncs (the A/B
                                 baseline the bench compares group
                                 commit against)

``PILOSA_TPU_WAL_GROUP=0`` removes the layer entirely (fragments
attach their file as the op writer and every append is a syscall, the
pre-group-commit behavior).

The ``wal.append`` failpoint fires at the LEADER's write with the
whole batch blob, so torn-write injection tears the file exactly where
a crash mid-group-commit would: at an arbitrary byte offset of a
multi-record batch. A failed/torn leader write truncates the file back
to the durable prefix (appended bytes are always past the open-time
mmap length, so mapped views stay valid), keeps the whole batch
pending, and raises to its waiters — the ops are unacked but retryable,
and a later barrier re-writes the batch cleanly. Only if the truncate
itself fails does the log fail-stop until the next snapshot swap hands
it a fresh file. A real crash (no truncate) leaves a torn tail that
reopen trims to the last complete record — never past an acked one.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..fault import diskfull as _diskfull
from ..fault import failpoints as _fp
from ..obs import accounting as _accounting
from ..obs import metrics as obs_metrics

OP_SIZE = 13  # one op record (storage.roaring.OP_SIZE; kept in sync)

# Pending bytes past which append() flushes inline instead of letting
# the buffer grow unboundedly (a 10M-bit import would otherwise hold
# 130 MB of records in userspace before its barrier).
_BUF_MAX = 1 << 18

FSYNC_NONE = "none"
FSYNC_GROUP = "group"
FSYNC_ALWAYS = "always"


def _fsync_policy() -> str:
    v = os.environ.get("PILOSA_TPU_WAL_FSYNC", FSYNC_NONE).strip().lower()
    return v if v in (FSYNC_NONE, FSYNC_GROUP, FSYNC_ALWAYS) else FSYNC_NONE


def group_enabled() -> bool:
    return os.environ.get("PILOSA_TPU_WAL_GROUP", "1") != "0"


def window_s() -> float:
    try:
        return float(os.environ.get("PILOSA_TPU_WAL_WINDOW_MS", "2")) / 1e3
    except ValueError:
        return 0.002


class WalError(OSError):
    """A leader flush failed; records past the durable prefix are in
    memory only until the next snapshot swap resets the log."""


class GroupCommitWal:
    """One fragment op-log with group-committed appends (see module
    docstring). Presents ``write()`` so it can stand wherever a plain
    file-like op writer did."""

    __slots__ = ("_file", "_base", "_mu", "_cond", "_pending",
                 "_seq_appended", "_seq_flushed", "_seq_synced",
                 "_leader", "_fail", "_registered", "fsync_policy",
                 "fsyncs", "flushes", "closed")

    def __init__(self, file, fsync_policy: Optional[str] = None):
        self._file = file
        # File offset where this WAL's records begin (current EOF):
        # seq s lives at byte _base + s, which is how a failed leader
        # write can ftruncate back to exactly the durable prefix. Every
        # appended byte is past the open-time mmap length, so the
        # truncate can never invalidate mapped container views.
        self._base = file.seek(0, os.SEEK_END) if file is not None else 0
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._pending = bytearray()
        # Sequence numbers are cumulative appended-byte counts since
        # attach — monotone across file swaps (the swap resets the
        # FILE, not the ordering contract).
        self._seq_appended = 0
        self._seq_flushed = 0
        self._seq_synced = 0
        self._leader = False
        self._fail: Optional[BaseException] = None
        self._registered = False  # in the process dirty set
        self.fsync_policy = (fsync_policy if fsync_policy is not None
                             else _fsync_policy())
        self.fsyncs = 0   # plain-int counters (GIL-coarse, stats only)
        self.flushes = 0
        self.closed = False

    # -- append (the mutation hot path) --------------------------------------

    def append(self, blob: bytes) -> int:
        """Buffer ``blob`` (one or more whole op records); returns the
        commit sequence to pass to ``flush``. No syscall unless the
        pending buffer is past ``_BUF_MAX``."""
        with self._mu:
            self._pending += blob
            self._seq_appended += len(blob)
            seq = self._seq_appended
            big = len(self._pending) >= _BUF_MAX
            if not self._registered:
                # Register BEFORE any inline flush: _registered must
                # imply dirty-set membership, or a racing append that
                # lands mid-leader-write leaves pending records no
                # barrier_all()/flusher pass can see (registry lock is
                # a leaf, safe under _mu).
                self._registered = True
                _register_dirty(self)
        if big:
            self.flush(seq, sync=self.fsync_policy == FSYNC_ALWAYS)
        return seq

    # File-like compatibility: roaring._wal_write calls writer.write().
    write = append

    def pending_bytes(self) -> int:
        with self._mu:
            return len(self._pending)

    def durable_seq(self) -> int:
        with self._mu:
            return self._seq_flushed

    # -- flush / commit barrier ----------------------------------------------

    def flush(self, seq: Optional[int] = None,
              sync: Optional[bool] = None) -> None:
        """Block until everything up to ``seq`` (default: everything
        appended so far) is written to the OS — and fsynced when
        ``sync`` (default: per the fsync policy). First waiter leads;
        the rest follow. Raises the leader's error if its write
        failed."""
        if sync is None:
            sync = self.fsync_policy != FSYNC_NONE
        t0 = 0.0
        with self._mu:
            if seq is None:
                seq = self._seq_appended
            while True:
                if self.closed:
                    # A closed WAL never writes again: the orderly
                    # close barriers BEFORE closing, so anything still
                    # pending here is an abandoned (crash-simulated or
                    # snapshot-superseded) batch the background flusher
                    # must not resurrect onto the old fd.
                    return
                if self._fail is not None and seq > self._seq_flushed:
                    raise WalError("wal: group flush failed") \
                        from self._fail
                if (self._seq_flushed >= seq
                        and (not sync or self._seq_synced >= seq)):
                    if not self._pending and self._registered:
                        # A racing append's deferred registration can
                        # land after the flush that drained it; clear
                        # the stale entry (registry lock is a leaf).
                        self._registered = False
                        _deregister_dirty(self)
                    if t0:
                        _note_wait(time.perf_counter() - t0)
                    return
                if not self._leader:
                    break
                # A leader is mid-flush: wait for it, then re-check.
                if not t0:
                    t0 = time.perf_counter()
                self._cond.wait()
            # Become the leader. Pending stays intact until the write
            # SUCCEEDS — a failed/torn write truncates the file back to
            # the durable prefix and the whole batch remains queued, so
            # a later barrier (or the background flusher) retries it
            # cleanly instead of leaving the log poisoned.
            self._leader = True
            batch = bytes(self._pending)
            flushed_before = self._seq_flushed
            file = self._file
        err: Optional[BaseException] = None
        recovered = False
        ft0 = time.perf_counter()
        try:
            if batch:
                if _fp.ACTIVE is not None:
                    # The torn-write injection point: a crash mid
                    # group commit tears the GROUPED batch at an
                    # arbitrary byte offset, not one record.
                    _fp.ACTIVE.hit("wal.append", writer=file, data=batch)
                file.write(batch)
            if sync and not self.closed:
                os.fsync(file.fileno())
                self.fsyncs += 1
                obs_metrics.WAL_FSYNCS.inc()
        except BaseException as e:  # noqa: BLE001 — must wake waiters
            err = e
            # A full disk is a NODE condition, not this WAL's: flip
            # the process write-unready (fault.diskfull) so the
            # serving layer answers 507 + Retry-After instead of
            # letting every write query rediscover the same wall.
            # The batch stays pending either way — recovery retries
            # it cleanly.
            _diskfull.note_if_enospc(e, "wal.append",
                                     getattr(file, "name", None))
            try:
                # An arbitrary prefix of the batch may be on disk; cut
                # the file back to the durable prefix so retries (and
                # crash replay) see only whole acked records. Appended
                # bytes all sit past the open-time mmap length, so no
                # mapped container view is invalidated.
                os.ftruncate(file.fileno(),
                             self._base + flushed_before)
                recovered = True
            except Exception:
                recovered = False  # fail-stop until the snapshot swap
        else:
            if batch:
                # Successful durable write: the cheapest possible
                # recovery signal when the node was write-unready.
                _diskfull.note_write_ok()
                if _archive_sinks:
                    # Still the leader here, so per-WAL sink order is
                    # commit order (the PITR replay contract).
                    _archive_notify(file, batch)
        el = time.perf_counter() - ft0
        with self._mu:
            self._leader = False
            if err is None:
                del self._pending[:len(batch)]
                self._seq_flushed = flushed_before + len(batch)
                if sync:
                    self._seq_synced = self._seq_flushed
                if batch:
                    self.flushes += 1
                    obs_metrics.WAL_GROUP_BATCH_SIZE.observe(
                        len(batch) // OP_SIZE)
                    obs_metrics.WAL_GROUP_FLUSH_SECONDS.observe(el)
            elif not recovered:
                self._fail = err
            self._cond.notify_all()
            if err is not None:
                raise WalError("wal: group flush failed") from err
            if self._pending:
                # Another batch formed while we wrote; the WAL stays
                # registered and the flusher re-arms on it.
                _flusher_wake.set()
                return
            # Clear-and-discard must be atomic under _mu (registry
            # lock is a leaf): clearing first and discarding after
            # releasing would let a racing append re-register in
            # between, then be discarded — pending records invisible
            # to barrier_all().
            self._registered = False
            _deregister_dirty(self)
        if t0:
            _note_wait(time.perf_counter() - t0)

    def barrier(self) -> None:
        """Commit barrier at the configured durability level: returns
        once every record appended so far is durable per policy."""
        self.flush(None, sync=self.fsync_policy != FSYNC_NONE)

    # -- lifecycle -----------------------------------------------------------

    def reset_file(self, file, clear_pending: bool = False) -> None:
        """Swap the backing file (snapshot rename path). The caller
        guarantees no appends are racing (fragment holds its mutation
        lock) and that pending records were either flushed to the OLD
        file or are covered by the new snapshot body
        (``clear_pending``). Clears any failed state: the new file is
        clean."""
        with self._mu:
            self._file = file
            self._fail = None
            self._base = (file.seek(0, os.SEEK_END)
                          if file is not None else 0)
            if clear_pending:
                self._pending.clear()
                self._seq_flushed = self._seq_appended
                self._seq_synced = self._seq_appended
            if file is None:
                self._registered = False
                _deregister_dirty(self)

    def close(self) -> None:
        with self._mu:
            self.closed = True
            self._cond.notify_all()  # release any blocked followers
            self._registered = False
            _deregister_dirty(self)


# -- process-wide dirty registry + barrier ------------------------------------
# Every WAL with un-flushed records registers here; the serving layer's
# ack point (write queries, imports) calls barrier_all() so the
# HTTP-level durability contract holds no matter how many fragments a
# request touched — and concurrent requests' barriers coalesce into
# one leader flush per WAL.

_dirty_mu = threading.Lock()
_dirty: set = set()
# When each dirty WAL first registered (monotonic): the watchdog's
# wedged-flusher detector is "some WAL has been dirty longer than the
# stall threshold" — a healthy flusher drains within ~one window.
_dirty_since: dict = {}
_flusher: Optional[threading.Thread] = None
_flusher_wake = threading.Event()
# Flusher heartbeat: stamped at the top of every flusher pass. A
# heartbeat that stops while WALs stay dirty means the flusher thread
# itself is wedged (stuck in a leader write), not merely idle.
_flusher_beat = 0.0


def _note_wait(seconds: float) -> None:
    cost = _accounting.current_cost()
    if cost is not None:
        cost.note_wal_wait(seconds)


# -- archive sinks (pilosa_tpu.backup WAL-segment archiving) ------------------
# A server with continuous WAL archiving registers a sink keyed by its
# data-dir root; every successfully committed batch whose file lives
# under that root is handed to the sink (file path + batch bytes) while
# the committing leader still holds leadership — per-WAL batch order is
# exactly commit order, which the point-in-time replay contract needs.
# Process-global because the WAL layer is: multiple servers in one
# process (the test suite) each claim only their own subtree.

_archive_mu = threading.Lock()
_archive_sinks: dict = {}


def register_archive_sink(root: str, fn) -> None:
    """Route committed batches of WALs under ``root`` (a data dir) to
    ``fn(file_path, batch_bytes)``. The sink must be fast and must not
    raise into the commit path (errors are swallowed here — archiving
    is asynchronous durability, never a write-ack dependency)."""
    with _archive_mu:
        _archive_sinks[os.path.abspath(root)] = fn


def deregister_archive_sink(root: str) -> None:
    with _archive_mu:
        _archive_sinks.pop(os.path.abspath(root), None)


def _archive_notify(file, batch: bytes) -> None:
    name = getattr(file, "name", None)
    if not isinstance(name, str):
        return
    name = os.path.abspath(name)
    with _archive_mu:
        sinks = list(_archive_sinks.items())
    for root, fn in sinks:
        if name.startswith(root + os.sep):
            try:
                fn(name, batch)
            except Exception:  # noqa: BLE001 - archiving never fails a commit
                pass
            return


def _register_dirty(wal: GroupCommitWal) -> None:
    global _flusher
    with _dirty_mu:
        _dirty.add(wal)
        _dirty_since.setdefault(wal, time.monotonic())
        if _flusher is None:
            _flusher = threading.Thread(target=_flush_loop,
                                        name="wal-group-flusher",
                                        daemon=True)
            _flusher.start()
    _flusher_wake.set()


def _deregister_dirty(wal: GroupCommitWal) -> None:
    with _dirty_mu:
        _dirty.discard(wal)
        _dirty_since.pop(wal, None)


def flusher_health() -> dict:
    """The WAL flusher's vital signs for the stall watchdog and the
    blackbox: the dirty set with per-WAL pending bytes + dirty age
    (worst first), the oldest dirty age, and the heartbeat age. All
    reads are lock-leaf cheap — safe from a 1 Hz watchdog."""
    now = time.monotonic()
    with _dirty_mu:
        items = [(w, t) for w, t in _dirty_since.items()
                 if w in _dirty]
        beat = _flusher_beat
    wals = []
    for w, t in items:
        try:
            pending = w.pending_bytes()
        except Exception:  # noqa: BLE001 - a wedged WAL must still report
            pending = -1
        wals.append({"file": getattr(w._file, "name", None) or "?",
                     "pendingBytes": pending,
                     "dirtyAgeS": round(now - t, 4)})
    wals.sort(key=lambda e: -e["dirtyAgeS"])
    return {
        "dirtyWals": len(wals),
        "oldestDirtyAgeS": wals[0]["dirtyAgeS"] if wals else 0.0,
        "flusherBeatAgeS": (round(now - beat, 4) if beat else None),
        "windowS": window_s(),
        "wals": wals[:8],
    }


def barrier_all() -> None:
    """Flush every dirty WAL at its configured durability level — the
    serving layer's pre-ack commit barrier."""
    with _dirty_mu:
        wals = list(_dirty)
    for wal in wals:
        try:
            wal.barrier()
        except WalError:
            if not wal.closed:
                raise


def _flush_loop() -> None:
    """Bounded-latency background flusher: any record a writer never
    barriers reaches the OS within ~one window (plus write time)."""
    global _flusher_beat
    while True:
        _flusher_wake.wait()
        _flusher_wake.clear()
        time.sleep(window_s())
        with _dirty_mu:
            _flusher_beat = time.monotonic()
            wals = list(_dirty)
        for wal in wals:
            if wal.closed:
                with wal._mu:
                    wal._registered = False
                    _deregister_dirty(wal)
                continue
            try:
                wal.flush(None,
                          sync=wal.fsync_policy == FSYNC_ALWAYS)
            except WalError:
                # Drop it from the dirty set so the loop doesn't
                # retry a failing disk every window — but clear
                # _registered with it, so the owner's NEXT append
                # re-registers and its barrier surfaces the error
                # (leaving _registered set would make barrier_all()
                # skip this WAL forever: acked-but-volatile).
                with wal._mu:
                    wal._registered = False
                    _deregister_dirty(wal)
        # Re-arm while anything stays dirty (a flush that early-returns
        # because a batch formed mid-write leaves records pending with
        # no new registration event to wake us): the window bound must
        # hold without relying on future appends.
        with _dirty_mu:
            if _dirty:
                _flusher_wake.set()
