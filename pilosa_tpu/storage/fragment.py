"""Fragment: the storage unit at one (frame, view, slice) intersection.

Reference: fragment.go. A fragment owns one file-backed roaring bitmap
holding a rows × 2^20-column block; bit position
``pos = row * SLICE_WIDTH + (col % SLICE_WIDTH)`` (fragment.go:1511-1514).

Durability model (identical to the reference):
- data file = roaring snapshot + appended op-log (WAL); ops replay on open
- every mutation appends an op; after MAX_OP_N ops the file is atomically
  rewritten (temp + rename) and remapped (fragment.go:63-65,991-1057)
- TopN cache ids are checkpointed to a ``.cache`` protobuf sidecar
  (fragment.go:1067-1093)

TPU-first departures:
- TopN candidate ranking reads numpy rank arrays straight off the caches
  and src intersection counts come from ONE vectorized pass over the
  fragment (cached per src × mutation epoch), then the reference's
  sequential heap/threshold semantics (fragment.go:490-625) replay over
  the precomputed counts — same results, no per-row walks. Device
  serving of TopN (cross-slice batched exact counts, HBM-resident
  candidate blocks) lives at the executor layer under the calibrated
  cost model (executor._topn_exact_resident).
- block checksums hash vectorized position spans (numpy → sha1) instead of
  iterator walks; MergeBlock consensus is a vectorized multiset vote.
"""

from __future__ import annotations

import fcntl
import hashlib
import heapq
import math
import mmap
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import SLICE_WIDTH
from ..errors import PilosaError
from ..fault import failpoints as _fp
from ..obs import accounting as _accounting
from ..obs import metrics as obs_metrics
from ..parallel.residency import DeviceRowCache
from ..proto import internal_pb2 as pb
from ..utils import logger as logger_mod
from ..utils import arrays as arrays_mod
from ..utils.arrays import sort_dedupe
from ..utils.streams import CappedReader
from . import cache as cache_mod
from . import integrity as integrity_mod
from . import roaring
from . import wal as wal_mod
from .bitmap import Bitmap
from .cache import Pair

# Number of operations before a snapshot rewrite (reference
# fragment.go:63-65). The reference default was 2000, sized for an era
# when every op paid its own write() and replay was a scalar walk; with
# the group-committed WAL (appends are buffered memcpy, one leader
# write per batch) and the vectorized replay lane, a 50 K-record log
# (~650 KB) reopens in milliseconds while the snapshot freeze —
# measured at ~15 ms of table patching per trigger — stops eating the
# per-op write budget 25× as often. Env-overridable so longevity
# harnesses can force snapshot storms (benchmarks/soak.py) without
# patching the module.
MAX_OP_N = int(os.environ.get("PILOSA_TPU_MAX_OP_N", "50000"))

# Replay-cost weight of one bulk-import blob position relative to one
# discrete op record: a blob's single add-run replays through the
# vectorized add_many lane (~0.06 us/bit) where mixed discrete tails
# pay the scalar/small-run walk (~1 us/op), so a blob bit contributes
# ~1/16th the reopen-replay pressure MAX_OP_N exists to bound.
_BLOB_OP_WEIGHT = 16

# Rows per checksum block (reference fragment.go:59).
HASH_BLOCK_SIZE = 100

# Run the cardinality-adaptive container-representation pass
# (roaring.Bitmap.optimize — array/bitmap/run selection per the Roaring
# papers) after bulk imports. On by default; settable off to pin the
# two-kind vintage behavior for comparisons (benchmarks/suite.py
# container_mix measures exactly this delta).
_RUN_OPTIMIZE = os.environ.get("PILOSA_TPU_RUN_CONTAINERS", "1") != "0"


@dataclass
class TopOptions:
    """Options for Fragment.top (reference fragment.go TopOptions)."""
    n: int = 0
    src: Optional[Bitmap] = None
    row_ids: list[int] = field(default_factory=list)
    filter_field: str = ""
    filter_values: list = field(default_factory=list)
    min_threshold: int = 0
    tanimoto_threshold: int = 0


@dataclass
class PairSet:
    """Parallel row/column id arrays (reference fragment.go PairSet)."""
    row_ids: np.ndarray
    column_ids: np.ndarray

    @staticmethod
    def empty() -> "PairSet":
        z = np.empty(0, dtype=np.uint64)
        return PairSet(z, z)


# Matched-position budget before folding src-count hits into a
# partial (ids, counts) map; module-level so tests can shrink it to
# exercise the multi-partial merge.
_SRC_FOLD_POSITIONS = 1 << 20

# Fragments at or under this many set bits take the all-positions
# vectorized src-count pass (8 B/bit peak -> <=128 MB); bigger ones
# keep the bounded chunked walk.
_SRC_VECTOR_BITS = 16 << 20

# Largest position vector kept resident per fragment (8 B each ->
# 32 MB); larger ones are rebuilt per pass instead of pinned.
_POSITIONS_CACHE_BITS = 4 << 20

# Entries kept in the incremental per-row count map before a reset
# (bounds memory on fragments with millions of distinct rows).
_ROW_COUNT_CAP = 1 << 16

# Snapshots between full close/remap cycles (see Fragment.snapshot).
_REMAP_EVERY = 16

# Largest bulk import the WAL-first lane holds as op records (13 B per
# position) before falling back to the vintage detach-then-snapshot
# contract — bounds transient log growth between snapshot cadences.
_WAL_IMPORT_MAX_BYTES = 32 << 20


class Fragment:
    def __init__(self, path: str, index: str, frame: str, view: str,
                 slice: int, cache_type: str = cache_mod.DEFAULT_CACHE_TYPE,
                 cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
                 row_attr_store=None, stats=None, logger=logger_mod.NOP,
                 quarantine=None):
        self.logger = logger
        self.path = path
        self.index = index
        self.frame = frame
        self.view = view
        self.slice = slice
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.row_attr_store = row_attr_store

        # Storage integrity (storage.integrity): the holder-level
        # quarantine registry (None for bare library fragments), the
        # quarantine flag gating the READ path, and the lazy
        # first-read verification latch (armed on every open of a
        # footered snapshot; one attr check on the read hot path).
        self.quarantine = quarantine
        self.quarantined = False
        self.quarantine_reason = ""
        self._verify_pending = False

        # Tiered storage (pilosa_tpu.tier): the TierManager hook (None
        # for bare library fragments — one attr check on the read hot
        # path keeps the gate free when tiering is off), the residency
        # state ("hot" | "cold" | "blob"), and — while cold — the set
        # of container-block indices not yet faulted in. Cold
        # fragments hold their full checksummed file on local disk;
        # reads fault exactly the blocks they touch, verifying each
        # against the footer's per-block crc table (the block map).
        # Blob fragments hold only a ``<path>.blob`` stub; the first
        # gated read fetches + verifies the file back from the blob
        # store and re-enters cold.
        self.tier = None
        self.tier_state = "hot"
        self._cold_pending: Optional[set] = None

        self.storage: Optional[roaring.Bitmap] = None
        self.cache = None                       # rank/lru count cache
        self._cache_flushed = None              # last flush_cache blob
        self.row_cache = cache_mod.SimpleCache()
        self.device = DeviceRowCache()
        self.checksums: dict[int, bytes] = {}
        self.stats = stats
        # src-TopN count maps, keyed by src-content hash, valid for one
        # mutation epoch (both TopN phases and repeat queries reuse
        # the one O(fragment bits) pass). Value: (epoch, (ids, counts)).
        self._src_counts: dict[
            bytes, tuple[int, tuple[np.ndarray, np.ndarray]]] = {}
        # Incremental per-row bit counts: single-bit mutations adjust by
        # +-1 instead of recounting the row (a full row_count walk costs
        # ~85 us vs ~1 us here — it was more than a third of the whole
        # SetBit path). Entries are exact post-mutation counts; absent
        # rows fall back to one row_count. Reset on bulk rewrites.
        self._row_counts: dict[int, int] = {}
        self._epoch = 0
        self._snapshot_n = 0
        # True once the count cache provably covers every present row
        # (set by _repair_cache_completeness on open; mutations maintain
        # coverage, LRU eviction is gated by consumers on len>=max).
        self._cache_complete = False

        # Group-commit WAL wrapper around the data file (storage.wal):
        # mutation paths APPEND records (no syscall); commit barriers
        # (wal_barrier / the serving layer's barrier_all before ack)
        # flush batches with one write()+fsync-per-policy. None when
        # PILOSA_TPU_WAL_GROUP=0 (the vintage write-through path).
        self._wal: Optional[wal_mod.GroupCommitWal] = None

        self._mu = threading.RLock()
        # Snapshot lifecycle lock. Ordering rule: ALWAYS acquired
        # BEFORE _mu when blocking (sync snapshot, close, restore);
        # the per-op async trigger — which runs UNDER _mu — only
        # try-acquires and skips when busy, so the order cannot
        # invert. Held by the background worker for its whole run
        # (released cross-thread in its finally), so "with _snap_mu"
        # doubles as the join barrier.
        self._snap_mu = threading.Lock()
        self._file = None
        self._mmap: Optional[mmap.mmap] = None
        self._open = False

    # -- lifecycle -----------------------------------------------------------

    @property
    def cache_path(self) -> str:
        return self.path + ".cache"

    def open(self) -> None:
        with self._mu:
            if self._open:
                return
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # The one-crossing mutate extension (built once, cached):
            # serving fragments are where the per-op path runs hot.
            from . import native_ext
            native_ext.load()
            self.cache = cache_mod.new_cache(self.cache_type, self.cache_size)
            stub = self.path + ".blob"
            if os.path.exists(stub):
                if os.path.exists(self.path):
                    # Crash between blob fetch-replace and stub
                    # removal: the DATA FILE WINS — it was verified
                    # before the os.replace, while a re-fetch could
                    # fail. Drop the stale stub and open normally.
                    try:
                        os.remove(stub)
                    except OSError:
                        pass
                else:
                    # Blob-tier fragment: no local bytes, no storage.
                    # The first gated read fetches + verifies the file
                    # back through the tier manager; ungated access
                    # fails loudly (storage is None) — never a guess.
                    self.tier_state = "blob"
                    self.storage = None
                    self._open = True
                    return
            self._open_storage_quarantining(verify=True)
            if not self.quarantined and os.path.exists(
                    self.path + ".corrupt"):
                # A prior quarantine replaced the data file with a
                # fresh one and the process died before repair
                # completed — the aside file is the crash-safe
                # sentinel. Without it a restart would serve the
                # near-empty replacement as authoritative (a silent
                # wrong answer, the one thing this subsystem exists
                # to prevent). The repairer removes the sentinel when
                # the replica re-stream verifies clean.
                self._set_quarantined(
                    "pending repair (restart before repair completed)",
                    site="open")
            self._open_cache()
            self._open = True

    def _open_storage_quarantining(self, verify: bool = False) -> None:
        """_open_storage, but a file whose bytes contradict their
        checksums (or no longer parse at all) QUARANTINES the fragment
        instead of bricking the open: the corrupt file moves aside
        (``<path>.corrupt`` — forensics + ``check --deep``), a fresh
        empty snapshot takes its place so writes keep buffering
        through the WAL, reads fail over to a replica (executor
        consults the registry), and the repairer re-streams the
        content (docs/FAULT_TOLERANCE.md)."""
        try:
            self._open_storage(verify=verify)
        except (ValueError, integrity_mod.CorruptionError) as e:
            self.logger.printf(
                "fragment: CORRUPT storage %s/%s/%s/%d: %s — "
                "quarantining", self.index, self.frame, self.view,
                self.slice, e)
            self._close_storage()
            try:
                os.replace(self.path, self.path + ".corrupt")
            except FileNotFoundError:
                pass
            self._open_storage()
            self._set_quarantined(f"open: {e}", site="open")

    def _open_storage(self, verify: bool = False) -> None:
        # Open (creating) the data file, flock it, seed empty files with an
        # empty snapshot header, map, replay snapshot + op-log, then attach
        # the op writer for subsequent mutations (reference
        # fragment.go:179-234).
        # buffering=0: each op record hits the OS immediately — a WAL that
        # lingers in a userspace buffer is not a WAL.
        # ``storage.read`` failpoint: the deterministic injection site
        # for on-disk corruption (corrupt mode flips real bits in the
        # file before it is read back — fault.failpoints).
        if _fp.ACTIVE is not None:
            _fp.ACTIVE.hit("storage.read", path=self.path)
        self._file = open(self.path, "a+b", buffering=0)
        fcntl.flock(self._file.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        self._file.seek(0, os.SEEK_END)
        if self._file.tell() == 0:
            # Seed with a footered empty snapshot so integrity
            # coverage starts at file birth.
            roaring.Bitmap().write_to(self._file, footer=True)
        self._mmap = mmap.mmap(self._file.fileno(), 0, prot=mmap.PROT_READ)
        self.storage = roaring.Bitmap.unmarshal(self._mmap, mapped=True,
                                                tolerate_torn_tail=True,
                                                verify_body=verify)
        if self.storage.torn_bytes:
            # Crash mid-append left a partial op record (or a torn
            # footer); the WAL is append-only so the tail is the only
            # casualty — trim it.
            size = self._file.seek(0, os.SEEK_END)
            self.storage.unmap()
            self._mmap = None
            os.ftruncate(self._file.fileno(), size - self.storage.torn_bytes)
            self._file.seek(0, os.SEEK_END)
            self._mmap = mmap.mmap(self._file.fileno(), 0,
                                   prot=mmap.PROT_READ)
            self.storage = roaring.Bitmap.unmarshal(self._mmap, mapped=True,
                                                    verify_body=verify)
        # Arm the lazy per-block verification: the first READ after an
        # open re-checks every container block's crc against the mmap
        # (the first-fault re-verification the footer exists for);
        # until then only the footer/header crcs have been checked.
        self._verify_pending = self.storage.footer is not None
        if wal_mod.group_enabled():
            self._wal = wal_mod.GroupCommitWal(self._file)
            self.storage.op_writer = self._wal
        else:
            self._wal = None
            self.storage.op_writer = self._file

    def _open_cache(self) -> None:
        # Re-rank persisted ids with counts from storage
        # (reference fragment.go:236-274).
        ids = []
        try:
            with open(self.cache_path, "rb") as f:
                ids = pb.Cache.FromString(f.read()).IDs
        except FileNotFoundError:
            pass
        except Exception:
            # The cache is advisory and reconstructible; a corrupt sidecar
            # (e.g. torn by a crash) must not brick the fragment.
            pass
        for rid in ids:
            self.cache.bulk_add(rid, self.row_count(rid))
        self._repair_cache_completeness()
        self.cache.recalculate()

    def _repair_cache_completeness(self) -> None:
        """The sidecar lags reality by up to one flush interval: rows
        first written after the last flush exist in the replayed WAL
        but not in the persisted id list, so after a crash the count
        cache silently misses them (review r5 — the single-pass TopN
        sums only cache entries, and would under-rank those rows).
        Detect by cardinality (exact per-row counts must sum to the
        storage total), repair from present_rows when the fragment is
        small enough to dump positions, else leave the cache flagged
        incomplete — consumers needing completeness (the single-pass
        TopN leg) then fall back to the recounting path."""
        total = self.storage.count()
        cached = 0
        if hasattr(self.cache, "_od"):
            cached = sum(self.cache._od.values())
        elif hasattr(self.cache, "entries"):
            cached = sum(self.cache.entries.values())
        if cached == total:
            self._cache_complete = True
            return
        if total <= _POSITIONS_CACHE_BITS:
            present = self.present_rows()
            if present is not None:
                for rid in present.tolist():
                    self.cache.bulk_add(rid, self.row_count(rid))
                self._cache_complete = True
                return
        self._cache_complete = False

    # -- storage integrity (storage.integrity; docs/FAULT_TOLERANCE.md) ------

    def _set_quarantined(self, reason: str, site: str) -> None:
        """Mark this fragment's local copy untrustworthy: the executor
        stops serving its slice locally (reads fail over through the
        breaker-ordered placement), anti-entropy stops letting it
        vote, and the repairer re-streams it from a replica. Writes
        keep applying (WAL-buffered) — they also fan out to every
        replica owner, so the repaired copy includes them."""
        obs_metrics.STORAGE_CORRUPTION.labels(site).inc()
        self.quarantined = True
        self.quarantine_reason = reason
        # Tail sampling: the query that tripped over the corruption is
        # keep-worthy evidence (obs.sampler reason "corruption").
        from ..sched import context as sched_context
        ctx = sched_context.current()
        if ctx is not None:
            ctx.note_flag("corruption")
        if self.quarantine is not None:
            if self.quarantine.add(self, reason):
                obs_metrics.STORAGE_QUARANTINED.inc()
            obs_metrics.STORAGE_QUARANTINED_LIVE.set(
                len(self.quarantine))
        else:
            obs_metrics.STORAGE_QUARANTINED.inc()
        self.logger.printf(
            "fragment: quarantined %s/%s/%s/%d (%s): %s", self.index,
            self.frame, self.view, self.slice, site, reason)

    def clear_quarantine(self) -> None:
        """Repair complete: the local copy is trustworthy again. The
        ``.corrupt`` aside file goes too — it doubles as the crash-safe
        quarantine sentinel (see open()), so leaving it would
        re-quarantine a REPAIRED fragment at the next restart."""
        try:
            os.remove(self.path + ".corrupt")
        except FileNotFoundError:
            pass
        except OSError as e:
            # A lingering sentinel re-quarantines this fragment at
            # every restart (and re-streams it for nothing) — say so
            # loudly instead of hiding the why.
            self.logger.printf(
                "fragment: could not remove quarantine sentinel"
                " %s.corrupt (%s) — the fragment will re-quarantine"
                " at the next restart until it is removed",
                self.path, e)
        self.quarantined = False
        self.quarantine_reason = ""
        if self.quarantine is not None:
            self.quarantine.remove(self)
            obs_metrics.STORAGE_QUARANTINED_LIVE.set(
                len(self.quarantine))

    def _verify_on_read(self) -> None:
        """Lazy per-block verification on the FIRST read after an open
        (the mmap-fault half of the footer contract): one crc pass over
        the container blocks against the footer table, then free. A
        mismatch quarantines and raises — the executor re-maps the
        slice onto a healthy replica (the same machinery as a failed
        remote leg)."""
        if not self._verify_pending:
            return
        self._verify_pending = False
        storage = self.storage
        info = getattr(storage, "footer", None)
        mm = self._mmap
        if info is None or mm is None:
            return
        bad = integrity_mod.verify_blocks(mm, info)
        obs_metrics.STORAGE_SCRUB_BLOCKS.labels("read").inc(
            info.block_n)
        if bad:
            self._set_quarantined(
                f"container block crc mismatch (blocks {bad[:4]},"
                f" {len(bad)} total)", site="read")
            raise integrity_mod.CorruptionError(
                f"fragment {self.index}/{self.frame}/{self.view}/"
                f"{self.slice}: {len(bad)} container blocks fail crc")

    # -- tiered storage (pilosa_tpu.tier; docs/STORAGE.md) --------------------

    def demote_cold(self) -> int:
        """Demote this fragment to the cold tier: WAL barrier, fold
        any op-log tail into a fresh checksummed snapshot, flush the
        TopN cache sidecar, then reopen metadata-only — header parsed,
        footer attached, NO container block read or verified. Returns
        the cold file's byte size (0 = demotion didn't apply: already
        cold, quarantined, torn WAL, or a footerless legacy file).
        OSError (ENOSPC mid-snapshot) propagates — the old file stays
        the record and the fragment stays hot."""
        with self._snap_mu:
            with self._mu:
                if (not self._open or self.quarantined
                        or self.tier_state != "hot"
                        or self.storage is None):
                    return 0
                try:
                    self.wal_barrier()
                except wal_mod.WalError:
                    return 0  # torn pending tail: not a clean point
                if (self.storage.op_n > 0
                        or self.storage.footer is None):
                    # Fold the op-log (and footer vintage files) into
                    # a clean footered snapshot — the cold format IS
                    # the PR-15 snapshot format, nothing new on disk.
                    self._snapshot_locked(reason="tier")
                self.flush_cache()
                self._close_storage()
                self._open_storage_quarantining()
                if self.quarantined:
                    return 0
                info = getattr(self.storage, "footer", None)
                if info is None or info.offsets is None:
                    return 0  # stay hot; nothing to fault against
                # The per-block fault gate supersedes the whole-file
                # first-read verify — each block's crc is checked as
                # it faults in instead.
                self._verify_pending = False
                self._cold_pending = set(range(info.block_n))
                self.tier_state = "cold"
                # Drop every derived cache: they hold materialized row
                # data whose residency the demotion exists to reclaim.
                self._epoch += 1
                self.row_cache.clear()
                self.device.invalidate_all()
                self.checksums.clear()
                self._src_counts.clear()
                self._cache_complete = False
                self.cache = cache_mod.new_cache(self.cache_type,
                                                 self.cache_size)
                return os.path.getsize(self.path)

    def tier_rechill(self) -> bool:
        """Reset a cold fragment's fault set (watermark eviction of a
        cold scan's residency): every block goes back to unfaulted and
        re-verifies on its next touch. Cheap — no file I/O."""
        with self._mu:
            if self.tier_state != "cold" or self.storage is None:
                return False
            info = getattr(self.storage, "footer", None)
            if info is None:
                return False
            self._cold_pending = set(range(info.block_n))
            self.row_cache.clear()
            self.device.invalidate_all()
            self._positions = None
            self._present_rows = None
            return True

    def promote(self, trigger: str = "prefetch") -> None:
        """Fully promote to hot (prefetcher / operator action). Blob
        fragments fetch first; cold fragments fault every remaining
        block (each crc-verified) and re-rank the TopN cache."""
        with self._mu:
            if not self._open or self.tier_state == "hot":
                return
            if self.tier_state == "blob":
                self._tier_fetch_locked()
            self._tier_promote_locked(trigger)

    def _tier_gate(self, row_id=None, row_ids=None, full=False,
                   write=False) -> None:
        """The read-path tier gate (caller holds _mu). Hot: stamp the
        ledger and return. Blob: fetch the file back (ColdFetchError
        on failure — the executor degrades, never guesses). Cold:
        fault exactly the container blocks covering the touched
        row(s); whole-fragment reads and writes promote fully."""
        st = self.tier_state
        if st != "hot":
            if st == "blob":
                self._tier_fetch_locked()
            if full or (row_id is None and row_ids is None):
                self._tier_promote_locked("write" if write
                                          else "read")
            else:
                idxs = self._tier_blocks_for(
                    [row_id] if row_ids is None else row_ids)
                self._fault_blocks_locked(idxs)
                if not self._cold_pending:
                    self._tier_promote_locked("read")
        if self.tier is not None:
            self.tier.on_access(self)

    def _tier_blocks_for(self, row_ids) -> list[int]:
        """Pending container-block indices covering ``row_ids``. Block
        i of the footer table is container i in file/key order, and a
        row spans exactly SLICE_WIDTH/65536 consecutive container
        keys — so the block map is two binary searches per row over
        the sorted key array (no container is touched)."""
        pending = self._cold_pending
        if not pending:
            return []
        keys = self.storage._keys_np()
        shift = (SLICE_WIDTH // 65536).bit_length() - 1
        out: set = set()
        for rid in row_ids:
            rid = int(rid)
            lo = int(np.searchsorted(keys, rid << shift, side="left"))
            hi = int(np.searchsorted(keys, (rid + 1) << shift,
                                     side="left"))
            out.update(i for i in range(lo, hi) if i in pending)
        return sorted(out)

    def _fault_blocks_locked(self, idxs) -> None:
        """Fault container blocks in: verify each block's bytes
        against the footer's crc table, then mark it resident. A
        mismatch quarantines (same contract as _verify_on_read) —
        cold data re-verifies on the way back in, so bit rot that
        happened while the fragment slept cannot reach a result."""
        if not idxs:
            return
        t0 = time.perf_counter()
        info = self.storage.footer
        offs, sizes, crcs = info.offsets, info.sizes, info.crcs
        if _fp.ACTIVE is not None:
            # Corrupt mode flips real bits in the file; the PROT_READ
            # MAP_SHARED mmap sees them, so the crc check below is the
            # real detection path, not a simulation. The span confines
            # flips to a block this fault will verify — detection is
            # guaranteed, not a draw against the whole file.
            first = idxs[0]
            _fp.ACTIVE.hit("tier.fault", path=self.path,
                           span=(int(offs[first]), int(sizes[first])))
        mm = self._mmap
        mv = memoryview(mm)
        bad: list[int] = []
        nbytes = 0
        for i in idxs:
            off, size = int(offs[i]), int(sizes[i])
            if (zlib.crc32(mv[off:off + size]) & 0xFFFFFFFF) \
                    != int(crcs[i]):
                bad.append(i)
            nbytes += size
        del mv
        obs_metrics.STORAGE_SCRUB_BLOCKS.labels("read").inc(len(idxs))
        if bad:
            obs_metrics.TIER_FAULTS.labels("corrupt").inc()
            self._set_quarantined(
                f"cold block fault crc mismatch (blocks {bad[:4]},"
                f" {len(bad)} total)", site="read")
            raise integrity_mod.CorruptionError(
                f"fragment {self.index}/{self.frame}/{self.view}/"
                f"{self.slice}: {len(bad)} cold blocks fail crc on"
                f" fault-in")
        self._cold_pending.difference_update(idxs)
        obs_metrics.TIER_FAULTS.labels("ok").inc()
        obs_metrics.TIER_FAULT_SECONDS.observe(
            time.perf_counter() - t0)
        if self.tier is not None:
            self.tier.note_fault(self, nbytes)

    def _tier_promote_locked(self, trigger: str) -> None:
        """Finish promotion to hot (caller holds _mu): fault whatever
        is still pending, then rebuild the TopN rank cache from the
        sidecar — top() on a promoted fragment must rank exactly like
        one that never left."""
        pending = self._cold_pending
        if pending:
            self._fault_blocks_locked(sorted(pending))
        self._cold_pending = None
        self.tier_state = "hot"
        self._open_cache()
        obs_metrics.TIER_PROMOTIONS.labels(trigger).inc()
        if self.tier is not None:
            try:
                size = os.path.getsize(self.path)
            except OSError:
                size = 0
            self.tier.note_promoted(self, size, trigger)

    def _tier_fetch_locked(self) -> None:
        """Materialize a blob-tier fragment back onto local disk
        (caller holds _mu): the manager fetches + verifies the
        reassembled file, we reopen it metadata-only and land in the
        cold tier (the read that triggered this then faults just the
        blocks it needs). No manager/store → ColdFetchError."""
        from ..tier.manager import ColdFetchError
        if self.tier is None:
            raise ColdFetchError(
                f"fragment {self.index}/{self.frame}/{self.view}/"
                f"{self.slice}: blob-tier but no tier manager")
        self.tier.fetch_blob(self)
        self._open_storage_quarantining()
        if self.quarantined:
            raise integrity_mod.CorruptionError(
                f"fragment {self.index}/{self.frame}/{self.view}/"
                f"{self.slice}: fetched blob data failed"
                f" verification")
        info = getattr(self.storage, "footer", None)
        self._verify_pending = False
        self.tier_state = "cold"
        self._cold_pending = (set(range(info.block_n))
                              if info is not None
                              and info.offsets is not None else set())
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        self.tier.note_fetched(self, size)

    def verify_on_disk(self) -> dict:
        """Re-read the data FILE and verify footer + blocks + WAL tail
        — the scrubber's per-fragment pass (storage.scrub). Opens its
        own fd (os.replace swaps pin the old inode, so the read is a
        consistent append-only prefix — the fragment backup trick),
        sizes it under the fragment lock after a commit barrier, and
        quarantines on any corruption verdict."""
        from . import scrub as scrub_mod
        try:
            self.wal_barrier()
        except wal_mod.WalError:
            pass  # torn pending tail: the flushed prefix still verifies
        if _fp.ACTIVE is not None:
            # The scrub leg's deterministic corruption injection site.
            _fp.ACTIVE.hit("storage.read", path=self.path)
        try:
            f = open(self.path, "rb")
        except OSError as e:
            return {"error": f"unreadable: {e}", "coverage": "none"}
        try:
            with self._mu:
                size = os.fstat(f.fileno()).st_size
            mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
            try:
                mv = memoryview(mm)
                try:
                    verdict = scrub_mod.scrub_buffer(mv[:size])
                finally:
                    del mv
            finally:
                mm.close()
        finally:
            f.close()
        if verdict.get("corrupt"):
            self._set_quarantined(
                f"scrub: {verdict.get('error', 'checksum mismatch')}",
                site="scrub")
        return verdict

    def reset_for_repair(self) -> None:
        """Drop the suspect local state ahead of a replica re-stream
        (server.repair): the data file moves aside to ``.corrupt``, a
        fresh footered empty snapshot takes its place, and every
        derived cache resets. Writes racing this land in the fresh
        WAL; reads stay quarantined until the repairer verifies the
        streamed copy and clears the flag. Lock order: _snap_mu (waits
        out any background snapshot worker) then _mu — the
        close/restore discipline."""
        with self._snap_mu, self._mu:
            if not self._open:
                return
            self._close_storage()
            try:
                aside = self.path + ".corrupt"
                if os.path.exists(aside):
                    # An open-time quarantine already moved the original
                    # corrupt bytes aside; keep THAT forensics file.
                    os.remove(self.path)
                else:
                    os.replace(self.path, aside)
            except FileNotFoundError:
                pass
            self._open_storage()
            self._epoch += 1
            self._row_counts.clear()
            self.row_cache.clear()
            self.device.invalidate_all()
            self.checksums.clear()
            self._src_counts.clear()
            self._cache_complete = False
            self.cache = cache_mod.new_cache(self.cache_type,
                                             self.cache_size)

    def close(self) -> None:
        # _snap_mu first (lock order): waits out any worker and blocks
        # new ones for the whole close — the TOCTOU where a writer
        # spawns a worker between a join and the lock acquisition
        # would let the worker swap files on a closed fragment.
        with self._snap_mu:
            self._close_locked()

    def _close_locked(self) -> None:
        with self._mu:
            if not self._open:
                return
            self.flush_cache()
            self._close_storage()
            self.device.invalidate_all()
            self._open = False

    def _close_storage(self) -> None:
        if self._wal is not None:
            # Orderly close = commit barrier: whatever a library caller
            # appended without barriering is durable per policy before
            # the fd goes away.
            try:
                self._wal.barrier()
            except wal_mod.WalError:
                pass  # torn log: reopen trims to the flushed prefix
            self._wal.close()
            self._wal = None
        if self.storage is not None:
            self.storage.op_writer = None
        # Do NOT mmap.close() and do NOT copy containers out
        # (storage.unmap): row-cache entries and escaped query results
        # share zero-copy container views into the map, and those views
        # PIN the mapping — dropping our references lets the OS unmap
        # when the last view is GC'd, while an eager copy-out pays a
        # whole-fragment heap copy (100 MB+ per restore/snapshot of a
        # large slice, measured) for data that is about to be garbage.
        # The old inode stays valid under os.replace, so mapped views
        # never go stale. The fd closes immediately (the mapping
        # outlives it) — but NOT the flock: see the explicit unlock
        # below.
        self._mmap = None
        self._row_counts.clear()
        self.row_cache.clear()
        if self._file is not None:
            # Release the flock EXPLICITLY: mmap dups the fd, and a dup
            # shares the open file description — so while any container
            # view keeps the old map alive, close() alone would leave
            # the lock held and block the next open of this path.
            try:
                fcntl.flock(self._file.fileno(), fcntl.LOCK_UN)
            except OSError:
                pass
            self._file.close()
            self._file = None

    # -- position / row helpers ---------------------------------------------

    def pos(self, row_id: int, column_id: int) -> int:
        min_col = self.slice * SLICE_WIDTH
        if not (min_col <= column_id < min_col + SLICE_WIDTH):
            raise ValueError("column out of bounds")
        return row_id * SLICE_WIDTH + (column_id % SLICE_WIDTH)

    def row(self, row_id: int, check_cache: bool = True,
            update_cache: bool = True) -> Bitmap:
        """Materialize a row as a one-segment result Bitmap of absolute
        column ids (reference fragment.go:338-367)."""
        with self._mu:
            self._verify_on_read()
            if self.tier is not None:
                self._tier_gate(row_id=row_id)
            if check_cache:
                cached = self.row_cache.fetch(row_id)
                if cached is not None:
                    return cached
            data = self.storage.offset_range(self.slice * SLICE_WIDTH,
                                             row_id * SLICE_WIDTH,
                                             (row_id + 1) * SLICE_WIDTH)
            bm = Bitmap()
            bm.add_segment(data, self.slice, writable=False)
            if update_cache:
                self.row_cache.add(row_id, bm)
            return bm

    def pack_row(self, row_id: int, out: np.ndarray,
                 cached: bool = True) -> np.ndarray:
        """Copy one row's packed slice-local words into ``out``.

        ``out`` is a caller-provided zeroed u32[WORDS_PER_SLICE] buffer —
        the executor's mesh fast path fills one [leaf, slice] plane of
        its batched block per call. With ``cached`` (the default for hot
        leaf rows) packed words come from the residency manager's host
        cache, so repeated queries memcpy instead of re-walking roaring
        containers; bulk packs of sets larger than the cache budget pass
        ``cached=False`` to avoid churning the LRU for a 0% hit rate."""
        from ..ops.packed import pack_storage_row
        with self._mu:
            self._verify_on_read()
            if self.tier is not None:
                self._tier_gate(row_id=row_id)
            if cached:
                out[:] = self.device.host_row_words(self.storage, row_id)
            else:
                pack_storage_row(self.storage, row_id, out)
        return out

    def row_count(self, row_id: int) -> int:
        return self.storage.count_range(row_id * SLICE_WIDTH,
                                        (row_id + 1) * SLICE_WIDTH)

    def max_row_id(self) -> int:
        return self.storage.max() // SLICE_WIDTH

    # -- mutation ------------------------------------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            return self._mutate(row_id, column_id, set=True)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            return self._mutate(row_id, column_id, set=False)

    def _mutate(self, row_id: int, column_id: int, set: bool) -> bool:
        # The per-op serving hot path (ISSUE 8): bounds + position
        # arithmetic inlined (pos() was a measured frame at per-op
        # rates; column_id - min_col == column_id % SLICE_WIDTH once
        # bounds-checked), every post-mutate maintenance step on
        # pre-bound locals.
        min_col = self.slice * SLICE_WIDTH
        if not (min_col <= column_id < min_col + SLICE_WIDTH):
            raise ValueError("column out of bounds")
        if self.tier is not None and self.tier_state != "hot":
            # Writes promote fully: the rank cache must cover every
            # row before cache.add maintains it incrementally.
            self._tier_gate(full=True, write=True)
        pos = row_id * SLICE_WIDTH + (column_id - min_col)
        storage = self.storage
        changed = storage.add(pos) if set else storage.remove(pos)
        if not changed:
            return False
        _accounting.note_bits_written(1)
        self._epoch += 1
        self.checksums.pop(row_id // HASH_BLOCK_SIZE, None)
        self.row_cache.invalidate(row_id)
        self.device.invalidate_row(row_id)
        row_counts = self._row_counts
        cur = row_counts.get(row_id)
        if cur is None:
            count = self.row_count(row_id)  # already post-mutation
        else:
            count = cur + (1 if set else -1)
        if len(row_counts) >= _ROW_COUNT_CAP:
            row_counts.clear()
        row_counts[row_id] = count
        self.cache.add(row_id, count)
        if self.stats is not None:
            self.stats.count("setN" if set else "clearN", 1)
        if storage.op_n > MAX_OP_N and not self._snap_mu.locked():
            self.snapshot(sync=False)
        return True

    def set_bits(self, row_ids, column_ids) -> np.ndarray:
        """Batched SetBit: one native crossing for container mutation +
        WAL construction, one group-commit op-log append, batched cache
        maintenance. Durability is identical to per-op set_bit — every
        changed bit has a checksummed WAL record on disk before this
        returns (a crash tears at worst the batch's final partial
        record, which the torn-tail trim on open already handles).
        Returns the sorted changed positions (row*SLICE_WIDTH +
        slice-local col) so callers can map per-op results; its length
        is the newly-set-bit count. The per-op ``set_bit`` stays as the
        single-op fallback (fragment.go:369-459; batching rationale:
        VERDICT r4 item 1)."""
        return self._mutate_batch(row_ids, column_ids, set=True)

    def clear_bits(self, row_ids, column_ids) -> np.ndarray:
        """Batched ClearBit (see set_bits)."""
        return self._mutate_batch(row_ids, column_ids, set=False)

    def _mutate_batch(self, row_ids, column_ids, set: bool) -> np.ndarray:
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(column_ids, dtype=np.uint64)
        if len(rows) != len(cols):
            raise ValueError("row/column id length mismatch")
        if not len(rows):
            return np.empty(0, dtype=np.uint64)
        min_col = self.slice * SLICE_WIDTH
        if (int(cols.min()) < min_col
                or int(cols.max()) >= min_col + SLICE_WIDTH):
            raise ValueError("column out of bounds")
        positions = rows * np.uint64(SLICE_WIDTH) + (
            cols % np.uint64(SLICE_WIDTH))
        return self._mutate_batch_positions(positions, set)

    def _mutate_batch_positions(self, positions: np.ndarray,
                                set: bool) -> np.ndarray:
        row_shift = np.uint64(SLICE_WIDTH.bit_length() - 1)
        with self._mu:
            if self.tier is not None and self.tier_state != "hot":
                self._tier_gate(full=True, write=True)
            changed = self.storage.apply_batch(positions, set=set,
                                               wal=True)
            if not len(changed):
                return changed
            _accounting.note_bits_written(len(changed))
            self._epoch += 1
            ch_rows, deltas = np.unique(changed >> row_shift,
                                        return_counts=True)
            row_counts = self._row_counts
            if len(row_counts) + len(ch_rows) >= _ROW_COUNT_CAP:
                row_counts.clear()
            sign = 1 if set else -1
            cache_add = self.cache.bulk_add
            for rid, d in zip(ch_rows.tolist(), deltas.tolist()):
                self.checksums.pop(rid // HASH_BLOCK_SIZE, None)
                self.row_cache.invalidate(rid)
                cur = row_counts.get(rid)
                if cur is None:
                    count = self.row_count(rid)  # already post-mutation
                else:
                    count = cur + sign * d
                row_counts[rid] = count
                cache_add(rid, count)
            self.cache.invalidate()
            self.device.invalidate_rows(ch_rows.tolist())
            if self.stats is not None:
                self.stats.count("setN" if set else "clearN",
                                 len(changed))
            self._increment_op_n()
            return changed

    def _increment_op_n(self) -> None:
        # locked() is a racy peek, but benign in both directions: a
        # stale False just try-acquires (and fails fast) inside
        # _snapshot_async; a stale True means the NEXT op re-triggers.
        # Walking the full snapshot() chain per op while a background
        # worker lagged behind the write rate was a measured chunk of
        # per-op latency.
        if self.storage.op_n > MAX_OP_N and not self._snap_mu.locked():
            self.snapshot(sync=False)

    def wal_barrier(self) -> None:
        """Commit barrier: every mutation applied so far has its WAL
        record in the OS (fsynced per PILOSA_TPU_WAL_FSYNC) when this
        returns. The serving layer calls the process-wide
        ``storage.wal.barrier_all()`` before acking write requests;
        library callers mutating fragments directly use this (or
        ``close()``) to get the same durability point."""
        wal = self._wal
        if wal is not None:
            wal.barrier()

    def snapshot(self, sync: bool = True,
                 reason: str = "storage") -> None:
        """Atomically rewrite the data file from current state
        (reference fragment.go:991-1057).

        ``sync=False`` (the per-op MAX_OP_N trigger) serializes a
        COW-frozen capture on a BACKGROUND thread and splices the ops
        appended meanwhile from the old file's WAL tail at swap time —
        the write path stops paying the ~15-30 ms serialization every
        2000 ops (it was a third of per-op latency), while durability
        is unchanged: every op is already in the old file's WAL, so a
        crash at ANY point replays identically. Bulk paths that detach
        the op writer (import, merge apply, restore) MUST use
        sync=True — their mutations exist nowhere but memory until the
        snapshot lands.

        Fast path: the rewritten file is swapped under the live storage
        object — no close/re-unmarshal/remap, which cost ~100 ms per
        MAX_OP_N=2000 ops (most of the steady-state write path). The
        in-memory containers are already the state just serialized, so
        only the fd, the flock, and the op counter change. Every
        ``_REMAP_EVERY``-th snapshot takes the full reopen instead: it
        re-establishes zero-copy mapped containers, un-pinning old map
        generations that copy-on-write views would otherwise keep alive
        indefinitely."""
        if not sync:
            self._snapshot_async()
            return
        # Lock order: _snap_mu (waits out / blocks any worker) then
        # _mu. Callers MUST NOT hold _mu here — import/merge release
        # it before snapshotting (the worker needs _mu to finish, so
        # joining under _mu would deadlock).
        with self._snap_mu:
            self._snapshot_locked(reason=reason)

    def _snapshot_locked(self, reason: str = "storage") -> None:
        with self._mu:
            with self.logger.track("fragment: snapshot %s/%s/%s/%d",
                                   self.index, self.frame, self.view,
                                   self.slice):
                # No unmap/copy-out: write_to reads the mapped
                # containers directly, and _close_storage just drops
                # the map reference (see its comment).
                t0 = time.perf_counter()
                tmp = self.path + ".snapshotting"
                try:
                    with open(tmp, "wb") as f:
                        self.storage.write_to(f, footer=True)
                        # The hit sits AFTER the body write so corrupt
                        # mode can flip real bits in the just-written
                        # snapshot (error/torn/enospc semantics are
                        # unchanged: the tmp file is discarded either
                        # way and the old file stays the record).
                        if _fp.ACTIVE is not None:
                            _fp.ACTIVE.hit("snapshot.write", writer=f)
                        f.flush()
                        os.fsync(f.fileno())
                except OSError as e:
                    # A full disk flips the node write-unready
                    # (fault.diskfull → writes answer 507, reads keep
                    # serving) before the failure propagates; the old
                    # snapshot+WAL stays the file of record.
                    from ..fault import diskfull as _diskfull
                    _diskfull.note_if_enospc(e, "snapshot.write",
                                             self.path)
                    raise
                self._swap_data_file(tmp, new_op_n=0)
                snap_s = time.perf_counter() - t0
                # The snapshot leg of the import-stage breakdown
                # (decode/apply land in the wire-import handler) —
                # only for snapshots the IMPORT path forced; op-log
                # threshold and anti-entropy rewrites would pollute
                # the import attribution.
                if reason == "import":
                    obs_metrics.IMPORT_STAGE_SECONDS.labels(
                        "snapshot").observe(snap_s)
                if self.stats is not None:
                    # Distribution, not last-write-wins: the expvar
                    # client aggregates count/sum/min/max and the
                    # registry bridge buckets it (obs.metrics).
                    self.stats.timing(
                        "snapshotDurationNs", snap_s * 1e9)

    def _swap_data_file(self, tmp: str, new_op_n: int) -> None:
        """Swap ``tmp`` in as the data file (caller holds _mu; one
        shared implementation for the sync and background paths).
        Fast path: replace the path, flock + attach a new fd — flock
        is per-inode, so the old fd's lock cannot conflict, and the
        old map stays alive while mapped container views pin it. Every
        ``_REMAP_EVERY``-th snapshot does the full close/reopen instead
        (re-establishes zero-copy mapped containers). A failed swap
        falls back to the full reopen so the WAL is never silently
        left detached; if THAT also fails the exception propagates and
        the fragment is visibly broken rather than quietly
        unlogged."""
        self._snapshot_n += 1
        if self._snapshot_n % _REMAP_EVERY == 0:
            self._close_storage()
            os.replace(tmp, self.path)
            # Quarantining reopen: a snapshot that landed corrupt
            # (failpoint corrupt mode, real bit rot in the write path)
            # must degrade to quarantine + repair, not brick the
            # fragment mid-swap.
            self._open_storage_quarantining()
            return
        self.storage.op_writer = None
        os.replace(tmp, self.path)
        try:
            new_file = open(self.path, "a+b", buffering=0)
            fcntl.flock(new_file.fileno(),
                        fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BaseException:
            self._close_storage()
            self._open_storage_quarantining()
            return
        old_file, self._file = self._file, new_file
        self._mmap = None
        if old_file is not None:
            try:
                fcntl.flock(old_file.fileno(), fcntl.LOCK_UN)
            except OSError:
                pass
            old_file.close()
        new_file.seek(0, os.SEEK_END)
        self.storage.op_n = new_op_n
        if self._wal is not None:
            # The snapshot body covers every applied mutation, so any
            # pending (even failed/torn) records are superseded; the
            # WAL continues over the fresh file with a clean slate.
            self._wal.reset_file(new_file, clear_pending=True)
            self.storage.op_writer = self._wal
        else:
            self.storage.op_writer = new_file

    def _join_snapshot(self) -> None:
        """Barrier: returns once no background snapshot is in flight
        (the worker holds _snap_mu for its entire run)."""
        with self._snap_mu:
            pass

    def _snapshot_async(self) -> None:
        # Called UNDER _mu (the per-op MAX_OP_N trigger): may only
        # TRY-acquire _snap_mu — blocking here would invert the
        # _snap_mu → _mu lock order against sync snapshot/close.
        if not self._snap_mu.acquire(blocking=False):
            return  # a worker or sync snapshot is running; op_n
            # keeps re-triggering until one lands
        try:
            if self._wal is not None:
                # The splice contract below needs the FILE to hold
                # every op appended so far: tail_off divides "covered
                # by the frozen body" from "spliced from the WAL tail",
                # so pending userspace records must land first. (A
                # failed/torn log raises here — fail-stop: the file
                # past the flushed prefix is not trustworthy, and a
                # reopen trims to exactly that prefix.)
                self._wal.flush(None, sync=False)
            frozen = self.storage.freeze()
            tail_off = self._file.seek(0, os.SEEK_END)
        except BaseException:
            self._snap_mu.release()
            raise
        # _snap_mu intentionally stays held; the worker releases it
        # (threading.Lock allows cross-thread release).
        threading.Thread(
            target=self._snapshot_worker, args=(frozen, tail_off),
            name="frag-snapshot", daemon=True).start()

    def _snapshot_worker(self, frozen, tail_off: int) -> None:
        # Runs with _snap_mu held (acquired by _snapshot_async,
        # released here — a plain Lock supports cross-thread release).
        try:
            with self.logger.track(
                    "fragment: async snapshot %s/%s/%s/%d", self.index,
                    self.frame, self.view, self.slice):
                tmp = self.path + ".snapshotting"
                try:
                    with open(tmp, "wb") as f:
                        # The expensive serialize + fsync of the frozen
                        # body runs with NO fragment lock held; writers
                        # keep appending to the old file's WAL.
                        roaring.write_frozen(frozen, f, footer=True)
                        # Crash-mid-snapshot injection: a fault here
                        # leaves a partial tmp file that is never
                        # swapped in — the old snapshot+WAL stays the
                        # file of record and the next MAX_OP_N trigger
                        # retries (the OSError handler below). Corrupt
                        # mode instead flips real bits in the written
                        # body, which the open-time / scrub checks
                        # must catch downstream.
                        if _fp.ACTIVE is not None:
                            _fp.ACTIVE.hit("snapshot.write", writer=f)
                        f.flush()
                        os.fsync(f.fileno())
                        with self._mu:
                            # Splice the ops that landed since the
                            # freeze, then swap — brief: the body is
                            # already on disk, only the tail pages
                            # need syncing.
                            if self._wal is not None:
                                # Writers appended under _mu; get their
                                # records into the old file so the tail
                                # read below sees them. WalError (torn
                                # log) aborts the swap via the OSError
                                # handler — the old file stays the file
                                # of record.
                                self._wal.flush(None, sync=False)
                            with open(self.path, "rb") as rf:
                                rf.seek(tail_off)
                                tail = rf.read()
                            f.write(tail)
                            f.flush()
                            os.fsync(f.fileno())
                            self._swap_data_file(
                                tmp,
                                new_op_n=len(tail) // roaring.OP_SIZE)
                except OSError as e:
                    # Pre-swap serialization IO failure: op_writer was
                    # never detached, the old snapshot+WAL remains the
                    # file of record, and the next MAX_OP_N trigger
                    # retries. (_swap_data_file failures are NOT
                    # caught: its own fallback reopen either restores
                    # a consistent state or propagates, leaving the
                    # fragment visibly broken — never quietly
                    # unlogged.) ENOSPC additionally flips the node
                    # write-unready (fault.diskfull) so the retry
                    # pressure stops at the HTTP layer with 507s.
                    from ..fault import diskfull as _diskfull
                    _diskfull.note_if_enospc(e, "snapshot.write",
                                             self.path)
                    self.logger.printf(
                        "fragment: async snapshot failed for"
                        " %s/%s/%s/%d: %s", self.index, self.frame,
                        self.view, self.slice, e)
        finally:
            self._snap_mu.release()

    def import_bits(self, row_ids, column_ids) -> None:
        """Bulk import: direct adds with the op-log detached, then snapshot
        (reference fragment.go:924-989)."""
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(column_ids, dtype=np.uint64)
        if len(rows) != len(cols):
            raise ValueError("row/column id length mismatch")
        min_col = self.slice * SLICE_WIDTH
        if len(cols) and (int(cols.min()) < min_col
                          or int(cols.max()) >= min_col + SLICE_WIDTH):
            raise ValueError("column out of bounds")
        positions = rows * np.uint64(SLICE_WIDTH) + (
            cols % np.uint64(SLICE_WIDTH))
        self.import_positions(positions)

    def clear_positions(self, positions: np.ndarray) -> np.ndarray:
        """Batched clear of slice-local bit positions through the WAL'd
        batch engine (the BSI value-import lane clears stale planes of
        re-imported columns with it). Returns the changed positions."""
        return self._mutate_batch_positions(
            np.asarray(positions, dtype=np.uint64), set=False)

    def import_positions(self, positions: np.ndarray) -> None:
        """Bulk import of slice-local bit positions (row*SLICE_WIDTH +
        col%SLICE_WIDTH) — the frame-level packed-sort import lane
        feeds each fragment its span of ONE globally sorted position
        vector, so no per-fragment re-sort happens here (add_many's
        is-sorted check passes on that lane)."""
        positions = np.asarray(positions, dtype=np.uint64)
        # Gate read under _mu: op_writer is swapped by snapshot/restore
        # code, and although every such path restores it under the same
        # _mu hold today, that invariant is one refactor away from
        # breaking silently (ADVICE r5 #3) — the lock is noise next to
        # the import itself.
        with self._mu:
            if self.tier is not None and self.tier_state != "hot":
                self._tier_gate(full=True, write=True)
            small = (len(positions) * 16 < len(self.storage.keys)
                     and self.storage.op_writer is not None)
        if small:
            # Small import into a large fragment: the WAL'd batch engine
            # is strictly cheaper than the detach-then-full-snapshot
            # import contract (a 3-bit /import into a 400 K-container
            # fragment paid ~0.9 s of snapshot serialization), and
            # strictly MORE durable — the bits are group-commit WAL'd
            # before return instead of living only in memory until the
            # snapshot lands.
            self._mutate_batch_positions(positions, set=True)
            self.wal_barrier()
            return
        # WAL-first bulk import: append one blob of add records for the
        # whole block (vectorized build, idempotent on replay — re-adds
        # of already-set bits are no-ops, exactly like op replay), bulk
        # apply, then a commit barrier. The sync snapshot the vintage
        # import contract paid per request (serialize whole fragment +
        # fsync, ~100 ms/slice — THE wire-import bound, VERDICT r5 #3)
        # moves to the MAX_OP_N async cadence; reopen replays the
        # records through the vectorized op-log lane instead. Imports
        # too large to sensibly hold as op records keep the vintage
        # detach-then-snapshot contract.
        wal_first = (self.storage.op_writer is not None
                     and len(positions) * roaring.OP_SIZE
                     <= _WAL_IMPORT_MAX_BYTES)
        with self._mu:
            self._epoch += 1
            _accounting.note_bits_written(len(positions))
            if wal_first:
                roaring._wal_write(self.storage.op_writer,
                                   roaring._wal_blob(positions,
                                                     roaring.OP_ADD))
                # MAX_OP_N bounds REOPEN REPLAY time, and a blob's
                # add-run replays through the vectorized bulk lane at
                # ~16x the discrete-op rate (roaring._replay_ops) —
                # so a blob bit carries 1/16th the snapshot pressure
                # of a discrete op. Unweighted, every import block
                # larger than MAX_OP_N forced a full snapshot whose
                # GIL-held serialization convoyed with the NEXT
                # block's apply (the measured wire-import long pole).
                self.storage.op_n += max(
                    1, len(positions) // _BLOB_OP_WEIGHT)
                self.storage.add_many(positions)
            else:
                writer, self.storage.op_writer = \
                    self.storage.op_writer, None
                try:
                    self.storage.add_many(positions)
                finally:
                    self.storage.op_writer = writer
            if _RUN_OPTIMIZE:
                # Cardinality-adaptive representation pass (roaring run
                # containers): bulk imports are where run-heavy data
                # (timestamp views, BSI planes) lands, so this is the
                # one site that (re)introduces run containers; the
                # snapshot below persists them via the runs cookie.
                # Restricted to containers this block put an ADJACENT
                # value pair into: a run form needs adjacency to beat
                # the legacy kinds, and run-shaped data carries its
                # adjacency in the import block itself — so a random
                # sparse import (which can never win) skips the pass
                # entirely instead of re-pricing every touched
                # container (measured: the unrestricted pass was 40%
                # of a 1M-bit import).
                srt = (positions if len(positions) < 2
                       or positions[0] <= positions[-1] else None)
                srt = np.sort(positions) if srt is None else srt
                adj = np.flatnonzero(np.diff(srt) == np.uint64(1))
                if len(adj):
                    self.storage.optimize(
                        sort_dedupe(srt[adj] >> np.uint64(16)))
            # Post-import row counts in ONE pass over the container
            # table: positions are row*SLICE_WIDTH + col, so a
            # container's row is its key >> log2(SLICE_WIDTH/65536) and
            # a row's count is the sum of its containers' cardinalities
            # (slice rows align exactly on container boundaries). The
            # per-row count_range walk this replaces was the bulk-import
            # long pole at 10^5 distinct rows (~230 us/row).
            shift = np.uint64((SLICE_WIDTH // 65536).bit_length() - 1)
            key_arr = self.storage._keys_np()
            # Packed-lane positions arrive sorted: sort_dedupe's linear
            # pass replaces np.unique's re-sort.
            uniq_rows = sort_dedupe(positions // np.uint64(SLICE_WIDTH))
            conts = self.storage.containers
            if len(uniq_rows) * 32 < len(key_arr):
                # Small import into a large fragment: sum only each
                # touched row's <=16-container key span instead of
                # walking the whole container table (review finding:
                # the full pass made every tiny /import request pay
                # O(all containers)).
                lo = np.searchsorted(key_arr, uniq_rows << shift)
                hi = np.searchsorted(key_arr,
                                     (uniq_rows + np.uint64(1)) << shift)
                cnts = np.fromiter(
                    (sum(conts[i].n for i in range(l, h))
                     for l, h in zip(lo.tolist(), hi.tolist())),
                    np.int64, len(uniq_rows))
            else:
                cards = np.fromiter((c.n for c in conts), np.int64,
                                    len(key_arr))
                crows = key_arr >> shift
                gb = np.flatnonzero(crows[1:] != crows[:-1]) + 1
                gstarts = np.concatenate(([0], gb)) if len(crows) else gb
                present_rows = crows[gstarts] if len(crows) else crows
                row_sums = (np.add.reduceat(cards, gstarts)
                            if len(crows) else cards)
                pos = np.searchsorted(present_rows, uniq_rows)
                cnts = row_sums[np.minimum(pos, max(len(row_sums) - 1,
                                                    0))]
                cnts[(pos >= len(present_rows))
                     | (present_rows[np.minimum(
                         pos, max(len(present_rows) - 1, 0))]
                        != uniq_rows)] = 0
            under_cap = len(self._row_counts) < _ROW_COUNT_CAP
            for rid, cnt in zip(uniq_rows.tolist(), cnts.tolist()):
                if rid in self._row_counts or under_cap:
                    self._row_counts[rid] = cnt
                    under_cap = len(self._row_counts) < _ROW_COUNT_CAP
                self.cache.bulk_add(rid, cnt)
            self.cache.recalculate()
            self.row_cache.clear()
            self.device.invalidate_all()
            self.checksums.clear()
        # Outside _mu: the sync snapshot takes _snap_mu then _mu (the
        # worker needs _mu to finish, so snapshotting under _mu would
        # deadlock the join).
        if wal_first:
            # The records are appended; commit them (one group flush,
            # coalesced with any concurrent import's barrier) and let
            # the op-count trigger schedule the snapshot in the
            # background — the import request path no longer pays it.
            with self._mu:
                self._increment_op_n()
            self.wal_barrier()
        else:
            # Vintage contract: the bulk adds were never WAL'd, so the
            # mutations exist nowhere but memory until this lands.
            self.snapshot(reason="import")

    # -- TopN ----------------------------------------------------------------

    def _top_pairs(self, row_ids: list[int]) -> list[Pair]:
        # reference fragment.go:627-677
        if not row_ids:
            self.cache.invalidate()
            return self.cache.top()
        pairs = []
        for rid in row_ids:
            n = self.cache.get(rid)
            if n <= 0:
                n = self.row_count(rid)
            if n > 0:
                pairs.append(Pair(rid, n))
        return pairs

    _EMPTY_COUNTS = (np.empty(0, dtype=np.int64),
                     np.empty(0, dtype=np.int64))

    def sparse_row_pairs(self, row_id: int):
        """(word idx, word value) pairs for one row, under the
        fragment lock — the extraction feeding sparse device uploads
        (ops.packed); lockless storage walks race with concurrent
        mutations (review finding, round 4)."""
        from ..ops import packed
        with self._mu:
            self._verify_on_read()
            if self.tier is not None:
                self._tier_gate(row_id=row_id)
            return packed.sparse_row_words(self.storage, row_id)

    def _cached_total_bits(self) -> int:
        """storage.count() walks every container in Python (~115 ms
        across 256 c5 fragments); cache per mutation epoch."""
        hit = getattr(self, "_total_bits", None)
        if hit is not None and hit[0] == self._epoch:
            return hit[1]
        n = self.storage.count()
        self._total_bits = (self._epoch, n)
        return n

    def container_stats(self) -> dict:
        """Per-kind container counts/resident bytes/run intervals
        (roaring.Bitmap.container_stats), cached per mutation epoch —
        the runtime collector samples every open fragment on its
        cadence, and the underlying walk is O(containers)."""
        hit = getattr(self, "_container_stats", None)
        if hit is not None and hit[0] == self._epoch:
            return hit[1]
        with self._mu:
            stats = self.storage.container_stats()
        self._container_stats = (self._epoch, stats)
        return stats

    def _cached_positions(self) -> np.ndarray:
        """all_positions per mutation epoch: every src's first count
        map (and any other whole-fragment pass) shares one walk. Only
        cached up to _POSITIONS_CACHE_BITS (32 MB resident); bigger
        fragments rebuild per pass rather than pinning hundreds of MB
        across a read-mostly fleet of fragments."""
        hit = getattr(self, "_positions", None)
        if hit is not None and hit[0] == self._epoch:
            return hit[1]
        pos = self.storage.all_positions()
        if len(pos) <= _POSITIONS_CACHE_BITS:
            self._positions = (self._epoch, pos)
        else:
            self._positions = None
        return pos

    def present_rows(self):
        """Sorted row ids holding >=1 bit, cached per mutation epoch —
        lets the TopN ids-refetch skip row_count for candidates with no
        bits here (at 1024 slices x 1000 candidates that recount was
        ~900 K walks per query). None when the fragment is too big to
        dump positions cheaply; callers then recount per id."""
        hit = getattr(self, "_present_rows", None)
        if hit is not None and hit[0] == self._epoch:
            return hit[1]
        if self._cached_total_bits() > _SRC_VECTOR_BITS:
            return None
        rows = np.unique(self._cached_positions()
                         >> np.uint64(SLICE_WIDTH.bit_length() - 1))
        self._present_rows = (self._epoch, rows)
        return rows

    def _host_src_count_map(self, src: Bitmap
                            ) -> tuple[np.ndarray, np.ndarray]:
        """src ∩ row intersection counts for EVERY row of this fragment
        in one vectorized pass, as (sorted row ids, counts).

        O(fragment bits) once, instead of one roaring walk per visited
        candidate — the unbounded rank-cache src-TopN walk (up to 50 K
        rows) costs seconds through per-row Python calls and ~10 ms
        here (reference does per-row counts, fragment.go:529-560, but
        its per-call cost is nanoseconds; ours is not)."""
        key = self._src_key(src)
        if key is None:
            return self._EMPTY_COUNTS
        hit = self._src_counts.get(key)
        if hit is not None and hit[0] == self._epoch:
            return hit[1]
        # Cache miss: NOW materialize the slice-local columns (the key
        # memo deliberately does not retain them — pinning the
        # uncompressed u64 vector per cached row object would dwarf
        # the roaring data it came from).
        seg = src._segment(self.slice, False)
        src_cols = seg.data.values() % np.uint64(SLICE_WIDTH)
        return self._compute_src_count_map(src_cols,
                                           np.uint64(SLICE_WIDTH), key)

    def _src_key(self, src: Bitmap):
        """sha1 key of the slice-local src columns for the src-count
        cache (None = absent/empty segment), memoized on the segment's
        roaring data: row() hands out the SAME cached Bitmap object
        across repeat queries (row_cache), and result bitmaps are COW
        — so the values walk + sha1 runs once per materialized object
        instead of twice per slice per query (both TopN phases key the
        same map). Guarded by Bitmap.version against in-place
        mutation; only the 20-byte digest is retained."""
        seg = src._segment(self.slice, False)
        if seg is None:
            return None
        data = seg.data
        memo = getattr(data, "_src_key_memo", None)
        if memo is not None and memo[0] == data.version:
            return memo[1]
        src_cols = data.values() % np.uint64(SLICE_WIDTH)
        key = (hashlib.sha1(src_cols.tobytes()).digest()
               if len(src_cols) else None)
        data._src_key_memo = (data.version, key)
        return key

    def _host_src_count_map_cached(self, src: Bitmap):
        """The cached (ids, counts) map for this src if one is already
        current — NO compute. TopN's exact phase (few re-queried
        candidates per slice) probes this: the candidate phase of the
        same query built the map moments earlier, so the per-candidate
        roaring intersections it would otherwise do are free gathers."""
        key = self._src_key(src)
        if key is None:
            return self._EMPTY_COUNTS
        hit = self._src_counts.get(key)
        if hit is not None and hit[0] == self._epoch:
            return hit[1]
        return None

    def _compute_src_count_map(self, src_cols, w, key
                               ) -> tuple[np.ndarray, np.ndarray]:
        total_bits = self._cached_total_bits()
        if total_bits <= _SRC_VECTOR_BITS:
            # One fully vectorized pass: the per-container chunked walk
            # below costs ~4 us of Python per container, which IS the
            # first-query latency on ultra-sparse fragments (c5: 1.7 K
            # near-empty containers per fragment x 256 fragments).
            positions = self._cached_positions()
            hits = positions[np.isin(positions % w, src_cols)]
            if len(hits):
                out = np.unique((hits // w).astype(np.int64),
                                return_counts=True)
            else:
                z = np.empty(0, dtype=np.int64)
                out = (z, z)
            self._src_counts[key] = (self._epoch, out)
            while len(self._src_counts) > 4:
                self._src_counts.pop(next(iter(self._src_counts)))
            return out
        # Partial (ids, counts) maps, folded every ~1 M matched
        # positions: peak memory is bounded by DISTINCT row ids, not by
        # matched bits (a broad src over 100 M matched bits would
        # otherwise hold ~800 MB of int64 row ids before one unique).
        partial_ids: list[np.ndarray] = []
        partial_counts: list[np.ndarray] = []
        hit_rows: list[np.ndarray] = []
        hit_len = 0
        # Batch container chunks to ~1 M positions per isin: sparse
        # fragments have millions of near-empty containers, and a
        # per-container isin pays its sort setup millions of times.
        batch: list[np.ndarray] = []
        batch_len = 0

        def fold_hits() -> None:
            nonlocal hit_rows, hit_len
            if not hit_rows:
                return
            rows = (hit_rows[0] if len(hit_rows) == 1
                    else np.concatenate(hit_rows))
            hit_rows, hit_len = [], 0
            ids, counts = np.unique(rows, return_counts=True)
            partial_ids.append(ids)
            partial_counts.append(counts)

        def flush() -> None:
            nonlocal batch, batch_len, hit_len
            if not batch:
                return
            vals = batch[0] if len(batch) == 1 else np.concatenate(batch)
            batch, batch_len = [], 0
            hits = vals[np.isin(vals % w, src_cols)]
            if len(hits):
                hit_rows.append((hits // w).astype(np.int64))
                hit_len += len(hits)
                if hit_len >= _SRC_FOLD_POSITIONS:
                    fold_hits()

        for vals in self.storage.value_chunks():
            batch.append(vals)
            batch_len += len(vals)
            if batch_len >= (1 << 20):
                flush()
        flush()
        fold_hits()
        if partial_ids:
            # Merge the bounded partials: (sorted row ids, counts) — NOT
            # a bincount array, whose size is max-row-id+1 and explodes
            # on sparse huge ids.
            if len(partial_ids) == 1:
                out = (partial_ids[0], partial_counts[0])
            else:
                all_ids = np.concatenate(partial_ids)
                all_counts = np.concatenate(partial_counts)
                ids, inv = np.unique(all_ids, return_inverse=True)
                out = (ids, np.bincount(inv, weights=all_counts)
                       .astype(np.int64))
        else:
            z = np.empty(0, dtype=np.int64)
            out = (z, z)
        self._src_counts[key] = (self._epoch, out)
        while len(self._src_counts) > 4:
            self._src_counts.pop(next(iter(self._src_counts)))
        return out

    def fold_scan_pays(self, row_ids) -> bool:
        """Should a fold over these rows take fold_rows over per-row
        roaring reads? Since fold_rows switched from a whole-fragment
        scan to gathering only the target rows' container key spans,
        its cost is O(selected bits) — the same data the per-row path
        reads, minus a Bitmap wrapper and merge per row — so at the
        many-leaf shapes that reach this gate it always pays. (The old
        heuristic modeled the retired whole-fragment walk AND paid an
        O(all containers) count() per decision to do it.)"""
        return True

    def fold_rows(self, op: str, row_ids: list[int]) -> np.ndarray:
        """Slice-local columns of a left-fold of ``op`` over the given
        rows, gathered from the rows' container key spans in one
        vectorized pass instead of one roaring merge per row (the
        reference folds per row, executor.go:253-268; at 1000-row
        fan-outs that is the whole query cost on the host path).

        Semantics match the sequential fold: ``or`` = union of all;
        ``and`` = columns present in every distinct row; ``andnot`` =
        first row minus the union of the rest."""
        if op not in ("or", "and", "andnot"):
            raise ValueError(f"unknown fold op: {op!r}")
        if not row_ids:
            return np.empty(0, dtype=np.uint64)
        with self._mu:
            self._verify_on_read()
            if self.tier is not None:
                self._tier_gate(row_ids=row_ids)
            w = np.uint64(SLICE_WIDTH)
            ids = np.unique(np.asarray(row_ids, dtype=np.uint64))
            # Gather ONLY the target rows' container key spans (each
            # row covers exactly SLICE_WIDTH/65536 consecutive keys)
            # instead of walking the whole fragment through
            # value_chunks and masking with np.isin — at c2 scale
            # (1000 rows over a wide fragment) the whole-fragment walk
            # was most of the host fold's cost.
            shift = np.uint64((SLICE_WIDTH // 65536).bit_length() - 1)
            positions = self.storage.positions_for_key_ranges(
                ids << shift, (ids + np.uint64(1)) << shift)
            if not len(positions):
                return np.empty(0, dtype=np.uint64)
            rows = positions // w
            cols = positions % w
            if op == "or":
                return np.unique(cols)
            if op == "and":
                uniq, counts = np.unique(cols, return_counts=True)
                # (row, col) pairs are distinct, so a column's count is
                # the number of rows containing it.
                return uniq[counts == len(ids)]
            # andnot: row_ids[0] minus the union of the rest (the
            # sequential fold's left-to-right difference collapses to
            # exactly this). A repeat of the first row later in the
            # list subtracts it from itself — empty.
            first = np.uint64(row_ids[0])
            if any(np.uint64(r) == first for r in row_ids[1:]):
                return np.empty(0, dtype=np.uint64)
            first_cols = np.unique(cols[rows == first])
            rest_cols = np.unique(cols[rows != first])
            return first_cols[~np.isin(first_cols, rest_cols,
                                       assume_unique=True)]

    def top(self, opt: TopOptions = None) -> list[Pair]:
        """TopN with threshold pruning, attr filter, Tanimoto
        (reference fragment.go:490-625; same semantics, batched counts)."""
        opt = opt or TopOptions()
        with self._mu:
            self._verify_on_read()
            if self.tier is not None:
                # TopN ranks through the count cache, which demotion
                # flushed — a block-granular fault can't rebuild it,
                # so top() promotes fully (rank correctness over
                # laziness).
                self._tier_gate(full=True)
            # Array fast path for the plain TopN(frame, n) shape — no
            # source bitmap, no attribute filter, no tanimoto: the
            # answer is the first n rank-cache entries with count ≥
            # max(threshold, 1), which the heap replay below computes
            # identically but one Python object at a time. At config-3
            # scale (50 K-entry caches × 10 slices) this is the
            # candidate phase's entire cost.
            if (opt.src is None and not opt.row_ids
                    and not (opt.filter_field and opt.filter_values)
                    and opt.tanimoto_threshold <= 0
                    and hasattr(self.cache, "top_arrays")):
                self.cache.invalidate()
                ids, counts = self.cache.top_arrays()
                # counts are rank-sorted descending: the ≥-floor set is
                # a prefix, found by binary search on the reversed view
                # — no 50K-entry boolean mask per slice per query.
                floor = max(opt.min_threshold, 1)
                cut = len(counts) - int(np.searchsorted(
                    counts[::-1], floor, side="left"))
                ids, counts = ids[:cut], counts[:cut]
                if opt.n:
                    ids, counts = ids[:opt.n], counts[:opt.n]
                return [Pair(i, c) for i, c in zip(ids.tolist(),
                                                   counts.tolist())]
            # ids-form fast path (TopN's exact phase re-queries every
            # candidate on every slice): rank-sort the per-id counts,
            # skipping the heap replay — at 256 slices × ~200
            # candidates the replay's per-pair heap ops were phase 2's
            # whole cost. Identical output: the replay with row_ids has
            # n=0 (push all positives ≥ threshold) and pops in
            # (count desc, id asc) order, which is exactly pairs_sort.
            if (opt.src is None and opt.row_ids
                    and not (opt.filter_field and opt.filter_values)
                    and opt.tanimoto_threshold <= 0):
                floor = max(opt.min_threshold, 1)
                return cache_mod.pairs_sort(
                    p for p in self._top_pairs(opt.row_ids)
                    if p.count >= floor)
            # Candidate stream as numpy arrays when the rank cache can
            # serve them: the src path used to materialize a Pair per
            # cached row per slice (117 K objects per c5 query, ~60 ms
            # of its 112 ms repeat p50) just to feed the replay loop.
            if not opt.row_ids and hasattr(self.cache, "top_arrays"):
                self.cache.invalidate()
                cand_ids, cand_counts = self.cache.top_arrays()
                cand_ids = cand_ids.astype(np.int64)
                cand_counts = np.asarray(cand_counts)
            else:
                pairs = self._top_pairs(opt.row_ids)
                cand_ids = np.fromiter((p.id for p in pairs),
                                       dtype=np.int64, count=len(pairs))
                cand_counts = np.fromiter((p.count for p in pairs),
                                          dtype=np.int64,
                                          count=len(pairs))
            n = 0 if opt.row_ids else opt.n

            filters = None
            if opt.filter_field and opt.filter_values:
                filters = set(opt.filter_values)

            tanimoto = 0
            min_tan = max_tan = 0.0
            src_count = 0
            if opt.tanimoto_threshold > 0 and opt.src is not None:
                tanimoto = opt.tanimoto_threshold
                src_count = opt.src.count()
                min_tan = src_count * tanimoto / 100
                max_tan = src_count * 100 / tanimoto

            # Candidate ∩ src counts. Past a handful of candidates, ONE
            # vectorized pass over the fragment computes every row's
            # count (O(fragment bits), ~10 ms/slice, cached per src ×
            # mutation epoch), then zero-overlap candidates drop out
            # before the replay. Safe: a src-count-0 pair can never
            # push (the replay skips count==0) and removing it cannot
            # move the break point (the next visited pair's cache
            # count is ≤ the removed one's, so the break still fires
            # before any further push). Small candidate sets (point
            # lookups, short ids=[...]) keep the per-row roaring
            # intersection — a full-fragment scan for 3 rows is waste.
            # Per-slice device batching was measured strictly worse on
            # every shape — one sync per slice per query; cross-slice
            # batched exact counts with residency live on the
            # EXECUTOR's device path (_topn_exact_resident), where the
            # cost model routes them.
            count_ids = count_vals = None
            if opt.src is not None:
                if len(cand_ids) > self.SRC_MAP_MIN:
                    count_ids, count_vals = \
                        self._host_src_count_map(opt.src)
                else:
                    # Small candidate set (the exact phase's ids= form,
                    # point lookups): never WORTH computing the map,
                    # but if one is already cached — the candidate
                    # phase of this very query built it — gathers beat
                    # per-candidate roaring intersections.
                    cached = self._host_src_count_map_cached(opt.src)
                    if cached is not None:
                        count_ids, count_vals = cached
            if count_ids is not None:
                scnt = None
                if len(cand_ids):
                    # count_ids is sorted: membership via searchsorted
                    # beats np.isin's hash/sort machinery at rank-cache
                    # scale (up to 50 K candidates x 256 slices/query);
                    # the same probe's indices serve as the src-count
                    # gather, so the vectorized replay never re-probes.
                    keep, at = arrays_mod.searchsorted_membership(
                        count_ids, cand_ids)
                    cand_ids = cand_ids[keep]
                    cand_counts = cand_counts[keep]
                    scnt = count_vals[at[keep]]
                if (filters is None and tanimoto == 0 and n > 0
                        and len(cand_ids)):
                    return self._top_src_vectorized(
                        cand_ids, cand_counts, scnt, n,
                        opt.min_threshold)

            def src_count_of(rid: int) -> int:
                if count_ids is None:
                    return opt.src.intersection_count(self.row(rid))
                i = np.searchsorted(count_ids, rid)
                if i < len(count_ids) and count_ids[i] == rid:
                    return int(count_vals[i])
                return 0

            # Replay the reference's heap algorithm over the counts.
            results: list[tuple[int, int]] = []  # min-heap of (count, -id)
            out: list[Pair] = []

            def push(rid, cnt):
                heapq.heappush(results, (cnt, -rid))

            for rid, cnt in zip(cand_ids.tolist(),
                                cand_counts.tolist()):
                if cnt <= 0:
                    continue
                if tanimoto > 0:
                    if cnt <= min_tan or cnt >= max_tan:
                        continue
                elif cnt < opt.min_threshold:
                    continue
                if filters is not None:
                    attrs = (self.row_attr_store.attrs(rid)
                             if self.row_attr_store else None)
                    if not attrs:
                        continue
                    val = attrs.get(opt.filter_field)
                    if val is None or val not in filters:
                        continue
                if n == 0 or len(results) < n:
                    count = cnt if opt.src is None else src_count_of(rid)
                    if count == 0:
                        continue
                    if tanimoto > 0:
                        t = math.ceil(count * 100 / (cnt + src_count - count))
                        if t <= tanimoto:
                            continue
                    elif count < opt.min_threshold:
                        continue
                    push(rid, count)
                    if n > 0 and len(results) == n and opt.src is None:
                        break
                    continue
                threshold = results[0][0]
                if threshold < opt.min_threshold or cnt < threshold:
                    break
                count = src_count_of(rid)
                if count < threshold:
                    continue
                push(rid, count)

            while results:
                cnt, neg_id = heapq.heappop(results)
                out.append(Pair(-neg_id, cnt))
            out.reverse()
            return out

    @staticmethod
    def _top_src_vectorized(cand_ids, cand_counts, scnt, n: int,
                            min_threshold: int) -> list[Pair]:
        """Vectorized replay of the heap walk for the plain-src shape
        (gathered src counts in hand, no tanimoto, no attr filter,
        n>0). Exactly reproduces the loop's visit-order semantics,
        including the SUPERSET it returns for the cross-slice fill:
        phase A pushes the first n valid candidates (cache count and
        src count both >= max(min_threshold, 1)); t = their min src
        count; the walk then breaks at the first later candidate whose
        CACHE count drops below t, and pushes every candidate before
        that whose src count >= t. Output sorted (count desc, id asc),
        like the heap drain. Equivalence is pinned against a verbatim
        port of the loop by randomized parity in
        tests/test_fragment.py::TestTopSrcVectorizedParity."""
        floor = max(min_threshold, 1)
        scnt = np.asarray(scnt, dtype=np.int64)
        cache_ok = cand_counts >= floor
        valid = cache_ok & (scnt >= floor)
        valid_idx = np.flatnonzero(valid)
        if len(valid_idx) <= n:
            take = valid_idx
        else:
            first_n = valid_idx[:n]
            t = int(scnt[first_n].min())
            # Break at the first cache-valid candidate AFTER phase A
            # whose cache count < t (invalid-by-cache candidates are
            # skipped by `continue`, not `break`).
            later = np.flatnonzero(cache_ok)
            later = later[later > first_n[-1]]
            brk = later[cand_counts[later] < t]
            stop = int(brk[0]) if len(brk) else len(cand_ids)
            phase_b = later[(later < stop) & (scnt[later] >= t)]
            take = np.concatenate((first_n, phase_b))
        ids = cand_ids[take]
        cnts = scnt[take]
        order = np.lexsort((ids, -cnts))
        return [Pair(int(i), int(c))
                for i, c in zip(ids[order].tolist(),
                                cnts[order].tolist())]

    def recalculate_cache(self) -> None:
        """Rebuild the rank cache regardless of the invalidate rate limit
        (reference fragment.go:1059-1063)."""
        with self._mu:
            self.cache.recalculate()

    # -- block checksums / anti-entropy --------------------------------------

    def checksum(self) -> bytes:
        """Whole-fragment checksum = SHA1 over block checksums
        (reference fragment.go:679-687)."""
        h = hashlib.sha1()
        for blk in self.blocks():
            h.update(blk[1])
        return h.digest()

    def block_n(self) -> int:
        return self.storage.max() // (HASH_BLOCK_SIZE * SLICE_WIDTH)

    def invalidate_checksums(self) -> None:
        self.checksums.clear()

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block_id, sha1) for all non-empty 100-row blocks
        (reference fragment.go:704-767). Hash = SHA1 of big-endian u64
        positions — wire-compatible with the reference's blockHasher."""
        with self._mu:
            values = self.storage.values()
            if not len(values):
                return []
            block_span = HASH_BLOCK_SIZE * SLICE_WIDTH
            block_ids = values // np.uint64(block_span)
            bounds = np.flatnonzero(np.diff(block_ids)) + 1
            starts = np.concatenate(([0], bounds))
            ends = np.concatenate((bounds, [len(values)]))
            out = []
            for s, e in zip(starts, ends):
                bid = int(block_ids[s])
                chk = self.checksums.get(bid)
                if chk is None:
                    chk = hashlib.sha1(
                        values[s:e].astype(">u8").tobytes()).digest()
                    self.checksums[bid] = chk
                out.append((bid, chk))
            return out

    def block_data(self, block_id: int) -> PairSet:
        """Bits in a block as (row, column-within-slice) arrays
        (reference fragment.go:785-795)."""
        with self._mu:
            span = HASH_BLOCK_SIZE * SLICE_WIDTH
            vals = self.storage.slice_range(block_id * span,
                                            (block_id + 1) * span)
            return PairSet(vals // np.uint64(SLICE_WIDTH),
                           vals % np.uint64(SLICE_WIDTH))

    def merge_block(self, block_id: int, data: list[PairSet]
                    ) -> tuple[list[PairSet], list[PairSet]]:
        """Majority-consensus merge of this block against peer copies
        (reference fragment.go:802-920, vectorized).

        Returns (sets, clears) diffs for each *peer* (local diffs are
        applied in place). A bit's final state is set iff ≥ half of the
        (len(data)+1) copies have it set.
        """
        for ps in data:
            if len(ps.row_ids) != len(ps.column_ids):
                raise ValueError("pair set mismatch")
        with self._mu:
            local = self.block_data(block_id)
            copies = [local] + list(data)
            min_row = block_id * HASH_BLOCK_SIZE
            max_row = (block_id + 1) * HASH_BLOCK_SIZE
            positions = []
            for ps in copies:
                keep = ((ps.row_ids >= min_row) & (ps.row_ids < max_row)
                        & (ps.column_ids < SLICE_WIDTH))
                # Dedup within each copy: a peer repeating a pair on the wire
                # must still get exactly one vote.
                positions.append(np.unique(
                    ps.row_ids[keep].astype(np.uint64) * np.uint64(SLICE_WIDTH)
                    + ps.column_ids[keep].astype(np.uint64)))
            all_pos = np.concatenate(positions) if positions else \
                np.empty(0, dtype=np.uint64)
            uniq, counts = np.unique(all_pos, return_counts=True)
            majority = (len(copies) + 1) // 2
            want = counts >= majority
            sets_out, clears_out = [], []
            local_set_pos = local_clear_pos = None
            for ps, pos in zip(copies, positions):
                has = np.isin(uniq, pos, assume_unique=True)
                to_set = uniq[want & ~has]
                to_clear = uniq[~want & has]
                if local_set_pos is None:  # first copy = local
                    local_set_pos, local_clear_pos = to_set, to_clear
                sets_out.append(PairSet(to_set // np.uint64(SLICE_WIDTH),
                                        to_set % np.uint64(SLICE_WIDTH)))
                clears_out.append(PairSet(to_clear // np.uint64(SLICE_WIDTH),
                                          to_clear % np.uint64(SLICE_WIDTH)))
            # Apply local diffs.
            need_snapshot = self._apply_merge_diffs(local_set_pos,
                                                    local_clear_pos)
        if need_snapshot:
            # Outside _mu: sync snapshot takes _snap_mu then _mu (see
            # import_bits for the ordering rationale).
            self.snapshot()
        else:
            # Per-bit path: records were group-appended; anti-entropy
            # acks the merge to peers, so commit before returning.
            self.wal_barrier()
        return sets_out[1:], clears_out[1:]

    # Above this many local diffs, per-bit WAL appends (plus a per-op
    # row-count cache update) cost more than one snapshot rewrite — the
    # same trade bulk import makes (fragment.go:924-989).
    MERGE_BULK_THRESHOLD = 256

    # src-TopN candidate sets up to this size use per-row roaring
    # intersections; larger walks take the one-pass vectorized map.
    SRC_MAP_MIN = 64


    def _apply_merge_diffs(self, set_pos: np.ndarray,
                           clear_pos: np.ndarray) -> None:
        """Apply a merge_block consensus diff locally. Small diffs go
        through the per-bit path (cheap WAL appends); large divergences
        bulk-apply with the op-log detached and one snapshot, so
        anti-entropy of a badly diverged replica does not crawl through
        a Python loop (reference bulk semantics: fragment.go:802-920)."""
        total = len(set_pos) + len(clear_pos)
        if total == 0:
            return False
        base_col = self.slice * SLICE_WIDTH
        if total <= self.MERGE_BULK_THRESHOLD:
            for pos in set_pos:
                self._mutate(int(pos) // SLICE_WIDTH,
                             base_col + int(pos) % SLICE_WIDTH, set=True)
            for pos in clear_pos:
                self._mutate(int(pos) // SLICE_WIDTH,
                             base_col + int(pos) % SLICE_WIDTH, set=False)
            return False  # per-bit path WALs every op; no snapshot due
        self._epoch += 1
        writer, self.storage.op_writer = self.storage.op_writer, None
        try:
            added = self.storage.add_many(set_pos)
            removed = self.storage.remove_many(clear_pos)
        finally:
            self.storage.op_writer = writer
        # Same per-bit side effects as _mutate, batched per row.
        rows = np.unique(np.concatenate((set_pos, clear_pos))
                         // np.uint64(SLICE_WIDTH))
        for rid in rows:
            rid = int(rid)
            self.checksums.pop(rid // HASH_BLOCK_SIZE, None)
            self.row_cache.invalidate(rid)
            self.device.invalidate_row(rid)
            cnt = self.row_count(rid)
            if (rid in self._row_counts
                    or len(self._row_counts) < _ROW_COUNT_CAP):
                self._row_counts[rid] = cnt
            self.cache.bulk_add(rid, cnt)
        self.cache.recalculate()
        if self.stats is not None:
            self.stats.count("setN", added)
            self.stats.count("clearN", removed)
        return True  # bulk path: caller snapshots outside _mu

    # -- iteration / export --------------------------------------------------

    def snapshot_value_chunks(self):
        """Point-in-time set positions, one sorted u64 array per
        container, safe to drain long after the call (e.g. by a WSGI
        layer streaming a CSV export). The fragment lock is held only
        while copying the COMPRESSED container buffers (u16 arrays /
        u64 words — bounded by on-disk size, not 8 B per set bit);
        expansion to positions happens lazily per yield, so neither
        lock-hold time nor peak memory scales with the rendered
        output. The reference streams exports bit-by-bit under its
        fragment mutex (handler.go:985-1025); this is the
        snapshot-then-stream equivalent."""
        with self._mu:
            snap = []
            for key, c in zip(list(self.storage.keys),
                              list(self.storage.containers)):
                if not c.n:
                    continue
                snap.append((int(key),
                             None if c.array is None else c.array.copy(),
                             None if c.bitmap is None else c.bitmap.copy(),
                             None if c.runs is None else c.runs.copy()))

        def expand():
            for key, arr, words, runs in snap:
                if runs is not None:
                    arr = roaring.runs_to_values(runs)
                elif arr is None:
                    arr = roaring.bitmap_words_to_values(words)
                yield np.uint64(key << 16) + arr.astype(np.uint64)
        return expand()

    def for_each_bit(self):
        """Yield (row_id, absolute_column_id) for every set bit."""
        base = self.slice * SLICE_WIDTH
        for pos in self.storage.values():
            pos = int(pos)
            yield pos // SLICE_WIDTH, base + pos % SLICE_WIDTH

    # -- backup / restore (reference fragment.go:1096-1266) ------------------

    def write_to(self, w) -> None:
        """Stream the fragment as a tar archive of 'data' (snapshot+WAL
        file bytes) and 'cache' (the TopN id sidecar). The data file is
        streamed from disk, not buffered — a full slice is 128 MB+.
        Size is captured under lock; the format is append-only so copying
        up to that size outside the lock is safe (fragment.go:1113-1155).
        """
        import tarfile
        self.flush_cache()
        self.wal_barrier()  # pending records must be inside the sized copy
        # Open the fd FIRST, then size it under lock: a concurrent
        # snapshot() os.replace()s the path, but this fd pins the old
        # inode, which only ever grows by appended ops — so copying
        # exactly fstat-size bytes from it is a consistent snapshot+WAL
        # prefix (same trick as fragment.go:1113-1151).
        tw = tarfile.open(fileobj=w, mode="w|")
        with open(self.path, "rb") as f:
            with self._mu:
                data_size = os.fstat(f.fileno()).st_size
            info = tarfile.TarInfo("data")
            info.size = data_size
            info.mode = 0o600
            tw.addfile(info, CappedReader(f, data_size))
        try:
            with open(self.cache_path, "rb") as f:
                cache_size = os.fstat(f.fileno()).st_size
                cinfo = tarfile.TarInfo("cache")
                cinfo.size = cache_size
                cinfo.mode = 0o600
                tw.addfile(cinfo, f)
        except FileNotFoundError:
            cinfo = tarfile.TarInfo("cache")
            cinfo.size = 0
            cinfo.mode = 0o600
            tw.addfile(cinfo)
        tw.close()

    def read_from(self, r) -> None:
        """Restore from a write_to tar stream: replace the data file,
        reopen storage, reload the cache. Entries stream to disk in
        chunks."""
        import shutil
        import tarfile
        tr = tarfile.open(fileobj=r, mode="r|")
        import io
        # _snap_mu first (lock order): a late worker must not splice a
        # stale pre-restore snapshot over the restored file.
        with self._snap_mu, self._mu:
            for info in tr:
                src = tr.extractfile(info) or io.BytesIO()
                if info.name == "data":
                    self._close_storage()
                    tmp = self.path + ".restoring"
                    try:
                        with open(tmp, "wb") as f:
                            shutil.copyfileobj(src, f)
                            f.flush()
                            os.fsync(f.fileno())
                        os.replace(tmp, self.path)
                    except BaseException:
                        # A truncated source (aborted upload) must not
                        # leave the fragment with storage closed — the
                        # old data file is still in place; reopen it.
                        self._open_storage()
                        raise
                    self._open_storage()
                    self._epoch += 1
                    self._row_counts.clear()
                    self.row_cache.clear()
                    self.device.invalidate_all()
                    self.checksums.clear()
                elif info.name == "cache":
                    with open(self.cache_path, "wb") as f:
                        shutil.copyfileobj(src, f)
                    self.cache = cache_mod.new_cache(self.cache_type,
                                                     self.cache_size)
                    self._cache_flushed = None  # sidecar replaced
                    self._open_cache()
                else:
                    raise PilosaError(f"invalid fragment archive file:"
                                      f" {info.name!r}")

    # -- cache persistence ---------------------------------------------------

    def flush_cache(self) -> None:
        """Persist cache ids to the .cache protobuf sidecar
        (reference fragment.go:1067-1093). Skips the write when the
        serialized blob matches the last flush — the sidecar is
        already those bytes, and repeated backup/stream passes must
        not pay (or hold ``_mu`` across) an fsync per fragment."""
        with self._mu:
            if self.cache is None:
                return
            blob = pb.Cache(IDs=self.cache.ids()).SerializeToString()
            if blob == self._cache_flushed:
                return
            tmp = self.cache_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.cache_path)
            self._cache_flushed = blob
