"""ctypes loader for the native host bit kernels (pilosa_tpu/native/bitops.cpp)
with pure-numpy fallbacks.

Mirrors the reference's build-tag dispatch between assembly and generic Go
popcount (roaring/assembly_asm.go / assembly_generic.go): the native library
is built on first use with g++ and cached next to the source; if the toolchain
is unavailable every entry point falls back to vectorized numpy.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "bitops.cpp")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _so_path() -> str:
    # Cache keyed by source content hash in a per-machine dir: the binary is
    # -march=native, so a committed or stale .so from another host could
    # SIGILL. Never ship the artifact, always rebuild per (machine, source).
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    from ..utils import cache_dir
    cache = cache_dir()
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"libbitops-{digest}.so")


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            so = _so_path()
            if not os.path.exists(so):
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                     "-o", so + ".tmp", _SRC],
                    check=True, capture_output=True)
                os.replace(so + ".tmp", so)
            lib = ctypes.CDLL(so)
            _declare(lib)
            _lib = lib
        except Exception:
            _load_failed = True
        return _lib


def _declare(lib):
    # The per-container serving ops take raw void* params: a million
    # tiny container calls per query pay ~4 us each for
    # ctypes.data_as + cast, vs ~0.4 us to read the buffer address
    # from __array_interface__ — the wrappers check dtype/contiguity
    # and pass plain ints (c_void_p accepts them).
    vp = ctypes.c_void_p
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    i64 = ctypes.c_int64
    for name in ("popcnt_and", "popcnt_or", "popcnt_xor", "popcnt_andnot"):
        fn = getattr(lib, name)
        fn.argtypes = [vp, vp, i64]
        fn.restype = ctypes.c_uint64
    lib.popcnt.argtypes = [vp, i64]
    lib.popcnt.restype = ctypes.c_uint64
    lib.intersect_sorted_u32.argtypes = [vp, i64, vp, i64, vp]
    lib.intersect_sorted_u32.restype = i64
    lib.intersection_count_sorted_u32.argtypes = [vp, i64, vp, i64]
    lib.intersection_count_sorted_u32.restype = i64
    lib.union_sorted_u32.argtypes = [vp, i64, vp, i64, vp]
    lib.union_sorted_u32.restype = i64
    lib.difference_sorted_u32.argtypes = [vp, i64, vp, i64, vp]
    lib.difference_sorted_u32.restype = i64
    lib.pack_positions_u32.argtypes = [u64p, i64, ctypes.c_uint64, i64, u32p]
    lib.pack_positions_u32.restype = None
    lib.bench_setbit.argtypes = [ctypes.c_char_p, u64p, i64, i64]
    lib.bench_setbit.restype = i64
    lib.unpack_words_u32.argtypes = [u32p, i64, u64p]
    lib.unpack_words_u32.restype = i64
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(i64)
    lib.batch_add.argtypes = [i64, u64p, u8p, u64p, i64p, u32p, i64p,
                              u32p, i64p, i64p, u8p, u64p, i64p,
                              u64p, u8p, i64]
    lib.batch_add.restype = i64
    lib.batch_remove.argtypes = [i64, u64p, u8p, u64p, i64p, u32p, i64p,
                                 u32p, i64p, i64p, u8p, u64p, u8p, i64]
    lib.batch_remove.restype = i64
    lib.write_snapshot_fd.argtypes = [ctypes.c_int, i64, u64p, i64p,
                                      u8p, u64p]
    lib.write_snapshot_fd.restype = i64
    lib.bitmap_intersection_count.argtypes = [
        i64, u64p, u8p, u64p, i64p, i64, u64p, u8p, u64p, i64p]
    lib.bitmap_intersection_count.restype = i64
    lib.parse_csv_u64_pairs.argtypes = [ctypes.c_char_p, i64, u64p,
                                        u64p, i64]
    lib.parse_csv_u64_pairs.restype = i64


def _u64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _u32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def _contig(a: np.ndarray, dtype) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=dtype)


_U32 = np.dtype(np.uint32)
_U64 = np.dtype(np.uint64)


def _addr32(a: np.ndarray) -> tuple[int, np.ndarray]:
    """(buffer address, the array actually addressed) for the raw
    void* calling convention; normalizes dtype/layout only when
    needed (the hot container arrays are always contiguous u32)."""
    if a.dtype is not _U32 and a.dtype != _U32 or not a.flags.c_contiguous:
        a = np.ascontiguousarray(a, dtype=np.uint32)
    return a.__array_interface__["data"][0], a


def _addr64(a: np.ndarray) -> tuple[int, np.ndarray]:
    if a.dtype is not _U64 and a.dtype != _U64 or not a.flags.c_contiguous:
        a = np.ascontiguousarray(a, dtype=np.uint64)
    return a.__array_interface__["data"][0], a


# ---- public API -------------------------------------------------------------


def popcnt_and(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is not None:
        pa, a = _addr64(a)
        pb, b = _addr64(b)
        return int(lib.popcnt_and(pa, pb, len(a)))
    return int(np.bitwise_count(a & b).sum())


def popcnt_or(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is not None:
        pa, a = _addr64(a)
        pb, b = _addr64(b)
        return int(lib.popcnt_or(pa, pb, len(a)))
    return int(np.bitwise_count(a | b).sum())


def popcnt_xor(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is not None:
        pa, a = _addr64(a)
        pb, b = _addr64(b)
        return int(lib.popcnt_xor(pa, pb, len(a)))
    return int(np.bitwise_count(a ^ b).sum())


def popcnt_andnot(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is not None:
        pa, a = _addr64(a)
        pb, b = _addr64(b)
        return int(lib.popcnt_andnot(pa, pb, len(a)))
    return int(np.bitwise_count(a & ~b).sum())


def popcnt(a: np.ndarray) -> int:
    lib = _load()
    if lib is not None:
        pa, a = _addr64(a)
        return int(lib.popcnt(pa, len(a)))
    return int(np.bitwise_count(a).sum())


def intersect_sorted_u32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is not None:
        pa, a = _addr32(a)
        pb, b = _addr32(b)
        out = np.empty(min(len(a), len(b)), dtype=np.uint32)
        n = lib.intersect_sorted_u32(pa, len(a), pb, len(b),
                                     out.__array_interface__["data"][0])
        return out[:n]
    return np.intersect1d(a, b, assume_unique=True).astype(np.uint32)


def intersection_count_sorted_u32(a: np.ndarray, b: np.ndarray) -> int:
    lib = _load()
    if lib is not None:
        pa, a = _addr32(a)
        pb, b = _addr32(b)
        return int(lib.intersection_count_sorted_u32(pa, len(a),
                                                     pb, len(b)))
    return len(np.intersect1d(a, b, assume_unique=True))


def union_sorted_u32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is not None:
        pa, a = _addr32(a)
        pb, b = _addr32(b)
        out = np.empty(len(a) + len(b), dtype=np.uint32)
        n = lib.union_sorted_u32(pa, len(a), pb, len(b),
                                 out.__array_interface__["data"][0])
        return out[:n]
    return np.union1d(a, b).astype(np.uint32)


def difference_sorted_u32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is not None:
        pa, a = _addr32(a)
        pb, b = _addr32(b)
        out = np.empty(len(a), dtype=np.uint32)
        n = lib.difference_sorted_u32(pa, len(a), pb, len(b),
                                      out.__array_interface__["data"][0])
        return out[:n]
    return np.setdiff1d(a, b, assume_unique=True).astype(np.uint32)


def pack_positions(positions: np.ndarray, slice_width: int,
                   words_per_row: int, words: np.ndarray) -> None:
    """Scatter u64 bit positions into a row-major u32 word matrix in place."""
    if words.dtype != np.uint32 or not words.flags.c_contiguous:
        # In-place scatter needs the real buffer: reshape(-1) of a
        # non-contiguous view would silently mutate a copy.
        raise ValueError("pack_positions: words must be C-contiguous uint32")
    if len(positions):
        # The native scatter is unchecked C; validate here so corrupt input
        # raises instead of corrupting the heap.
        n_rows = words.size // words_per_row
        pos = np.asarray(positions, dtype=np.uint64)
        if int(pos.max()) >= n_rows * slice_width:
            raise ValueError("pack_positions: position out of range")
        if np.any((pos % np.uint64(slice_width)) >>
                  np.uint64(5) >= words_per_row):
            raise ValueError("pack_positions: column exceeds words_per_row")
    lib = _load()
    if lib is not None:
        positions = _contig(positions, np.uint64)
        lib.pack_positions_u32(_u64p(positions), len(positions),
                               slice_width, words_per_row,
                               _u32p(words.reshape(-1)))
        return
    pos = positions.astype(np.uint64)
    rows = (pos // np.uint64(slice_width)).astype(np.int64)
    cols = pos % np.uint64(slice_width)
    flat = rows * words_per_row + (cols >> np.uint64(5)).astype(np.int64)
    np.bitwise_or.at(words.reshape(-1), flat,
                     (np.uint32(1) << (cols & np.uint64(31)).astype(np.uint32)))


def unpack_words(words: np.ndarray) -> np.ndarray:
    """Expand a u32 word vector into sorted u64 bit positions."""
    lib = _load()
    if lib is not None:
        words = _contig(words, np.uint32)
        total = int(np.bitwise_count(words).sum())
        out = np.empty(total, dtype=np.uint64)
        n = lib.unpack_words_u32(_u32p(words), len(words), _u64p(out))
        return out[:n]
    bits = ((words[:, None] >> np.arange(32, dtype=np.uint32)) &
            np.uint32(1)).astype(bool)
    w, b = np.nonzero(bits)
    return w.astype(np.uint64) * np.uint64(32) + b.astype(np.uint64)


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def batch_add(keys, types, arr_ptrs, arr_ns, chunk_vals, chunk_starts,
              out_vals, out_offsets, out_ns, out_kind, out_bitmaps,
              out_bm_idx, changed, wal, wal_op_type: int) -> int:
    """One native crossing applying a whole add batch across touched
    containers (see bitops.cpp batch_add). Group types: 0=array,
    1=bitmap (mutated in place), 2=run (wire-form u16 buffer, decoded
    and merged through the array path — the engine's transparent run
    upgrade). Caller guarantees sizing and copy-on-write of in-place
    bitmap groups; raises if the native library is unavailable
    (roaring.apply_batch has the numpy fallback)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return int(lib.batch_add(
        len(keys), _u64p(keys), _u8p(types), _u64p(arr_ptrs),
        _i64p(arr_ns), _u32p(chunk_vals), _i64p(chunk_starts),
        _u32p(out_vals), _i64p(out_offsets), _i64p(out_ns),
        _u8p(out_kind), _u64p(out_bitmaps), _i64p(out_bm_idx),
        _u64p(changed), _u8p(wal), wal_op_type))


def batch_remove(keys, types, arr_ptrs, arr_ns, chunk_vals, chunk_starts,
                 out_vals, out_offsets, out_ns, out_kind, changed, wal,
                 wal_op_type: int) -> int:
    """One native crossing applying a whole remove batch (bitops.cpp
    batch_remove)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return int(lib.batch_remove(
        len(keys), _u64p(keys), _u8p(types), _u64p(arr_ptrs),
        _i64p(arr_ns), _u32p(chunk_vals), _i64p(chunk_starts),
        _u32p(out_vals), _i64p(out_offsets), _i64p(out_ns),
        _u8p(out_kind), _u64p(changed), _u8p(wal), wal_op_type))


def write_snapshot_fd(fd: int, keys, ns, types, ptrs) -> int:
    """Write a whole roaring snapshot from a serialization-table capture
    via batched writev straight out of the container buffers (bitops.cpp
    write_snapshot_fd). Returns bytes written, or -1 on IO error; raises
    if the native library is unavailable (write_frozen falls back)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return int(lib.write_snapshot_fd(fd, len(keys), _u64p(keys),
                                     _i64p(ns), _u8p(types), _u64p(ptrs)))


def bitmap_intersection_count(keys_a, types_a, ptrs_a, ns_a,
                              keys_b, types_b, ptrs_b, ns_b) -> int:
    """Whole-bitmap intersection count over two container tables in ONE
    crossing (bitops.cpp bitmap_intersection_count); raises when the
    native library is unavailable — the caller keeps the per-container
    walk as the fallback."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return int(lib.bitmap_intersection_count(
        len(keys_a), _u64p(keys_a), _u8p(types_a), _u64p(ptrs_a),
        _i64p(ns_a), len(keys_b), _u64p(keys_b), _u8p(types_b),
        _u64p(ptrs_b), _i64p(ns_b)))


def bench_setbit(path: str, positions: np.ndarray,
                 max_op_n: int = 2000) -> int:
    """Run the native write-path micro-engine (mutate + WAL append +
    snapshot cadence) over u64 fragment positions; returns bits
    changed, or raises if the native library is unavailable. The
    measured host denominator for the SetBit path."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    pos = _contig(positions, np.uint64)
    rc = lib.bench_setbit(path.encode(), _u64p(pos), len(pos),
                          max_op_n)
    if rc < 0:
        raise OSError("bench_setbit IO error")
    return rc


def parse_csv_pairs(data: bytes):
    """One-pass native parse of a ``digits,digits\\n`` byte buffer →
    (rows u64, cols u64), or None when the library is unavailable OR
    the buffer has any other shape (blank/3-field/non-digit lines,
    values past 2^64-1) — the caller's exact per-row path owns the
    error messages. Strictness matches the reference's ParseUint."""
    lib = _load()
    if lib is None or not data:
        return None
    cap = data.count(b"\n") + 1
    rows = np.empty(cap, dtype=np.uint64)
    cols = np.empty(cap, dtype=np.uint64)
    n = lib.parse_csv_u64_pairs(data, len(data), _u64p(rows),
                                _u64p(cols), cap)
    if n < 0:
        return None
    return rows[:n], cols[:n]


def available() -> bool:
    return _load() is not None
