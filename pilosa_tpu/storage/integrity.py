"""Storage integrity: checksummed snapshot footers + quarantine.

The fragment data file (roaring snapshot + appended op-log) is the one
layer of the durability story that carried no checksums of its own: WAL
records are FNV-checksummed and the observability rings are crc-framed,
but a flipped bit in a snapshot container block silently corrupted
Count/TopN answers forever. This module closes that hole:

- **Footer** — snapshot writers (both the Python serializer and the
  native writev path) append a version-flagged footer after the body:
  a per-container-block crc32 table, a crc32 of the header region, and
  a whole-body crc32 digest. Vintage (un-footered) files stay fully
  readable — the footer is detected by magic at the body/op-log
  boundary, and its first byte can never alias a valid op record
  (op types are 0/1).
- **Verification** — the footer's own crc and the header-region crc
  are checked at unmarshal time (cheap, O(header)); the per-block
  table is re-checked lazily on the first read after an open and
  re-checked continuously by the background scrubber
  (storage.scrub), which also cross-validates the WAL tail's FNV
  checksums.
- **Quarantine** — a mismatch quarantines the fragment in the
  holder's :class:`QuarantineRegistry`: reads fail over to a healthy
  replica through the breaker-ordered placement (executor consults
  ``slice_blocked``), no-replica reads degrade per the ``?partial=1``
  contract (503 without it), writes keep buffering through the WAL,
  and the repairer (server.repair) re-streams the content from a
  replica and un-quarantines.

Footer wire format (all little-endian), appended at the end of the
snapshot body — op-log records append AFTER the footer::

    footer := magic(u32 = 0x46B10C07)     # low byte 0x07: never a
                                          # valid op-record type (0/1)
              version(u16 = 1) flags(u16 = 0)
              bodyLen(u64)                # bytes covered: [0, bodyLen)
              blockN(u32)                 # container blocks (= keyN)
              { blockCrc32(u32) } * blockN
              headerCrc32(u32)            # crc of [0, dataStart)
              bodyCrc32(u32)              # crc of [0, bodyLen) — the
                                          # whole-file digest
              footerCrc32(u32)            # crc of the footer bytes
                                          # before this field

A truncated footer at EOF (crash mid-append on a direct write path)
reads as a torn tail — the bytes are reported so the reopen trims
them, exactly like a torn op record. A complete footer whose own crc
fails is corruption, not a tear.
"""

from __future__ import annotations

import struct
import threading
import zlib
from typing import Optional

import numpy as np

FOOTER_MAGIC = 0x46B10C07
FOOTER_VERSION = 1

_FIXED = struct.Struct("<IHHQI")     # magic, version, flags, bodyLen, blockN
_TAIL = struct.Struct("<III")        # headerCrc, bodyCrc, footerCrc
_FIXED_N = _FIXED.size               # 20
_TAIL_N = _TAIL.size                 # 12


def footer_len(block_n: int) -> int:
    return _FIXED_N + 4 * block_n + _TAIL_N


class CorruptionError(ValueError):
    """On-disk bytes contradict their recorded checksums (or a footer
    is structurally invalid). Subclasses ValueError so the vintage
    open-path error handling (which quarantines on any unmarshal
    failure) catches it uniformly."""


class TornFooterError(ValueError):
    """A footer truncated at EOF — the signature of a crash mid-append,
    not of corruption. Carries ``torn_bytes`` so the caller can trim
    the tail like any torn op record."""

    def __init__(self, torn_bytes: int):
        super().__init__(f"torn snapshot footer ({torn_bytes} bytes)")
        self.torn_bytes = torn_bytes


class FooterInfo:
    """A parsed footer plus the block layout needed to re-verify it
    against the buffer it came from."""

    __slots__ = ("version", "body_len", "block_n", "crcs",
                 "header_crc", "body_crc", "size", "offsets", "sizes")

    def __init__(self, version: int, body_len: int, block_n: int,
                 crcs: np.ndarray, header_crc: int, body_crc: int,
                 size: int):
        self.version = version
        self.body_len = body_len
        self.block_n = block_n
        self.crcs = crcs                    # u32[block_n]
        self.header_crc = header_crc
        self.body_crc = body_crc
        self.size = size                    # total footer bytes
        # Container-block layout, attached by the snapshot parser so
        # lazy per-block verification needs no re-parse.
        self.offsets: Optional[np.ndarray] = None
        self.sizes: Optional[np.ndarray] = None

    def to_json(self) -> dict:
        return {"version": self.version, "bodyLen": self.body_len,
                "blocks": self.block_n}


# -- building -----------------------------------------------------------------


def build_footer(head: bytes, block_crcs: list[int],
                 body_crc: int, body_len: int) -> bytes:
    """Assemble the footer bytes for a just-written snapshot body.
    ``head`` is the header region (cookie through the offset table),
    ``block_crcs`` one crc32 per container block in file order, and
    ``body_crc`` the running crc32 over the whole body."""
    parts = [_FIXED.pack(FOOTER_MAGIC, FOOTER_VERSION, 0, body_len,
                         len(block_crcs))]
    if block_crcs:
        parts.append(np.asarray(block_crcs,
                                dtype="<u4").tobytes())
    parts.append(struct.pack("<II", zlib.crc32(head) & 0xFFFFFFFF,
                             body_crc & 0xFFFFFFFF))
    body = b"".join(parts)
    return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)


# -- parsing / verification ---------------------------------------------------


def parse_footer(buf, offset: int) -> Optional[FooterInfo]:
    """Parse a footer at ``offset`` of ``buf`` (the end of the
    container blocks). Returns None when no footer magic is present
    (a vintage file, or op records follow directly). Raises
    :class:`TornFooterError` when a footer is truncated at EOF and
    :class:`CorruptionError` when a complete footer fails its own
    crc."""
    avail = len(buf) - offset
    if avail < 4:
        return None
    magic = int.from_bytes(bytes(buf[offset:offset + 4]), "little")
    if magic != FOOTER_MAGIC:
        return None
    if avail < _FIXED_N:
        raise TornFooterError(avail)
    ver, _flags, body_len, block_n = _FIXED.unpack(
        bytes(buf[offset:offset + _FIXED_N]))[1:]
    if ver > FOOTER_VERSION:
        raise CorruptionError(
            f"snapshot footer version {ver} unsupported")
    total = footer_len(block_n)
    if avail < total:
        raise TornFooterError(avail)
    raw = bytes(buf[offset:offset + total])
    (want_crc,) = struct.unpack("<I", raw[-4:])
    got_crc = zlib.crc32(raw[:-4]) & 0xFFFFFFFF
    if want_crc != got_crc:
        raise CorruptionError(
            f"snapshot footer crc mismatch: exp={want_crc:08x},"
            f" got={got_crc:08x}")
    crcs = np.frombuffer(raw, dtype="<u4", count=block_n,
                         offset=_FIXED_N).copy()
    header_crc, body_crc = struct.unpack(
        "<II", raw[_FIXED_N + 4 * block_n:_FIXED_N + 4 * block_n + 8])
    if body_len != offset:
        raise CorruptionError(
            f"snapshot footer bodyLen {body_len} != body end {offset}")
    return FooterInfo(ver, body_len, block_n, crcs, header_crc,
                      body_crc, total)


def verify_header(buf, header_len: int, info: FooterInfo) -> None:
    # memoryview slice: crc straight off the mmap, no copy.
    got = zlib.crc32(memoryview(buf)[:header_len]) & 0xFFFFFFFF
    if got != info.header_crc:
        raise CorruptionError(
            f"snapshot header crc mismatch: exp={info.header_crc:08x},"
            f" got={got:08x}")


def verify_body(buf, info: FooterInfo) -> None:
    """The whole-file digest: one crc pass over [0, bodyLen) —
    memoryview-sliced so a multi-GB mmap'd body is streamed by zlib,
    never copied (this runs on every cold open and scrub pass)."""
    got = zlib.crc32(memoryview(buf)[:info.body_len]) & 0xFFFFFFFF
    if got != info.body_crc:
        raise CorruptionError(
            f"snapshot body crc mismatch: exp={info.body_crc:08x},"
            f" got={got:08x}")


def parse_and_verify_footer(buf, key_n: int, header_len: int,
                            offs, sizes, body_end: int,
                            check_body: bool = False
                            ) -> Optional[FooterInfo]:
    """The ONE footer-verification sequence shared by the decoder
    (roaring.Bitmap.unmarshal) and the scrubber (storage.scrub):
    parse the footer at ``body_end`` (None for vintage files), check
    blockN against the header's keyN, verify the header-region crc,
    attach the block layout for later per-block checks, and — with
    ``check_body`` — verify the whole-body digest. Raises
    TornFooterError / CorruptionError exactly like parse_footer."""
    info = parse_footer(buf, body_end)
    if info is None:
        return None
    if info.block_n != key_n:
        raise CorruptionError(
            f"snapshot footer blockN {info.block_n} != keyN {key_n}")
    verify_header(buf, header_len, info)
    info.offsets = offs
    info.sizes = np.asarray(sizes, dtype=np.int64)
    if check_body:
        verify_body(buf, info)
    return info


def verify_blocks(buf, info: FooterInfo) -> list[int]:
    """Re-check every container block's crc32 against the footer
    table; returns the indices that mismatch (empty = clean). The
    layout arrays must have been attached by the snapshot parser."""
    offs, sizes = info.offsets, info.sizes
    if offs is None or sizes is None or info.block_n != len(offs):
        return []
    bad: list[int] = []
    mv = memoryview(buf)
    crcs = info.crcs
    for i, (off, size) in enumerate(zip(offs.tolist(),
                                        sizes.tolist())):
        if (zlib.crc32(mv[off:off + size]) & 0xFFFFFFFF) != int(crcs[i]):
            bad.append(i)
    return bad


# -- quarantine ---------------------------------------------------------------


class QuarantineRegistry:
    """Per-holder registry of quarantined fragments. The executor
    consults ``slice_blocked`` per (index, slice) on the read path (an
    O(1) rollup), /debug/integrity lists entries, and the repairer
    drains it."""

    def __init__(self):
        self._mu = threading.Lock()
        self._entries: dict[tuple, dict] = {}
        self._by_slice: dict[tuple, int] = {}
        # Wired by the server's repairer so a quarantine recorded at
        # any time (open, lazy read verify, scrub) wakes a repair
        # attempt without polling.
        self.on_quarantine = None

    @staticmethod
    def _key(frag) -> tuple:
        return (frag.index, frag.frame, frag.view, frag.slice)

    def add(self, frag, reason: str) -> bool:
        """Record ``frag`` as quarantined; returns False when it was
        already recorded (re-detections do not re-count)."""
        import time
        key = self._key(frag)
        with self._mu:
            if key in self._entries:
                self._entries[key]["reason"] = reason
                return False
            self._entries[key] = {
                "index": frag.index, "frame": frag.frame,
                "view": frag.view, "slice": frag.slice,
                "path": frag.path, "reason": reason,
                "since": time.time()}
            sk = (frag.index, frag.slice)
            self._by_slice[sk] = self._by_slice.get(sk, 0) + 1
        cb = self.on_quarantine
        if cb is not None:
            try:
                cb(frag)
            except Exception:  # noqa: BLE001 - advisory wake
                pass
        return True

    def remove(self, frag) -> bool:
        key = self._key(frag)
        with self._mu:
            if self._entries.pop(key, None) is None:
                return False
            sk = (frag.index, frag.slice)
            n = self._by_slice.get(sk, 0) - 1
            if n <= 0:
                self._by_slice.pop(sk, None)
            else:
                self._by_slice[sk] = n
        return True

    def slice_blocked(self, index: str, slice: int) -> bool:
        """True when ANY fragment of (index, slice) is quarantined
        here — the read path must not serve the slice locally."""
        if not self._by_slice:  # lock-free fast path: empty registry
            return False
        return (index, slice) in self._by_slice

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> list[dict]:
        with self._mu:
            return [dict(v) for v in self._entries.values()]
