"""Roaring bitmap engine — host-side storage layer, numpy-vectorized.

File-format compatible with the reference implementation
(/root/reference/roaring/roaring.go) so fragment data files interchange:

    snapshot  := cookie(u32 LE = 12346) keyN(u32 LE)
                 { key(u64 LE) n-1(u32 LE) } * keyN          # container headers
                 { offset(u32 LE) } * keyN                    # container offsets
                 container blocks                             # see below
    container := array  : n * u32 LE   (low-16-bit values widened to u32)
               | bitmap : 1024 * u64 LE
    op-log    := { typ(u8: 0=add 1=remove) value(u64 LE) fnv1a32(u32 LE) } *
                 appended after the snapshot body, replayed on load.

(Reference format sections: roaring.go:475-614 for snapshot,
roaring.go:1560-1626 for the op-log.)

Run containers (the reference vintage predates them) follow the later
papers — arXiv:1603.06549 and arXiv:1709.07821 — which add a third
container kind holding sorted ``[start, length]`` interval pairs plus a
cardinality-adaptive ``optimize()`` pass that picks the smallest of
array/bitmap/run per container. A snapshot containing at least one run
container uses the runs cookie (the upstream SERIAL_COOKIE idiom):

    snapshot  := cookie(u32 LE = 12347) keyN(u32 LE)
                 runFlags: ceil(keyN/8) bytes rounded up to a multiple
                           of 8 (bit i, little-endian bit order, set ⇒
                           container i is a run container)
                 { key(u64 LE) n-1(u32 LE) } * keyN   # n = cardinality
                 { offset(u32 LE) } * keyN
                 container blocks
    run block := numRuns(u16 LE) { start(u16 LE) length-1(u16 LE) } *

A snapshot with no run containers is byte-identical to the legacy
12346 form, so pre-run files (and the golden fixtures) interchange
unchanged. The op-log is kind-agnostic: replay mutates run containers
directly (interval surgery) or splits/extends them.

Design departure from the reference: containers are numpy arrays, not
pointer-chased structs — an array container is a sorted ``np.uint32`` vector
(values < 2^16), a bitmap container is an ``np.uint64[1024]`` word vector,
and a run container is a little-endian ``np.uint16`` vector that IS the
wire block (``[numRuns, start0, len0-1, ...]``), so serialization is a
write of the buffer and an mmap load is a zero-copy view.
All set algebra is vectorized (numpy or the optional C++ kernel lib in
``pilosa_tpu.native``); the same dense-word orientation is what packs straight
onto the TPU (see pilosa_tpu.ops.packed).
"""

from __future__ import annotations

import bisect
import io
import struct
import zlib
from typing import Callable, Iterator as TIterator, Optional

import numpy as np

from . import integrity as _integrity
from . import native
from . import native_ext
from . import wal as _wal_mod
from ..fault import failpoints as _fp
from ..obs import accounting as _accounting
from ..utils.arrays import searchsorted_membership, sort_dedupe


def _wal_write(writer, blob: bytes) -> None:
    """Every op-log append funnels through here. A group-commit WAL
    (storage.wal.GroupCommitWal — the fragment's default op writer)
    buffers the records; its LEADER flush is where bytes reach the
    file, so the ``wal.append`` failpoint fires there, tearing the
    GROUPED batch exactly where a crash mid-group-commit would. Plain
    file-like writers (tests attaching BytesIO, PILOSA_TPU_WAL_GROUP=0)
    keep the vintage per-append injection + write. Disarmed cost: one
    module-attr read."""
    if type(writer) is _wal_mod.GroupCommitWal:
        writer.append(blob)
        return
    if _fp.ACTIVE is not None:
        _fp.ACTIVE.hit("wal.append", writer=writer, data=blob)
    writer.write(blob)

# --- constants (match reference wire format) ---------------------------------

COOKIE = 12346               # roaring.go:30
COOKIE_RUNS = 12347          # runs format (upstream SERIAL_COOKIE idiom)
HEADER_SIZE = 8              # roaring.go:33
BITMAP_N = 1024              # u64 words per bitmap container (roaring.go:36)
ARRAY_MAX_SIZE = 4096        # roaring.go:833
# Past this many runs the run block (2 + 4R bytes) can never be the
# smallest representation (a bitmap is 8192 bytes), so mutation paths
# convert rather than let a degrading run container grow unboundedly.
RUN_MAX_SIZE = 2047
OP_SIZE = 13                 # 1 + 8 + 4 (roaring.go:1626)

OP_ADD = 0
OP_REMOVE = 1

_EMPTY_U32 = np.empty(0, dtype=np.uint32)
_EMPTY_U64 = np.empty(0, dtype=np.uint64)

# FNV-1a 32-bit (op-log checksums).
_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)

# Snapshot container-header record (key u64 LE + n-1 u32 LE, packed) —
# one definition shared by the reader (unmarshal) and the writer
# (_write_snapshot) so a format tweak cannot desynchronize them.
_HDR_DTYPE = np.dtype([("key", "<u8"), ("n", "<u4")])


def _container_sizes(ns: np.ndarray) -> np.ndarray:
    """On-disk payload bytes per container from its value count
    (n<=4096 ⇒ u32 array block, else 1024 u64 words)."""
    return np.where(ns <= ARRAY_MAX_SIZE, ns * 4, BITMAP_N * 8)


def fnv1a32(data: bytes) -> int:
    h = int(_FNV_OFFSET)
    for b in data:
        h = ((h ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFF
    return h


def highbits(v: int) -> int:
    return v >> 16


def lowbits(v: int) -> int:
    return v & 0xFFFF


# --- container ---------------------------------------------------------------


class Container:
    """One 2^16-value container: sorted u32 array, 1024-word u64 bitmap,
    or a wire-form u16 run buffer ([R, start, len-1, ...]).

    ``mapped`` marks data backed by an external (mmap'd) buffer; any mutation
    first copies (copy-on-write), mirroring the reference's ``mapped`` flag
    (roaring.go:536-614) and BitmapSegment.writable (bitmap.go:384-392).
    """

    __slots__ = ("array", "bitmap", "runs", "n", "mapped", "cow")

    def __init__(self):
        self.array: Optional[np.ndarray] = _EMPTY_U32  # sorted u32, or None
        self.bitmap: Optional[np.ndarray] = None       # u64[1024], or None
        self.runs: Optional[np.ndarray] = None         # u16 run buffer, or None
        self.n: int = 0
        self.mapped: bool = False
        # Copy-on-write token for frozen-snapshot captures: when this
        # lags the owning Bitmap's _cow_epoch, an in-place bitmap-word
        # mutation must copy the buffer first (a background snapshot
        # serializes the captured buffer by pointer). Array and run
        # buffers are replaced, never mutated in place, so they need no
        # token check.
        self.cow: int = 0

    # -- representation management

    def is_array(self) -> bool:
        return self.bitmap is None and self.runs is None

    def is_run(self) -> bool:
        return self.runs is not None

    def kind(self) -> str:
        if self.runs is not None:
            return "run"
        return "array" if self.bitmap is None else "bitmap"

    def _unmap(self) -> None:
        if self.mapped:
            if self.array is not None:
                self.array = self.array.copy()
            if self.bitmap is not None:
                self.bitmap = self.bitmap.copy()
            if self.runs is not None:
                self.runs = self.runs.copy()
            self.mapped = False

    def _to_bitmap(self) -> None:
        """array/run → bitmap conversion (roaring.go:951-976)."""
        if self.bitmap is not None:
            return
        self.bitmap = self.as_words()
        self.array = None
        self.runs = None
        self.mapped = False

    def _to_array(self) -> None:
        """bitmap/run → array conversion (roaring.go:1023-1048)."""
        if self.runs is not None:
            self.array = runs_to_values(self.runs)
            self.runs = None
            self.mapped = False
            return
        if self.bitmap is None:
            return
        self.array = bitmap_words_to_values(self.bitmap)
        self.bitmap = None
        self.mapped = False

    def _run_to_legacy(self) -> None:
        """Run → the legacy kind the n<=4096 file rule dictates — the
        transparent upgrade the bulk write paths apply before mutating
        (runs re-appear at the next optimize())."""
        if self.n > ARRAY_MAX_SIZE:
            self._to_bitmap()
        else:
            self._to_array()

    def _maybe_convert(self) -> None:
        # Invariant (required by the file format, where n<=4096 ⇒ array
        # block): array containers hold at most ARRAY_MAX_SIZE values, bitmap
        # containers strictly more. Matches reference arrayAdd/bitmapRemove
        # boundaries (roaring.go:951-953,1023-1025). Run containers are
        # exempt (the runs flag bitset identifies them on disk); they
        # only convert when mutation degrades them past the point where
        # runs could ever be the smallest form.
        if self.runs is not None:
            if (len(self.runs) >> 1) > RUN_MAX_SIZE:
                self._run_to_legacy()
            return
        if self.bitmap is None:
            if self.n > ARRAY_MAX_SIZE:
                self._to_bitmap()
        else:
            if self.n <= ARRAY_MAX_SIZE:
                self._to_array()

    def optimize(self) -> str:
        """Cardinality-adaptive representation selection (the
        runOptimize pass of arXiv:1603.06549 §3 / arXiv:1709.07821 §2.1):
        count the runs the current contents compress into and keep the
        smallest wire form — run (2+4R bytes) vs the legacy kind the
        n<=4096 rule dictates (4n or 8192). Returns the chosen kind.
        A container already in its best form is left untouched (mmap'd
        buffers stay zero-copy)."""
        if self.n == 0:
            if self.runs is not None:
                self._to_array()
            return self.kind()
        if self.runs is not None:
            n_runs = (len(self.runs) - 1) >> 1
        elif self.bitmap is None:
            n_runs = run_count_array(self.array)
        else:
            n_runs = run_count_words(self.bitmap)
        run_size = 2 + 4 * n_runs
        legacy_size = (self.n * 4 if self.n <= ARRAY_MAX_SIZE
                       else BITMAP_N * 8)
        if run_size < legacy_size:
            if self.runs is None:
                vals = (self.array if self.bitmap is None
                        else bitmap_words_to_values(self.bitmap))
                self.runs = values_to_runs(vals)
                self.array = None
                self.bitmap = None
                self.mapped = False
            return "run"
        if self.runs is not None:
            self._run_to_legacy()
        else:
            self._maybe_convert()
        return self.kind()

    # -- point ops

    def add(self, v: int) -> bool:
        # The single-op write hot path: manual copy-insert instead of
        # np.insert (which is a Python-level helper costing ~15 us per
        # call), and plain Python ints on the bitmap branch (numpy
        # scalar ops pay ~2 us each). Building a fresh array also
        # detaches from a mapped buffer, so no _unmap() copy on the
        # array branch.
        if self.runs is not None:
            return self._run_add(v)
        if self.bitmap is None:
            # Manual numpy copy-insert: the ctypes pointer prep for the
            # native kernel costs ~4 us/call (arr.ctypes construction +
            # cast) — more than the whole insert at container sizes, so
            # the C path only pays for bulk ops, not point adds.
            a = self.array
            i = int(np.searchsorted(a, v))
            if i < len(a) and a[i] == v:
                return False
            grown = np.empty(len(a) + 1, dtype=np.uint32)
            grown[:i] = a[:i]
            grown[i] = v
            grown[i + 1:] = a[i:]
            self.array = grown
            self.mapped = False
            self.n += 1
            self._maybe_convert()
            return True
        w = v >> 6
        word = int(self.bitmap[w])
        bit = 1 << (v & 63)
        if word & bit:
            return False
        self._unmap()
        self.bitmap[w] = word | bit
        self.n += 1
        return True

    def _run_add(self, v: int) -> bool:
        """Interval surgery: extend/merge the neighbouring runs or
        insert a fresh single-value run. Rebuilds the (small) buffer —
        run buffers are never mutated in place, which keeps mmap'd and
        frozen captures safe without COW bookkeeping."""
        starts, ends = _runs_starts_ends(self.runs)
        n_runs = len(starts)
        i = int(np.searchsorted(starts, v, side="right")) - 1
        if i >= 0 and v < ends[i]:
            return False
        join_prev = i >= 0 and v == int(ends[i])
        join_next = i + 1 < n_runs and v == int(starts[i + 1]) - 1
        if join_prev and join_next:
            starts = np.delete(starts, i + 1)
            ends = np.delete(ends, i)
        elif join_prev:
            ends[i] += 1
        elif join_next:
            starts[i + 1] -= 1
        else:
            starts = np.insert(starts, i + 1, v)
            ends = np.insert(ends, i + 1, v + 1)
        self.runs = _build_runs(starts, ends)
        self.mapped = False
        self.n += 1
        self._maybe_convert()
        return True

    def _run_remove(self, v: int) -> bool:
        starts, ends = _runs_starts_ends(self.runs)
        i = int(np.searchsorted(starts, v, side="right")) - 1
        if i < 0 or v >= ends[i]:
            return False
        if ends[i] - starts[i] == 1:
            starts = np.delete(starts, i)
            ends = np.delete(ends, i)
        elif v == int(starts[i]):
            starts[i] += 1
        elif v == int(ends[i]) - 1:
            ends[i] -= 1
        else:  # split the run around v
            tail_end = int(ends[i])
            ends[i] = v
            starts = np.insert(starts, i + 1, v + 1)
            ends = np.insert(ends, i + 1, tail_end)
        self.runs = _build_runs(starts, ends)
        self.mapped = False
        self.n -= 1
        self._maybe_convert()
        return True

    def remove(self, v: int) -> bool:
        if self.runs is not None:
            return self._run_remove(v)
        if self.bitmap is None:
            a = self.array
            i = int(np.searchsorted(a, v))
            if i >= len(a) or a[i] != v:
                return False
            self._unmap()
            self.array = np.delete(self.array, i)
            self.n -= 1
            return True
        w, b = v >> 6, np.uint64(1) << np.uint64(v & 63)
        if not (self.bitmap[w] & b):
            return False
        self._unmap()
        self.bitmap[w] &= ~b
        self.n -= 1
        self._maybe_convert()
        return True

    def contains(self, v: int) -> bool:
        if self.runs is not None:
            starts = self.runs[1::2]
            i = int(np.searchsorted(starts, v, side="right")) - 1
            return (i >= 0 and
                    v <= int(starts[i]) + int(self.runs[2 + 2 * i]))
        if self.bitmap is None:
            a = self.array
            i = int(np.searchsorted(a, v))
            return i < len(a) and a[i] == v
        return bool((self.bitmap[v >> 6] >> np.uint64(v & 63)) & np.uint64(1))

    # -- bulk access

    def values(self) -> np.ndarray:
        """All set low-16-bit values, sorted, as u32."""
        if self.runs is not None:
            return runs_to_values(self.runs)
        if self.bitmap is None:
            return self.array
        return bitmap_words_to_values(self.bitmap)

    def as_words(self) -> np.ndarray:
        """Dense u64[1024] word view (built on demand for array and run
        containers)."""
        if self.runs is not None:
            return runs_to_words(self.runs)
        if self.bitmap is not None:
            return self.bitmap
        a = self.array
        if a is None or not len(a):
            return np.zeros(BITMAP_N, dtype=np.uint64)
        # One pass through a byte mask + packbits beats a u64 or.at
        # scatter ~4x: both are O(range), but packbits runs at memcpy
        # speed while or.at is a per-element C loop.
        bits = np.zeros(1 << 16, dtype=np.uint8)
        bits[a] = 1
        # "<u8": packbits emits value 64w+8i+j as byte 8w+i bit j, which
        # is a little-endian u64 word regardless of host endianness.
        return np.packbits(bits, bitorder="little").view("<u8")

    def count_range(self, start: int, end: int) -> int:
        """Number of set values in [start, end) within this container."""
        start, end = max(start, 0), min(end, 1 << 16)
        if start >= end:
            return 0
        if self.runs is not None:
            starts, ends = _runs_starts_ends(self.runs)
            return int((np.clip(ends, start, end)
                        - np.clip(starts, start, end)).sum())
        if self.bitmap is None:
            a = self.array
            return int(np.searchsorted(a, end) - np.searchsorted(a, start))
        # Whole-word popcount with masked edge words — O(words), no
        # cardinality-proportional allocation.
        w0, w1 = start >> 6, (end - 1) >> 6
        words = self.bitmap[w0:w1 + 1].copy()
        words[0] &= ~np.uint64(0) << np.uint64(start & 63)
        last_bits = ((end - 1) & 63) + 1
        if last_bits < 64:
            words[-1] &= ~(~np.uint64(0) << np.uint64(last_bits))
        return int(np.bitwise_count(words).sum())

    def rank(self, v: int) -> int:
        """Number of set values <= v within this container."""
        return self.count_range(0, v + 1)

    def size_bytes(self) -> int:
        """Serialized size (roaring.go container size(); run blocks are
        numRuns(u16) + 4 bytes per interval)."""
        if self.runs is not None:
            return int(self.runs.size) * 2
        return self.n * 4 if self.bitmap is None else BITMAP_N * 8

    def check(self) -> None:
        """Internal consistency (roaring.go:653-674 spirit). Run
        containers validate the full interval invariant set: buffer
        length matches the numRuns prefix, starts strictly sorted,
        intervals non-overlapping and non-adjacent, Σ lengths == n."""
        if self.runs is not None:
            r = self.runs
            if r.ndim != 1 or not len(r):
                raise ValueError("container: malformed run buffer")
            if len(r) != 1 + 2 * int(r[0]):
                raise ValueError(
                    f"container: run buffer length {len(r)} != "
                    f"1 + 2*{int(r[0])}")
            starts, ends = _runs_starts_ends(r)
            if len(starts) > 1 and not np.all(starts[1:] > ends[:-1]):
                raise ValueError(
                    "container: runs overlapping or adjacent")
            if int((ends - starts).sum()) != self.n:
                raise ValueError(
                    f"container: run lengths sum "
                    f"{int((ends - starts).sum())} != n {self.n}")
            if len(ends) and int(ends[-1]) > 1 << 16:
                raise ValueError("container: run past 2^16")
            return
        if self.bitmap is None:
            a = self.array
            if a is None:
                raise ValueError("container: nil array")
            if len(a) != self.n:
                raise ValueError(f"container: array len {len(a)} != n {self.n}")
            if len(a) > 1 and not np.all(a[1:] > a[:-1]):
                raise ValueError("container: array not strictly sorted")
            if len(a) and int(a[-1]) > 0xFFFF:
                raise ValueError("container: array value out of range")
            if len(a) > ARRAY_MAX_SIZE:
                # n<=4096 ⇒ array is a FILE-FORMAT rule: the snapshot
                # sizer maps n>4096 to an 8192-byte bitmap block, so an
                # oversized array serializes corrupt.
                raise ValueError(
                    f"container: array n {len(a)} > {ARRAY_MAX_SIZE}")
        else:
            got = int(np.bitwise_count(self.bitmap).sum())
            if got != self.n:
                raise ValueError(f"container: bitmap count {got} != n {self.n}")

    @staticmethod
    def from_array(a: np.ndarray, mapped: bool = False) -> "Container":
        c = Container()
        c.array = a
        c.n = len(a)
        c.mapped = mapped
        return c

    @staticmethod
    def from_bitmap(words: np.ndarray, n: Optional[int] = None,
                    mapped: bool = False) -> "Container":
        c = Container()
        c.array = None
        c.bitmap = words
        c.n = int(np.bitwise_count(words).sum()) if n is None else n
        c.mapped = mapped
        return c

    @staticmethod
    def from_runs(runs: np.ndarray, n: Optional[int] = None,
                  mapped: bool = False) -> "Container":
        c = Container()
        c.array = None
        c.runs = runs
        if n is None:
            n_runs = (len(runs) - 1) >> 1
            if n_runs <= _RUN_SMALL:  # scalar beats numpy overhead
                n = sum(runs.tolist()[2::2]) + n_runs
            else:
                n = int(runs[2::2].astype(np.int64).sum()) + n_runs
        c.n = n
        c.mapped = mapped
        return c


def bitmap_words_to_values(words: np.ndarray) -> np.ndarray:
    """Expand u64 words → sorted u32 value vector (vectorized)."""
    nz = np.flatnonzero(words)
    if not len(nz):
        return _EMPTY_U32
    # Expand each non-zero word into its set bit positions.
    w = words[nz]
    bits = ((w[:, None] >> np.arange(64, dtype=np.uint64)) &
            np.uint64(1)).astype(bool)
    word_idx, bit_idx = np.nonzero(bits)
    return (nz[word_idx].astype(np.uint32) * np.uint32(64)
            + bit_idx.astype(np.uint32))


# --- run-container helpers ---------------------------------------------------
# A run buffer is a little-endian u16 vector [R, s0, l0-1, s1, l1-1, ...]
# — exactly the wire block, so snapshots write it verbatim and mmap
# loads view it zero-copy. Invariants (Container.check): starts
# strictly increasing, intervals non-overlapping AND non-adjacent
# (adjacent runs must be merged), n == Σ lengths.


def _build_runs(starts, ends) -> np.ndarray:
    """Wire-form run buffer from int64 starts/exclusive-ends vectors."""
    n_runs = len(starts)
    buf = np.empty(1 + 2 * n_runs, dtype="<u2")
    buf[0] = n_runs
    buf[1::2] = starts
    buf[2::2] = np.asarray(ends) - np.asarray(starts) - 1
    return buf


def _runs_starts_ends(runs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(starts, exclusive ends) of a run buffer as int64 vectors."""
    starts = runs[1::2].astype(np.int64)
    return starts, starts + runs[2::2].astype(np.int64) + 1


def run_count_array(a: np.ndarray) -> int:
    """Number of runs a sorted value vector would compress into."""
    if not len(a):
        return 0
    return 1 + int(np.count_nonzero(np.diff(a) != 1))


def run_count_words(words: np.ndarray) -> int:
    """Number of runs in a u64[1024] bitmap: set bits whose predecessor
    bit is clear, counted across word boundaries in one vector pass
    (the popcount((x << 1) &~ x) trick from arXiv:1709.07821 §3)."""
    carry = np.concatenate(([np.uint64(0)],
                            words[:-1] >> np.uint64(63)))
    shifted = (words << np.uint64(1)) | carry
    return int(np.bitwise_count(words & ~shifted).sum())


def values_to_runs(vals: np.ndarray) -> np.ndarray:
    """Sorted unique low-16-bit values → wire-form run buffer."""
    if not len(vals):
        return np.zeros(1, dtype="<u2")
    v = vals.astype(np.int64)
    brk = np.flatnonzero(np.diff(v) != 1)
    starts = v[np.concatenate(([0], brk + 1))]
    lasts = v[np.concatenate((brk, [len(v) - 1]))]
    return _build_runs(starts, lasts + 1)


def runs_to_values(runs: np.ndarray) -> np.ndarray:
    """Run buffer → sorted u32 value vector (vectorized decode)."""
    starts, ends = _runs_starts_ends(runs)
    lens = ends - starts
    total = int(lens.sum())
    if not total:
        return _EMPTY_U32
    offs = np.concatenate(([0], np.cumsum(lens[:-1])))
    return (np.repeat(starts - offs, lens)
            + np.arange(total)).astype(np.uint32)


def runs_to_words(runs: np.ndarray) -> np.ndarray:
    """Run buffer → dense u64[1024] words — the device decode step:
    residency uploads blit this straight into bit-plane slabs (see
    ops.packed). Boundary-mark + cumsum, O(2^16) regardless of
    cardinality; the non-adjacency invariant guarantees every mark
    index is distinct, so plain fancy assignment is safe."""
    starts, ends = _runs_starts_ends(runs)
    mark = np.zeros((1 << 16) + 1, dtype=np.int8)
    mark[starts] = 1
    mark[ends] = -1
    cov = np.cumsum(mark[:-1], dtype=np.int8).astype(np.uint8)
    return np.packbits(cov, bitorder="little").view("<u8")


def _runs_member(runs: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Boolean membership mask of sorted values against a run buffer —
    one searchsorted over the starts (the vectorized form of the
    galloping probe)."""
    starts = runs[1::2]
    if not len(starts):
        return np.zeros(len(vals), dtype=bool)
    i = np.searchsorted(starts, vals, side="right").astype(np.int64) - 1
    safe = np.maximum(i, 0)
    lasts = starts[safe].astype(np.int64) + runs[2::2][safe].astype(np.int64)
    return (i >= 0) & (vals.astype(np.int64) <= lasts)


def _runs_coverage_at(runs: np.ndarray, xs: np.ndarray) -> np.ndarray:
    """Σ |[s,e) ∩ [0,x)| per x — prefix coverage of a run set, one
    searchsorted + clip (no event sweep)."""
    starts, ends = _runs_starts_ends(runs)
    lens = ends - starts
    prefix = np.concatenate(([0], np.cumsum(lens)))
    k = np.searchsorted(starts, xs, side="right")
    km = np.maximum(k - 1, 0)
    overshoot = np.where(k > 0,
                         np.clip(ends[km] - xs, 0, lens[km]), 0)
    return prefix[k] - overshoot


# Interval-list size (Ra + Rb) below which the run algebra takes
# plain-int scalar paths: well-compressed containers hold 1-3 runs,
# where ~10 vectorized numpy calls of fixed ~1.5 us overhead each cost
# 10x the actual work.
_RUN_SMALL = 16


def _run_overlap_count(a_runs: np.ndarray, b_runs: np.ndarray) -> int:
    """|A ∩ B| for two run sets: each A-interval's overlap with B is a
    prefix-coverage difference — the intersection_count fast path for
    the Count(Intersect) serving shape (no merged interval list is
    ever built). Tiny lists take a scalar two-pointer merge."""
    n_a = (len(a_runs) - 1) >> 1
    n_b = (len(b_runs) - 1) >> 1
    if not n_a or not n_b:
        return 0
    if n_a + n_b <= _RUN_SMALL:
        al, bl = a_runs.tolist(), b_runs.tolist()
        total = 0
        i = j = 0
        while i < n_a and j < n_b:
            s1 = al[1 + 2 * i]
            e1 = s1 + al[2 + 2 * i] + 1
            s2 = bl[1 + 2 * j]
            e2 = s2 + bl[2 + 2 * j] + 1
            lo, hi = max(s1, s2), min(e1, e2)
            if hi > lo:
                total += hi - lo
            if e1 <= e2:
                i += 1
            else:
                j += 1
        return total
    sa, ea = _runs_starts_ends(a_runs)
    return int((_runs_coverage_at(b_runs, ea)
                - _runs_coverage_at(b_runs, sa)).sum())


def _interval_combine_small(a_runs: np.ndarray, b_runs: np.ndarray,
                            op: str) -> np.ndarray:
    """Scalar event sweep for tiny interval lists (see _RUN_SMALL)."""
    evs = []
    for runs, da, db in ((a_runs, 1, 0), (b_runs, 0, 1)):
        rl = runs.tolist()
        for i in range((len(rl) - 1) >> 1):
            s = rl[1 + 2 * i]
            e = s + rl[2 + 2 * i] + 1
            evs.append((s, da, db))
            evs.append((e, -da, -db))
    evs.sort()
    res_s: list[int] = []
    res_e: list[int] = []
    ca = cb = 0
    k, n = 0, len(evs)
    while k < n:
        pos = evs[k][0]
        while k < n and evs[k][0] == pos:
            ca += evs[k][1]
            cb += evs[k][2]
            k += 1
        if k >= n:
            break
        if op == "and":
            act = ca > 0 and cb > 0
        elif op == "or":
            act = ca > 0 or cb > 0
        elif op == "andnot":
            act = ca > 0 and cb <= 0
        else:  # xor
            act = (ca > 0) != (cb > 0)
        if act:
            if res_e and res_e[-1] == pos:
                res_e[-1] = evs[k][0]
            else:
                res_s.append(pos)
                res_e.append(evs[k][0])
    if not res_s:
        return np.zeros(1, dtype="<u2")
    flat = [len(res_s)]
    for s, e in zip(res_s, res_e):
        flat.append(s)
        flat.append(e - s - 1)
    return np.array(flat, dtype="<u2")


def _interval_combine(a_runs: np.ndarray, b_runs: np.ndarray,
                      op: str) -> np.ndarray:
    """The run×run algebra engine: boundary-event sweep over both
    interval sets, one argsort + two cumsums, emitting the merged runs
    where the per-operand coverage satisfies ``op`` (and/or/andnot/
    xor). O((Ra+Rb) log(Ra+Rb)) — never touches cardinality. Tiny
    lists (the well-compressed common case) take the scalar sweep."""
    if len(a_runs) + len(b_runs) <= 2 * _RUN_SMALL + 2:
        return _interval_combine_small(a_runs, b_runs, op)
    sa, ea = _runs_starts_ends(a_runs)
    sb, eb = _runs_starts_ends(b_runs)
    na, nb = len(sa), len(sb)
    pos = np.concatenate([sa, ea, sb, eb])
    da = np.zeros(2 * (na + nb), dtype=np.int64)
    da[:na] = 1
    da[na:2 * na] = -1
    db = np.zeros(2 * (na + nb), dtype=np.int64)
    db[2 * na:2 * na + nb] = 1
    db[2 * na + nb:] = -1
    order = np.argsort(pos, kind="stable")
    pos = pos[order]
    ca = np.cumsum(da[order])
    cb = np.cumsum(db[order])
    # Collapse duplicate boundary positions: the coverage between two
    # distinct positions is the cumsum at the LAST event of the lower.
    if len(pos) > 1:
        last = np.concatenate((pos[1:] != pos[:-1], [True]))
        pos, ca, cb = pos[last], ca[last], cb[last]
    ina, inb = ca > 0, cb > 0
    if op == "and":
        act = ina & inb
    elif op == "or":
        act = ina | inb
    elif op == "andnot":
        act = ina & ~inb
    else:  # xor
        act = ina ^ inb
    idx = np.flatnonzero(act[:-1]) if len(pos) > 1 else \
        np.empty(0, dtype=np.int64)
    if not len(idx):
        return np.zeros(1, dtype="<u2")
    # Segments tile the breakpoint span, so consecutive kept indices
    # are adjacent intervals — merge each consecutive group into one run.
    brk = np.flatnonzero(np.diff(idx) != 1) + 1
    gs = np.concatenate(([0], brk))
    ge = np.concatenate((brk, [len(idx)]))
    return _build_runs(pos[idx[gs]], pos[idx[ge - 1] + 1])


# --- container set algebra (vectorized; native C++ when available) -----------

# Per-(op, operand-kind) call counters — the per-container-type
# statistics the Roaring library paper (arXiv:1709.07821) credits for
# making its optimizations tractable. Pre-seeded plain ints bumped
# inline (GIL-coarse increments; a rare lost count is acceptable for
# metrics), published as pilosa_roaring_container_ops_total by the
# runtime collector (obs.runtime).
OP_KINDS = ("array_array", "array_bitmap", "bitmap_bitmap",
            "run_run", "run_array", "run_bitmap")
_OPS = ("intersect", "intersection_count", "union", "difference", "xor")
_OP_COUNTS: dict[tuple[str, str], int] = {
    (op, kind): 0 for op in _OPS for kind in OP_KINDS}

# Canonical pair naming order for the operand-kind label.
_KIND_ORDER = {"run": 0, "array": 1, "bitmap": 2}


def _op_kind(a: Container, b: Container) -> str:
    ka, kb = a.kind(), b.kind()
    if _KIND_ORDER[kb] < _KIND_ORDER[ka]:
        ka, kb = kb, ka
    return f"{ka}_{kb}"


def op_counts() -> dict[tuple[str, str], int]:
    """Snapshot of the container set-algebra op counters."""
    return dict(_OP_COUNTS)


# Fixed bitmap-container word count (65536 bits / 64-bit words) — the
# scan cost a bitmap operand contributes to the per-query ledger.
_BITMAP_WORDS = 1024


def _scan_words(c: Container) -> int:
    """Word-equivalents one operand contributes: a bitmap container is
    a full 1024-word scan; an array container counts its elements at
    64 per word (the comparable memory-traffic unit); a run container
    counts its interval list's bytes at 8 per word — the whole point of
    runs showing up in the ledger is that this number collapses on
    sorted data."""
    if c.runs is not None:
        return max(1, (int(c.runs.size) * 2) >> 3)
    if c.is_array():
        return (len(c.array) + 63) >> 6
    return _BITMAP_WORDS


def _bump(op: str, a: Container, b: Container) -> None:
    """One site feeding BOTH accountings: the process-global counters
    (pilosa_roaring_container_ops_total via the runtime collector) and
    the current query's cost ledger (obs.accounting) when one is bound
    to this thread — per-query container-kind attribution is the whole
    point of the ledger (arXiv:1709.07821's per-container-type
    statistics, per query)."""
    kind = _op_kind(a, b)
    _OP_COUNTS[(op, kind)] += 1
    cost = _accounting.current_cost()
    if cost is not None:
        cost.note_container_op(op, kind,
                               _scan_words(a) + _scan_words(b))


# Size ratio past which a sorted-array intersection switches from the
# linear two-pointer merge to binary-search probes of the small side
# into the large (the galloping/skewed strategy of arXiv:1709.07821
# §4.2 — vectorized here as one searchsorted_membership pass).
_GALLOP_RATIO = 64


def _skewed(a: np.ndarray, b: np.ndarray) -> bool:
    na, nb = len(a), len(b)
    return min(na, nb) * _GALLOP_RATIO < max(na, nb)


def _settle(c: Container) -> Container:
    """Pick the smallest representation for an algebra result that came
    out as runs (the output half of the cardinality-adaptive kernel
    selection — a 3-run intersection result should not stay a run
    container if 2 array values are smaller)."""
    c.optimize()
    return c


def _as_runs(c: Container) -> np.ndarray:
    """Operand's interval form for the run×run engine (arrays convert
    in O(n); callers keep bitmaps on the word path instead)."""
    if c.runs is not None:
        return c.runs
    return values_to_runs(c.array)


def _intersect(a: Container, b: Container) -> Container:
    _bump("intersect", a, b)
    ra, rb = a.runs is not None, b.runs is not None
    if ra or rb:
        if (ra or a.bitmap is None) and (rb or b.bitmap is None):
            if ra != rb:
                # run ∩ array via membership probes of the array into
                # the run list — O(n_array log R), no interval sweep.
                run, arr = (a, b) if ra else (b, a)
                return Container.from_array(
                    arr.array[_runs_member(run.runs, arr.array)])
            return _settle(Container.from_runs(
                _interval_combine(a.runs, b.runs, "and")))
        run, bmp = (a, b) if ra else (b, a)
        words = runs_to_words(run.runs) & bmp.bitmap
        c = Container.from_bitmap(words)
        c._maybe_convert()
        return c
    if a.is_array() and b.is_array():
        if _skewed(a.array, b.array):
            small, big = ((a.array, b.array)
                          if len(a.array) <= len(b.array)
                          else (b.array, a.array))
            hit, _ = searchsorted_membership(big, small)
            return Container.from_array(small[hit])
        out = native.intersect_sorted_u32(a.array, b.array)
        return Container.from_array(out)
    if a.is_array() != b.is_array():
        arr, bmp = (a, b) if a.is_array() else (b, a)
        av = arr.array
        hit = (bmp.bitmap[av >> np.uint32(6)] >>
               (av.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
        return Container.from_array(av[hit.astype(bool)])
    words = a.bitmap & b.bitmap
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


def _intersection_count(a: Container, b: Container) -> int:
    _bump("intersection_count", a, b)
    ra, rb = a.runs is not None, b.runs is not None
    if ra or rb:
        if ra and rb:
            return _run_overlap_count(a.runs, b.runs)
        run, other = (a, b) if ra else (b, a)
        if other.bitmap is None:
            return int(_runs_member(run.runs, other.array).sum())
        return native.popcnt_and(runs_to_words(run.runs), other.bitmap)
    if a.is_array() and b.is_array():
        if _skewed(a.array, b.array):
            small, big = ((a.array, b.array)
                          if len(a.array) <= len(b.array)
                          else (b.array, a.array))
            hit, _ = searchsorted_membership(big, small)
            return int(hit.sum())
        return native.intersection_count_sorted_u32(a.array, b.array)
    if a.is_array() != b.is_array():
        arr, bmp = (a, b) if a.is_array() else (b, a)
        av = arr.array
        hit = (bmp.bitmap[av >> np.uint32(6)] >>
               (av.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
        return int(hit.sum())
    return native.popcnt_and(a.bitmap, b.bitmap)


def _union(a: Container, b: Container) -> Container:
    _bump("union", a, b)
    ra, rb = a.runs is not None, b.runs is not None
    if ra or rb:
        if (ra or a.bitmap is None) and (rb or b.bitmap is None):
            return _settle(Container.from_runs(
                _interval_combine(_as_runs(a), _as_runs(b), "or")))
        run, bmp = (a, b) if ra else (b, a)
        c = Container.from_bitmap(runs_to_words(run.runs) | bmp.bitmap)
        c._maybe_convert()
        return c
    if a.is_array() and b.is_array():
        out = np.union1d(a.array, b.array).astype(np.uint32)
        c = Container.from_array(out)
        c._maybe_convert()
        return c
    words = a.as_words() | b.as_words()
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


def _difference(a: Container, b: Container) -> Container:
    _bump("difference", a, b)
    ra, rb = a.runs is not None, b.runs is not None
    if ra or rb:
        if not ra and a.bitmap is None:  # array \ run: membership drop
            return Container.from_array(
                a.array[~_runs_member(b.runs, a.array)])
        if (ra or a.bitmap is None) and (rb or b.bitmap is None):
            return _settle(Container.from_runs(
                _interval_combine(_as_runs(a), _as_runs(b), "andnot")))
        words = a.as_words() & ~b.as_words()
        c = Container.from_bitmap(words)
        c._maybe_convert()
        return c
    if a.is_array():
        av = a.array
        if b.is_array():
            keep = ~np.isin(av, b.array, assume_unique=True)
        else:
            keep = ~((b.bitmap[av >> np.uint32(6)] >>
                      (av.astype(np.uint64) & np.uint64(63))) &
                     np.uint64(1)).astype(bool)
        return Container.from_array(av[keep])
    words = a.bitmap & ~b.as_words()
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


def _xor(a: Container, b: Container) -> Container:
    _bump("xor", a, b)
    ra, rb = a.runs is not None, b.runs is not None
    if ra or rb:
        if (ra or a.bitmap is None) and (rb or b.bitmap is None):
            return _settle(Container.from_runs(
                _interval_combine(_as_runs(a), _as_runs(b), "xor")))
        words = a.as_words() ^ b.as_words()
        c = Container.from_bitmap(words)
        c._maybe_convert()
        return c
    if a.is_array() and b.is_array():
        out = np.setxor1d(a.array, b.array, assume_unique=True).astype(np.uint32)
        c = Container.from_array(out)
        c._maybe_convert()
        return c
    words = a.as_words() ^ b.as_words()
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


# --- op-log ------------------------------------------------------------------


_OP_BODY = struct.Struct("<BQ")  # op type + u64 value (13-byte record w/ checksum)


def fnv_fold_records(recs: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a over the 9 body bytes of each 13-byte op
    record row ([n, OP_SIZE] u8) — the ONE checksum fold shared by
    the record builder (_wal_blob), replay validation (_replay_ops),
    and the scrubber's WAL-tail cross-check (storage.scrub)."""
    h = np.full(len(recs), int(_FNV_OFFSET), dtype=np.uint32)
    for i in range(9):
        h = (h ^ recs[:, i].astype(np.uint32)) * _FNV_PRIME
    return h


def _wal_blob(values: np.ndarray, typ: int) -> bytes:
    """13-byte op records for a value vector, checksummed, vectorized —
    the group-commit form of Op.marshal (verified byte-identical in
    tests; 0.1 us/record vs ~2 us through the scalar path). With the
    extension loaded the whole build is one GIL-released C crossing, so
    concurrent import threads' record builds overlap each other's
    applies."""
    ext = native_ext.EXT
    if ext is not None:
        fn = getattr(ext, "wal_records", None)
        if fn is not None:
            return fn(np.ascontiguousarray(values, dtype=np.uint64),
                      typ)
    n = len(values)
    rec = np.zeros((n, OP_SIZE), dtype=np.uint8)
    rec[:, 0] = typ
    rec[:, 1:9] = values.astype("<u8").view(np.uint8).reshape(n, 8)
    h = fnv_fold_records(rec)
    rec[:, 9:13] = h.astype("<u4").view(np.uint8).reshape(n, 4)
    return rec.tobytes()


class Op:
    """One op-log record (roaring.go:1560-1626)."""

    __slots__ = ("typ", "value")

    def __init__(self, typ: int, value: int):
        self.typ = typ
        self.value = value

    def marshal(self) -> bytes:
        body = _OP_BODY.pack(self.typ, self.value)
        return body + fnv1a32(body).to_bytes(4, "little")

    @staticmethod
    def unmarshal(buf: memoryview) -> "Op":
        if len(buf) < OP_SIZE:
            raise ValueError(f"op data out of bounds: len={len(buf)}")
        body = bytes(buf[:9])
        chk = int.from_bytes(buf[9:13], "little")
        want = fnv1a32(body)
        if chk != want:
            raise ValueError(f"checksum mismatch: exp={want:08x}, got={chk:08x}")
        return Op(body[0], int.from_bytes(body[1:9], "little"))

    def apply(self, b: "Bitmap") -> bool:
        if self.typ == OP_ADD:
            return b._add(self.value)
        if self.typ == OP_REMOVE:
            return b._remove(self.value)
        raise ValueError(f"invalid op type: {self.typ}")


def _replay_ops(b: "Bitmap", rest: memoryview,
                tolerate_torn_tail: bool) -> None:
    """Replay a trailing op-log in bulk.

    The scalar record walk this replaces cost ~10 us/op — a reopen
    after one 250 K-bit wire-import block paid 2.7 s of replay, which
    is what forced a snapshot per import block (MAX_OP_N bounds
    REPLAY time, so replay speed sets how much op-log a fragment may
    carry). Here validation is one vectorized pass (the same FNV fold
    as the `_wal_blob` record builder) and maximal same-type op runs
    apply through add_many/remove_many: order across runs is
    preserved, and within a same-type run set semantics are order-
    and duplicate-insensitive. Error contract matches the scalar
    walk: a torn (partial) trailing record is tolerated only under
    ``tolerate_torn_tail`` (reported via ``torn_bytes``); a complete
    record with a bad checksum or unknown type raises — the caller
    discards ``b``, so prevalidating before any apply is
    unobservable. Container representations may differ from scalar
    replay (bulk lanes upgrade touched run containers to legacy
    kinds); the serialized-set contract is unchanged."""
    n_rest = len(rest)
    n_ops = n_rest // OP_SIZE
    torn = n_rest - n_ops * OP_SIZE
    if torn and not tolerate_torn_tail:
        raise ValueError(f"op data out of bounds: len={torn}")
    if n_ops:
        recs = np.frombuffer(rest, dtype=np.uint8,
                             count=n_ops * OP_SIZE).reshape(n_ops,
                                                            OP_SIZE)
        h = fnv_fold_records(recs)
        stored = np.ascontiguousarray(recs[:, 9:13]).view("<u4").ravel()
        types = recs[:, 0]
        bad_chk = np.flatnonzero(h != stored)
        bad_typ = np.flatnonzero(types > OP_REMOVE)
        first_chk = int(bad_chk[0]) if len(bad_chk) else n_ops
        first_typ = int(bad_typ[0]) if len(bad_typ) else n_ops
        if first_chk <= first_typ and first_chk < n_ops:
            raise ValueError(
                f"checksum mismatch: exp={int(h[first_chk]):08x},"
                f" got={int(stored[first_chk]):08x}")
        if first_typ < n_ops:
            raise ValueError(f"invalid op type: {int(types[first_typ])}")
        vals = np.ascontiguousarray(recs[:, 1:9]).view("<u8").ravel()
        bnd = np.flatnonzero(types[1:] != types[:-1]) + 1
        starts = np.concatenate(([0], bnd))
        ends = np.concatenate((bnd, [n_ops]))
        for s, e in zip(starts.tolist(), ends.tolist()):
            if e - s < 16:
                # Tiny runs (alternating add/remove traffic): the
                # scalar ops beat the bulk lanes' fixed numpy overhead.
                apply = b._add if types[s] == OP_ADD else b._remove
                for v in vals[s:e].tolist():
                    apply(int(v))
            elif types[s] == OP_ADD:
                b.add_many(vals[s:e])
            else:
                b.remove_many(vals[s:e])
        b.op_n += n_ops
    if torn:
        b.torn_bytes = torn


# --- bitmap ------------------------------------------------------------------


class Bitmap:
    """Two-level roaring bitmap: sorted high-48-bit keys → containers.

    ``op_writer`` (a binary file-like) mirrors the reference's OpWriter hook
    (roaring.go:51,616-628): when set, every add/remove appends an op record.
    """

    def __init__(self, *values: int):
        self.keys: list[int] = []
        self.containers: list[Container] = []
        self.op_writer = None
        self.op_n = 0      # ops appended/replayed since last snapshot
        self.torn_bytes = 0  # dangling tail bytes found during unmarshal
        # Parsed integrity footer (storage.integrity.FooterInfo) when
        # the decoded snapshot carried one; None for vintage files and
        # wire-form buffers. Consumers (fragment lazy verify, scrub)
        # re-check its per-block crc table against the backing buffer.
        self.footer = None
        # Monotonic mutation counter: bumped by every mutating entry
        # point so derived-value memos (e.g. the fragment src-key
        # cache) can validate against in-place mutation instead of
        # trusting object identity.
        self.version = 0
        # Frozen-capture COW epoch (see Container.cow) and the
        # incrementally-maintained serialization table (see _SerTable).
        # Point mutations record their container key in _table_dirty
        # instead of invalidating the table — the entries are patched
        # in bulk before any table read (_flush_table_dirty), keeping
        # the MAX_OP_N freeze O(dirty) instead of O(all containers)
        # for per-op write workloads.
        self._cow_epoch = 0
        self._table: Optional[_SerTable] = None
        self._table_dirty: set[int] = set()
        # Containers created by POINT ops while a table exists: their
        # insertion is deferred to _flush_table_dirty (one vectorized
        # table.insert per freeze) instead of invalidating the table —
        # a wholesale rebuild is an O(all containers) Python walk that
        # dominated the per-op write path's MAX_OP_N snapshot cadence
        # on fragments growing by point writes.
        self._table_new: set[int] = set()
        for v in values:
            self._add(v)

    def _guard_inplace(self, c: Container) -> None:
        """Make c's bitmap words safe to mutate in place: copy out of an
        mmap (the mapped flag) or out of a frozen snapshot capture (the
        cow token)."""
        if c.mapped:
            c._unmap()
            c.cow = self._cow_epoch
        elif c.cow != self._cow_epoch:
            if c.bitmap is not None:
                c.bitmap = c.bitmap.copy()
            c.cow = self._cow_epoch

    # -- container lookup

    def _index(self, key: int) -> int:
        """Bisect keys; returns insertion point."""
        return bisect.bisect_left(self.keys, key)

    def container(self, key: int) -> Optional[Container]:
        i = self._index(key)
        if i < len(self.keys) and self.keys[i] == key:
            return self.containers[i]
        return None

    def _container_or_create(self, key: int) -> Container:
        i = self._index(key)
        if i < len(self.keys) and self.keys[i] == key:
            return self.containers[i]
        c = Container()
        self.keys.insert(i, key)
        self.containers.insert(i, c)
        return c

    # -- point ops (public ops write to the op-log; _ops do not)

    def add(self, v: int) -> bool:
        # The per-op write hot path: ONE compiled crossing does the
        # container mutate AND builds the marshaled WAL record
        # (native/fastmutate.c), so Python only appends the returned
        # bytes to the group-commit log. The extension bails (None) on
        # anything unusual — new container, COW-stale bitmap words,
        # odd buffers — and the pure-Python path below re-runs the op
        # from scratch (the extension made no state change when it
        # bails), keeping behavior identical by construction.
        ext = native_ext.EXT
        if ext is not None:
            rec = ext.setbit(self, v)
            if rec is not None:
                if rec is False:
                    return False
                # _write_op_bytes/_wal_write inlined (two frames of
                # pure dispatch at per-op serving rates): group WAL
                # appends go straight to the buffer; plain writers
                # keep the failpoint injection.
                w = self.op_writer
                if w is not None:
                    if type(w) is _wal_mod.GroupCommitWal:
                        w.append(rec)
                    else:
                        if _fp.ACTIVE is not None:
                            _fp.ACTIVE.hit("wal.append", writer=w,
                                           data=rec)
                        w.write(rec)
                    self.op_n += 1
                return True
        changed = self._add(v)
        if changed:
            self._write_op(Op(OP_ADD, v))
        return changed

    def _add(self, v: int) -> bool:
        self.version += 1
        key = highbits(v)
        if self._table is not None:
            n0 = len(self.keys)
            c = self._container_or_create(key)
            if len(self.keys) != n0:
                self._table_new.add(key)  # deferred table insert
            else:
                self._table_dirty.add(key)
        else:
            c = self._container_or_create(key)
        if c.bitmap is not None:
            self._guard_inplace(c)
        return c.add(lowbits(v))

    def remove(self, v: int) -> bool:
        ext = native_ext.EXT
        if ext is not None:
            rec = ext.clearbit(self, v)
            if rec is not None:
                if rec is False:
                    return False
                w = self.op_writer
                if w is not None:  # same inlining as add()
                    if type(w) is _wal_mod.GroupCommitWal:
                        w.append(rec)
                    else:
                        if _fp.ACTIVE is not None:
                            _fp.ACTIVE.hit("wal.append", writer=w,
                                           data=rec)
                        w.write(rec)
                    self.op_n += 1
                return True
        changed = self._remove(v)
        if changed:
            self._write_op(Op(OP_REMOVE, v))
        return changed

    def _remove(self, v: int) -> bool:
        self.version += 1
        key = highbits(v)
        c = self.container(key)
        if c is None:
            return False
        if self._table is not None:
            self._table_dirty.add(key)
        if c.bitmap is not None:
            self._guard_inplace(c)
        return c.remove(lowbits(v))

    def contains(self, v: int) -> bool:
        c = self.container(highbits(v))
        return c.contains(lowbits(v)) if c is not None else False

    def _write_op(self, op: Op) -> None:
        if self.op_writer is not None:
            _wal_write(self.op_writer, op.marshal())
            self.op_n += 1

    def _write_op_bytes(self, rec: bytes) -> None:
        """Append an already-marshaled op record (the one-crossing
        extension returns the bytes; byte-identical to Op.marshal)."""
        if self.op_writer is not None:
            _wal_write(self.op_writer, rec)
            self.op_n += 1

    # -- bulk ops

    def add_many(self, values: np.ndarray) -> int:
        """Vectorized bulk add of a u64 value vector. Returns #newly set.

        The import hot path (reference: fragment.go:924-989 detaches the op
        writer and bulk-adds); callers snapshot afterwards.
        """
        values = np.asarray(values, dtype=np.uint64)
        if not len(values):
            return 0
        values = sort_dedupe(values)
        self.version += 1
        self._table = None
        highs = values >> np.uint64(16)
        lows = (values & np.uint64(0xFFFF)).astype(np.uint32)
        bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(values)]))
        # One vectorized key probe for every group (sparse imports touch
        # hundreds of thousands of containers; per-group bisect +
        # list.insert was quadratic in the table size).
        uniq = highs[starts]
        key_arr = self._keys_np()
        exists, idx = searchsorted_membership(key_arr, uniq)
        if not exists.all():
            self._insert_containers(uniq[~exists].tolist())
            idx = np.searchsorted(self._keys_np(), uniq)
        containers = self.containers
        conts = [containers[i] for i in idx.tolist()]
        added = 0
        n_g = len(conts)
        for c in conts:
            # Bulk paths transparently upgrade run containers to the
            # legacy kind before merging; optimize() re-compresses
            # after the batch (import contract, arXiv:1709.07821 §2.1).
            if c.runs is not None:
                c._run_to_legacy()
        bm_mask = np.fromiter((c.bitmap is not None for c in conts),
                              bool, n_g)
        for gi in np.flatnonzero(bm_mask).tolist():
            # OR-scatter straight into the word vector: O(chunk + words),
            # no representation churn for the dense-import hot path.
            chunk = lows[starts[gi]:ends[gi]]
            c = conts[gi]
            before = c.n
            self._guard_inplace(c)
            np.bitwise_or.at(
                c.bitmap, chunk >> np.uint32(6),
                np.uint64(1) << (chunk.astype(np.uint64) & np.uint64(63)))
            c.n = int(np.bitwise_count(c.bitmap).sum())
            c._maybe_convert()
            added += c.n - before
        arr_gis = np.flatnonzero(~bm_mask)
        if len(arr_gis) > 256:
            added += self._merge_array_groups_global(
                conts, arr_gis, uniq, values, bm_mask,
                (ends - starts).astype(np.int64))
        else:
            for gi in arr_gis.tolist():
                chunk = lows[starts[gi]:ends[gi]]
                c = conts[gi]
                before = c.n
                if c.n == 0:
                    # Copy the chunk out of the batch vector: a view
                    # would pin the WHOLE batch buffer for the
                    # container's lifetime (review finding — a few tiny
                    # surviving containers must not hold a 10 M-value
                    # batch's 80 MB alive). The global-merge path keeps
                    # views because there the base is collectively
                    # covered by its containers.
                    c.array, c.bitmap, c.n = chunk.copy(), None, len(chunk)
                    c.mapped = False
                else:
                    merged = np.union1d(c.array, chunk).astype(np.uint32)
                    c._unmap()
                    c.array, c.n = merged, len(merged)
                c._maybe_convert()
                added += c.n - before
        return added

    def _merge_array_groups_global(self, conts, arr_gis, uniq, values,
                                   bm_mask, group_lens) -> int:
        """Merge a large batch of value groups into their array-form
        containers in ONE vectorized pass: gather every target
        container's current values into a single u64 position vector,
        union it with the incoming values, then re-slice the result
        back into per-container views. Replaces a per-group union1d
        (~8 us/group — the import long pole at 10^5..10^6 touched
        containers, e.g. a 100 K-row sparse frame) with work that is
        O(total values) regardless of group count."""
        sel_conts = [conts[g] for g in arr_gis.tolist()]
        lens = np.fromiter((c.n for c in sel_conts), np.int64,
                           len(sel_conts))
        old_total = int(lens.sum())
        key_sel = uniq[arr_gis]
        if old_total:
            old_low = np.concatenate(
                [c.array for c in sel_conts if c.n])
            old_vals = ((np.repeat(key_sel, lens) << np.uint64(16))
                        | old_low.astype(np.uint64))
        else:
            old_vals = _EMPTY_U64
        new_vals = values[np.repeat(~bm_mask, group_lens)]
        merged = np.union1d(old_vals, new_vals)
        mh = merged >> np.uint64(16)
        ml = (merged & np.uint64(0xFFFF)).astype(np.uint32)
        b2 = np.flatnonzero(mh[1:] != mh[:-1]) + 1
        s2 = np.concatenate(([0], b2))
        e2 = np.concatenate((b2, [len(merged)]))
        # Every selected group contributes >=1 incoming value and every
        # gathered value came from a selected container, so the merged
        # key set equals key_sel exactly and stays aligned by sort order.
        ns2 = (e2 - s2)
        for c, s, e, n in zip(sel_conts, s2.tolist(), e2.tolist(),
                              ns2.tolist()):
            c.array, c.bitmap, c.n, c.mapped = ml[s:e], None, n, False
        for g in np.flatnonzero(ns2 > ARRAY_MAX_SIZE).tolist():
            sel_conts[g]._to_bitmap()
        return len(merged) - old_total

    def remove_many(self, values: np.ndarray) -> int:
        """Vectorized bulk remove of a u64 value vector; returns #cleared.

        The anti-entropy bulk-repair path (reference fragment.go:802-920
        applies merge diffs through the fragment with the op log handled
        by the caller) — callers detach the op writer and snapshot after,
        exactly like add_many's import contract."""
        values = np.asarray(values, dtype=np.uint64)
        if not len(values):
            return 0
        values = sort_dedupe(values)
        self.version += 1
        self._table = None
        highs = values >> np.uint64(16)
        bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(values)]))
        # Vectorized key probe, dropping groups with no (live) container
        # — the same shape as add_many's (a sparse anti-entropy repair
        # touches 10^5+ containers; per-group bisect was the long pole).
        uniq = highs[starts]
        key_arr = self._keys_np()
        present, idx = searchsorted_membership(key_arr, uniq)
        removed = 0
        containers = self.containers
        pres = np.flatnonzero(present)
        pres_conts = [containers[int(i)] for i in idx[pres]]
        n_p = len(pres_conts)
        for c in pres_conts:
            if c.runs is not None:
                c._run_to_legacy()
        live = np.fromiter((c.n > 0 for c in pres_conts), bool, n_p)
        is_bm = np.fromiter((c.bitmap is not None for c in pres_conts),
                            bool, n_p)
        bm_gis = pres[live & is_bm].tolist()
        arr_gis = pres[live & ~is_bm].tolist()
        for gi in bm_gis:
            c = containers[int(idx[gi])]
            chunk = (values[starts[gi]:ends[gi]]
                     & np.uint64(0xFFFF)).astype(np.uint32)
            before = c.n
            # AND-NOT scatter; duplicate words in chunk compose fine
            # because each element clears only its own bit.
            self._guard_inplace(c)
            np.bitwise_and.at(
                c.bitmap, chunk >> np.uint32(6),
                ~(np.uint64(1) << (chunk.astype(np.uint64)
                                   & np.uint64(63))))
            c.n = int(np.bitwise_count(c.bitmap).sum())
            c._maybe_convert()
            removed += before - c.n
        if len(arr_gis) > 256:
            removed += self._remove_array_groups_global(
                [containers[int(idx[g])] for g in arr_gis],
                uniq[arr_gis], values, starts, ends, arr_gis)
        else:
            for gi in arr_gis:
                c = containers[int(idx[gi])]
                chunk = (values[starts[gi]:ends[gi]]
                         & np.uint64(0xFFFF)).astype(np.uint32)
                keep = ~np.isin(c.array, chunk, assume_unique=False)
                if keep.all():
                    continue
                c._unmap()
                before = c.n
                c.array = c.array[keep]
                c.n = len(c.array)
                removed += before - c.n
        return removed

    def _remove_array_groups_global(self, sel_conts, key_sel, values,
                                    starts, ends, arr_gis) -> int:
        """Global-pass removal from array containers: gather all target
        containers' values into one u64 vector, drop members of the
        incoming batch with ONE searchsorted membership test, and
        re-slice the survivors back per container (spans recovered by
        key-boundary searchsorted, so fully-emptied containers come out
        naturally empty). The remove-side twin of
        _merge_array_groups_global."""
        lens = np.fromiter((c.n for c in sel_conts), np.int64,
                           len(sel_conts))
        old_low = np.concatenate([c.array for c in sel_conts if c.n])
        old_vals = ((np.repeat(key_sel, lens) << np.uint64(16))
                    | old_low.astype(np.uint64))
        g_arr = np.zeros(len(ends), dtype=bool)
        g_arr[arr_gis] = True
        new_vals = values[np.repeat(g_arr,
                                    (ends - starts).astype(np.int64))]
        hit, _ = searchsorted_membership(new_vals, old_vals)
        merged = old_vals[~hit]
        ml = (merged & np.uint64(0xFFFF)).astype(np.uint32)
        # Survivor spans derived from the gather layout itself (count
        # of hits per original container span), NOT from key
        # arithmetic: (key+1)<<16 would wrap u64 for the max container
        # key 2^48-1 (review finding). Every selected container has
        # n>0 (live_gis filter), so reduceat's index vector is strictly
        # increasing.
        gstarts = np.concatenate(([0], np.cumsum(lens)[:-1]))
        surv = lens - np.add.reduceat(hit.astype(np.int64), gstarts)
        e2 = np.cumsum(surv)
        s2 = e2 - surv
        for c, s, e in zip(sel_conts, s2.tolist(), e2.tolist()):
            c.array, c.bitmap, c.n, c.mapped = ml[s:e], None, e - s, False
        return len(old_vals) - len(merged)

    @staticmethod
    def from_sorted(values: np.ndarray) -> "Bitmap":
        b = Bitmap()
        b.add_many(values)
        return b

    # -- batched mutation engine (native write path) --------------------------

    def _keys_np(self) -> np.ndarray:
        """Sorted keys as u64 for vectorized container lookup. Cached
        by key-list length: keys are only ever inserted (empty
        containers persist), so any structural change grows the list
        and invalidates the cache."""
        kc = getattr(self, "_keys_np_cache", None)
        if kc is not None and kc[0] == len(self.keys):
            return kc[1]
        arr = np.array(self.keys, dtype=np.uint64)
        self._keys_np_cache = (len(self.keys), arr)
        return arr

    def _insert_containers(self, new_keys: list[int]) -> None:
        """Insert fresh empty containers for the given (sorted, absent)
        keys. Few keys take bisect inserts; a storm (cold fragment's
        first batches) merges wholesale — one vectorized key merge that
        also refreshes the _keys_np cache in place (rebuilding it from
        the Python list each batch was most of the cold-write cost)."""
        if self._table is not None and self._table_new:
            # Point-created containers awaiting their deferred table
            # splice: land them first — the positions computed below
            # are relative to the CURRENT key array, which already
            # contains them.
            self._flush_table_dirty()
        new_arr = np.array(new_keys, dtype=np.uint64)
        old_arr = self._keys_np()
        pos = np.searchsorted(old_arr, new_arr)
        merged = np.insert(old_arr, pos, new_arr)
        if len(new_keys) <= 64:
            # Positions are original-list-relative; each earlier insert
            # shifts later ones by one.
            for j, (k, p) in enumerate(zip(new_keys, pos.tolist())):
                self.keys.insert(p + j, k)
                self.containers.insert(p + j, Container())
        else:
            # Mask-based two-list merge: one boolean scatter places every
            # new slot, then two zip loops of plain stores — the
            # extend-per-insertion walk this replaces cost ~2.5 us per
            # new key (the add_many long pole when a sparse import
            # creates 10^5..10^6 containers at once).
            total = len(old_arr) + len(new_keys)
            is_new = np.zeros(total, dtype=bool)
            is_new[pos + np.arange(len(new_keys))] = True
            out: list[Container] = [None] * total
            for p, c in zip(np.flatnonzero(~is_new).tolist(),
                            self.containers):
                out[p] = c
            for p in np.flatnonzero(is_new).tolist():
                out[p] = Container()
            self.keys = merged.tolist()
            self.containers = out
        self._keys_np_cache = (len(self.keys), merged)
        if self._table is not None:
            self._table = self._table.insert(pos.astype(np.int64),
                                             len(new_keys))

    def apply_batch(self, values: np.ndarray, set: bool = True,
                    wal: bool = True) -> np.ndarray:
        """Apply a whole batch of adds (or removes) in ONE native
        crossing: container merges, changed-value detection, and WAL
        record construction all happen in bitops.cpp, then the op-log
        gets a single group-commit append covering exactly the changed
        values (idempotent re-sets never hit the WAL, same as the
        per-op path, roaring.go:1560-1626).

        Returns the sorted changed positions. ``wal=False`` (bulk
        import / merge-apply contract, fragment.go:924-989) skips
        record construction entirely; callers snapshot afterwards.
        """
        values = sort_dedupe(np.asarray(values, dtype=np.uint64))
        if not len(values):
            return _EMPTY_U64
        self.version += 1

        highs = values >> np.uint64(16)
        bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
        starts = np.concatenate(([0], bounds, [len(values)]))
        group_keys = highs[starts[:-1]]
        chunk_vals = (values & np.uint64(0xFFFF)).astype(np.uint32)

        keys_np = self._keys_np()
        idx = np.searchsorted(keys_np, group_keys)
        present = ((idx < len(keys_np))
                   & (keys_np[np.minimum(idx, len(keys_np) - 1)]
                      == group_keys)) if len(keys_np) else \
            np.zeros(len(group_keys), dtype=bool)
        if set:
            if not present.all():
                self._insert_containers(
                    group_keys[~present].tolist())
                keys_np = self._keys_np()
                idx = np.searchsorted(keys_np, group_keys)
        else:
            if not present.all():
                # Removes against absent containers are no-ops; drop
                # those groups (and their chunk spans).
                keep_g = present
                if not keep_g.any():
                    return _EMPTY_U64
                keep_vals = np.repeat(keep_g,
                                      np.diff(starts).astype(np.int64))
                values = values[keep_vals]
                chunk_vals = chunk_vals[keep_vals]
                highs = values >> np.uint64(16)
                bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
                starts = np.concatenate(([0], bounds, [len(values)]))
                group_keys = highs[starts[:-1]]
                idx = np.searchsorted(keys_np, group_keys)

        changed = self._apply_groups(group_keys, idx.tolist(),
                                     chunk_vals, starts, set, wal)
        if wal and len(changed):
            self.op_n += len(changed)
        return changed

    def _apply_groups(self, group_keys, idx_list, chunk_vals, starts,
                      set: bool, wal: bool) -> np.ndarray:
        from . import native
        n_g = len(group_keys)
        chunk_ns = np.diff(starts).astype(np.int64)
        containers = self.containers
        conts: list[Container] = [containers[i] for i in idx_list]
        if not native.available():
            # The fallback neither uses nor maintains the table; prep
            # work (rebuild, pointer gather) would be pure waste here.
            return self._apply_groups_python(conts, group_keys,
                                             chunk_vals, starts, set,
                                             wal)
        # Point mutations since the last table read are parked in the
        # dirty set; their entries MUST be patched before the gather
        # below trusts table pointers/counts (stale entries feed the
        # native engine wrong buffers).
        self._flush_table_dirty()
        if self._table is None and n_g * 4 >= len(containers):
            # Rebuilding once makes this and every later batch's prep
            # fully vectorized; below the ratio a point-op-heavy mix
            # would thrash O(all containers) rebuilds, so fall through
            # to the per-group prep instead.
            self._rebuild_table()
        table = self._table
        if table is not None:
            # Vectorized prep: the serialization table already tracks
            # (type, ptr, n) per container — gather instead of walking
            # groups in Python. Only mapped/frozen bitmap containers
            # need per-group attention (in-place mutation safety).
            gi = np.asarray(idx_list, dtype=np.int64)
            types = table.types[gi]
            ptrs = table.ptrs[gi].copy()
            ns = table.ns[gi].copy()
            epoch = self._cow_epoch
            for g in np.flatnonzero(types == 1).tolist():
                c = conts[g]
                if c.mapped or c.cow != epoch:
                    self._guard_inplace(c)
                    p = c.bitmap.__array_interface__["data"][0]
                    ptrs[g] = p
                    table.ptrs[gi[g]] = p
                    table.bufs[gi[g]] = c.bitmap
        else:
            types = np.empty(n_g, dtype=np.uint8)
            ptrs = np.empty(n_g, dtype=np.uint64)
            ns = np.empty(n_g, dtype=np.int64)
            for g in range(n_g):
                c = conts[g]
                if c.bitmap is not None:
                    # native mutates bitmap words in place: copy out of
                    # an mmap or a frozen capture first
                    self._guard_inplace(c)
                    types[g] = 1
                    ptrs[g] = c.bitmap.__array_interface__["data"][0]
                    ns[g] = c.n
                elif c.runs is not None:
                    # Run groups ship as type 2: the engine decodes the
                    # wire-form interval buffer and merges through the
                    # array path (the "transparent upgrade" contract —
                    # output is array or bitmap, never runs).
                    types[g] = 2
                    ptrs[g] = c.runs.__array_interface__["data"][0]
                    ns[g] = c.n
                else:
                    a = c.array
                    types[g] = 0
                    ptrs[g] = a.__array_interface__["data"][0]
                    ns[g] = len(a)

        if not set:
            # Transparent-upgrade, remove leg: the engine's non-bitmap
            # remove output is array-kind only, so a run group whose
            # cardinality exceeds ARRAY_MAX_SIZE must go in as a bitmap
            # (the in-place branch, whose n<=4096 rule re-unpacks) — an
            # oversized array result would violate the serialization
            # invariant and be mis-sized as a bitmap block on snapshot.
            for g in np.flatnonzero((types == 2)
                                    & (ns > ARRAY_MAX_SIZE)).tolist():
                c = conts[g]
                c._to_bitmap()
                self._guard_inplace(c)
                types[g] = 1
                ptrs[g] = c.bitmap.__array_interface__["data"][0]
                if table is not None:
                    table.types[gi[g]] = 1
                    table.ptrs[gi[g]] = ptrs[g]
                    table.bufs[gi[g]] = c.bitmap

        # Capacity masks: run groups (type 2) consume array-output
        # space like array groups (ns holds their cardinality).
        arr_mask = types != 1
        total_chunk = len(chunk_vals)
        changed = np.empty(total_chunk, dtype=np.uint64)
        wal_buf = (np.empty(total_chunk * OP_SIZE, dtype=np.uint8)
                   if wal else np.empty(0, dtype=np.uint8))
        wal_type = ((OP_ADD if set else OP_REMOVE) if wal else -1)
        out_offsets = np.empty(n_g, dtype=np.int64)
        out_ns = np.empty(n_g, dtype=np.int64)
        out_kind = np.empty(n_g, dtype=np.uint8)
        gk = np.ascontiguousarray(group_keys, dtype=np.uint64)
        cstarts = starts.astype(np.int64)
        if set:
            cap = int((ns[arr_mask] + chunk_ns[arr_mask]).sum())
            out_vals = np.empty(max(cap, 1), dtype=np.uint32)
            n_conv = int((arr_mask
                          & (ns + chunk_ns > ARRAY_MAX_SIZE)).sum())
            out_bitmaps = np.empty((max(n_conv, 1), BITMAP_N),
                                   dtype=np.uint64)
            out_bm_idx = np.empty(n_g, dtype=np.int64)
            n_changed = native.batch_add(
                gk, types, ptrs, ns, chunk_vals, cstarts, out_vals,
                out_offsets, out_ns, out_kind, out_bitmaps, out_bm_idx,
                changed, wal_buf, wal_type)
        else:
            cap = int(ns[arr_mask].sum()) + \
                int((~arr_mask).sum()) * ARRAY_MAX_SIZE  # bitmap unpack room
            out_vals = np.empty(max(cap, 1), dtype=np.uint32)
            out_bitmaps = out_bm_idx = None
            n_changed = native.batch_remove(
                gk, types, ptrs, ns, chunk_vals, cstarts, out_vals,
                out_offsets, out_ns, out_kind, changed, wal_buf,
                wal_type)

        offs = out_offsets.tolist()
        kinds = out_kind.tolist()
        new_ns = out_ns.tolist()
        bm_idx = out_bm_idx.tolist() if out_bm_idx is not None else None
        table = self._table
        epoch = self._cow_epoch
        for g, c in enumerate(conts):
            kind = kinds[g]
            if kind == 0:
                off = offs[g]
                # Copy out of the shared batch buffer: a view would pin
                # the WHOLE out_vals allocation for as long as any one
                # container from this batch survives (review r5) —
                # per-slice memcpy of <=16 KB is noise next to that.
                c.array = out_vals[off:off + new_ns[g]].copy()
                c.bitmap = None
                c.runs = None
                c.mapped = False
            elif kind == 1:
                c.bitmap = out_bitmaps[bm_idx[g]].copy()
                c.array = None
                c.runs = None
                c.mapped = False
                c.cow = epoch
            c.n = new_ns[g]
            if table is not None:
                buf = c.bitmap if c.bitmap is not None else c.array
                table.bufs[idx_list[g]] = buf
                # Pointer taken from the attached buffer itself (the
                # copies above own fresh allocations; an offset into
                # the dead batch buffer would dangle once it's GC'd).
                ptrs[g] = buf.__array_interface__["data"][0]
        if table is not None:
            gi = np.asarray(idx_list, dtype=np.int64)
            table.ns[gi] = out_ns
            table.types[gi] = (out_kind != 0).astype(np.uint8)
            table.ptrs[gi] = ptrs
        if wal and n_changed and self.op_writer is not None:
            _wal_write(self.op_writer,
                       wal_buf[:n_changed * OP_SIZE].tobytes())
        return changed[:n_changed]

    def _apply_groups_python(self, conts, group_keys, chunk_vals,
                             starts, set: bool, wal: bool) -> np.ndarray:
        """Numpy fallback for apply_batch when the native library is
        unavailable — identical semantics, per-group vectorized ops."""
        self._table = None
        changed_parts: list[np.ndarray] = []
        starts_l = starts.tolist()
        for c in conts:
            if c.runs is not None:
                c._run_to_legacy()
        for g, c in enumerate(conts):
            chunk = chunk_vals[starts_l[g]:starts_l[g + 1]]
            base = np.uint64(int(group_keys[g]) << 16)
            if set:
                if c.bitmap is not None:
                    hit = ((c.bitmap[chunk >> np.uint32(6)]
                            >> (chunk.astype(np.uint64) & np.uint64(63)))
                           & np.uint64(1)).astype(bool)
                    new = chunk[~hit]
                    if len(new):
                        self._guard_inplace(c)
                        np.bitwise_or.at(
                            c.bitmap, new >> np.uint32(6),
                            np.uint64(1) << (new.astype(np.uint64)
                                             & np.uint64(63)))
                        c.n += len(new)
                else:
                    new = chunk[~np.isin(chunk, c.array,
                                         assume_unique=True)]
                    if len(new):
                        merged = np.empty(c.n + len(new),
                                          dtype=np.uint32)
                        merged[:c.n] = c.array
                        merged[c.n:] = new
                        merged.sort()
                        c.array = merged
                        c.n = len(merged)
                        c.mapped = False
                        c._maybe_convert()
                if len(new):
                    changed_parts.append(base + new.astype(np.uint64))
            else:
                if c.bitmap is not None:
                    hit = ((c.bitmap[chunk >> np.uint32(6)]
                            >> (chunk.astype(np.uint64) & np.uint64(63)))
                           & np.uint64(1)).astype(bool)
                    gone = chunk[hit]
                    if len(gone):
                        self._guard_inplace(c)
                        np.bitwise_and.at(
                            c.bitmap, gone >> np.uint32(6),
                            ~(np.uint64(1) << (gone.astype(np.uint64)
                                               & np.uint64(63))))
                        c.n -= len(gone)
                        c._maybe_convert()
                else:
                    hit = np.isin(c.array, chunk, assume_unique=True)
                    gone = c.array[hit]
                    if len(gone):
                        c._unmap()
                        c.array = c.array[~hit]
                        c.n = len(c.array)
                if len(gone):
                    changed_parts.append(base + gone.astype(np.uint64))
        if not changed_parts:
            return _EMPTY_U64
        changed = np.concatenate(changed_parts)
        if wal and self.op_writer is not None:
            _wal_write(self.op_writer,
                       _wal_blob(changed, OP_ADD if set else OP_REMOVE))
        return changed

    def values(self) -> np.ndarray:
        """All set positions as a sorted u64 vector."""
        parts = list(self.value_chunks())
        if not parts:
            return _EMPTY_U64
        return np.concatenate(parts)

    def all_positions(self) -> np.ndarray:
        """Every set position as one sorted u64 vector, built with
        minimal per-container Python (one three-list append pass vs
        value_chunks' ~4 us generator step — the difference is the
        whole first-query cost on ultra-sparse fragments: BASELINE c5
        has ~434 K near-empty containers, and the per-container walk
        alone once cost the first src-TopN ~1.8 s). One concatenate +
        one repeat; peak memory is 8 B per set bit, so callers with
        100 M-bit fragments should prefer value_chunks (see
        fragment._host_src_count_map's size gate)."""
        # ONE pass appending to three plain lists: the previous
        # tuple-listcomp + two fromiter(genexpr) re-walks cost ~1 us
        # per container, which WAS the cold src-TopN query at 434 K
        # near-empty containers per c5 fragment sweep.
        keys_l: list = []
        vals_l: list = []
        ns_l: list = []
        for k, c in zip(self.keys, self.containers):
            if c.n:
                keys_l.append(k)
                vals_l.append(c.array if c.bitmap is None
                              and c.runs is None
                              else c.values())
                ns_l.append(c.n)
        if not keys_l:
            return _EMPTY_U64
        vals = np.concatenate(vals_l, dtype=np.uint64)
        bases = np.repeat(
            np.array(keys_l, dtype=np.uint64) << np.uint64(16),
            np.array(ns_l, dtype=np.int64))
        return bases + vals

    def positions_for_key_ranges(self, key_lo: np.ndarray,
                                 key_hi: np.ndarray) -> np.ndarray:
        """Set positions from every container whose key falls in any
        [key_lo[i], key_hi[i]) range, as one sorted u64 vector —
        all_positions restricted to key spans (fragment.fold_rows
        gathers the target rows' spans through this instead of
        duplicating the container-decoding walk)."""
        key_arr = self._keys_np()
        lo = np.searchsorted(key_arr, key_lo)
        hi = np.searchsorted(key_arr, key_hi)
        conts = self.containers
        skeys = self.keys
        keys_l: list = []
        vals_l: list = []
        ns_l: list = []
        for s, e in zip(lo.tolist(), hi.tolist()):
            for i in range(s, e):
                c = conts[i]
                if c.n:
                    keys_l.append(skeys[i])
                    vals_l.append(c.array if c.bitmap is None
                                  and c.runs is None
                                  else c.values())
                    ns_l.append(c.n)
        if not keys_l:
            return _EMPTY_U64
        return (np.repeat(np.array(keys_l, dtype=np.uint64)
                          << np.uint64(16),
                          np.array(ns_l, dtype=np.int64))
                | np.concatenate(vals_l, dtype=np.uint64))

    def value_chunks(self):
        """Sorted set positions as one u64 array per container — the
        streaming form of values() for exports that must not
        materialize a whole 100M+-bit fragment (reference streams
        exports bit-by-bit, handler.go:985-1025)."""
        for key, c in zip(list(self.keys), list(self.containers)):
            if c.n:
                yield np.uint64(key << 16) + c.values().astype(np.uint64)

    # -- counts / ranges

    def count(self) -> int:
        return sum(c.n for c in self.containers)

    def max(self) -> int:
        """Largest set position, or 0 if empty (reference roaring.go Max)."""
        for key, c in zip(reversed(self.keys), reversed(self.containers)):
            if c.n:
                if c.runs is not None:
                    r = c.runs
                    return ((key << 16) + int(r[-2]) + int(r[-1]))
                if c.is_array():
                    return (key << 16) + int(c.array[-1])
                w = int(np.flatnonzero(c.bitmap)[-1])
                return (key << 16) + w * 64 + int(c.bitmap[w]).bit_length() - 1
        return 0

    def rank(self, pos: int) -> int:
        """Number of set positions <= pos (reference Rank semantics)."""
        return self.count_range(0, pos + 1)

    def count_range(self, start: int, end: int) -> int:
        """Set bits in [start, end)."""
        if start >= end:
            return 0
        total = 0
        hi0, hi1 = highbits(start), highbits(end - 1)
        i = self._index(hi0)
        while i < len(self.keys) and self.keys[i] <= hi1:
            key, c = self.keys[i], self.containers[i]
            lo = lowbits(start) if key == hi0 else 0
            hi = lowbits(end - 1) + 1 if key == hi1 else 1 << 16
            total += c.count_range(lo, hi)
            i += 1
        return total

    def slice_range(self, start: int, end: int) -> np.ndarray:
        """Sorted u64 vector of set positions in [start, end)."""
        if start >= end:
            return _EMPTY_U64
        parts = []
        hi0, hi1 = highbits(start), highbits(end - 1)
        i = self._index(hi0)
        while i < len(self.keys) and self.keys[i] <= hi1:
            key, c = self.keys[i], self.containers[i]
            vals = c.values().astype(np.uint64) + np.uint64(key << 16)
            if key == hi0 or key == hi1:
                vals = vals[(vals >= start) & (vals < end)]
            if len(vals):
                parts.append(vals)
            i += 1
        if not parts:
            return _EMPTY_U64
        return np.concatenate(parts)

    def offset_range(self, offset: int, start: int, end: int) -> "Bitmap":
        """New bitmap of bits in [start,end) rebased to ``offset``
        (reference: roaring.go:253-285 — the Fragment.row() primitive).

        offset/start/end must be container-aligned (multiples of 2^16).
        Containers are shared (not copied) and marked mapped for
        copy-on-write, so this is O(containers in range).
        """
        for x, nm in ((offset, "offset"), (start, "start"), (end, "end")):
            if x & 0xFFFF:
                raise ValueError(f"{nm} must be multiple of 2^16")
        off_hi, hi0, hi1 = highbits(offset), highbits(start), highbits(end)
        out = Bitmap()
        i = self._index(hi0)
        while i < len(self.keys) and self.keys[i] < hi1:
            c = self.containers[i]
            if c.n:
                out.keys.append(off_hi + (self.keys[i] - hi0))
                c.mapped = True  # force copy-on-write in both holders
                out.containers.append(_shared_view(c))
            i += 1
        return out

    # -- set algebra

    def _binary_op(self, other: "Bitmap",
                   containers_fn: Callable, union_keys: bool) -> "Bitmap":
        out = Bitmap()
        i = j = 0
        ak, bk = self.keys, other.keys
        while i < len(ak) or j < len(bk):
            if j >= len(bk) or (i < len(ak) and ak[i] < bk[j]):
                if union_keys:
                    r = containers_fn(self.containers[i], None)
                    if r is not None and r.n:
                        out.keys.append(ak[i])
                        out.containers.append(r)
                i += 1
            elif i >= len(ak) or (j < len(bk) and bk[j] < ak[i]):
                if union_keys:
                    r = containers_fn(None, other.containers[j])
                    if r is not None and r.n:
                        out.keys.append(bk[j])
                        out.containers.append(r)
                j += 1
            else:
                r = containers_fn(self.containers[i], other.containers[j])
                if r is not None and r.n:
                    out.keys.append(ak[i])
                    out.containers.append(r)
                i += 1
                j += 1
        return out

    def intersect(self, other: "Bitmap") -> "Bitmap":
        return self._binary_op(other, lambda a, b: _intersect(a, b),
                               union_keys=False)

    def _table_for_read(self) -> Optional["_SerTable"]:
        """The serialization table, built on demand, for native
        whole-bitmap reads. The one-time O(containers) rebuild costs
        about as much as ONE Python container walk and then amortizes
        across every later read of this object (row-cache bitmaps are
        long-lived; the TopN src path re-reads the same source per
        slice)."""
        if not native.available():
            return None
        self._flush_table_dirty()
        if self._table is None:
            self._rebuild_table()
        return self._table

    def intersection_count(self, other: "Bitmap") -> int:
        # Whole-bitmap native crossing: the zip walk below pays ~3-6 us
        # of Python per container PAIR (the reference's inner loop is
        # nanoseconds, roaring.go:1192-1268); one call over both
        # container tables removes it entirely.
        if len(self.keys) and len(other.keys) and native.available():
            ta = self._table_for_read()
            tb = other._table_for_read()
            if (ta is not None and tb is not None
                    and not ta.has_runs and not tb.has_runs):
                # The native crossing only dispatches array/bitmap
                # pairs; run operands keep the per-container walk,
                # whose run kernels are already vectorized.
                return native.bitmap_intersection_count(
                    self._keys_np(), ta.types, ta.ptrs, ta.ns,
                    other._keys_np(), tb.types, tb.ptrs, tb.ns)
        total = 0
        i = j = 0
        while i < len(self.keys) and j < len(other.keys):
            if self.keys[i] < other.keys[j]:
                i += 1
            elif self.keys[i] > other.keys[j]:
                j += 1
            else:
                total += _intersection_count(self.containers[i],
                                             other.containers[j])
                i += 1
                j += 1
        return total

    def union(self, other: "Bitmap") -> "Bitmap":
        def f(a, b):
            if a is None:
                return _shared_copy(b)
            if b is None:
                return _shared_copy(a)
            return _union(a, b)
        return self._binary_op(other, f, union_keys=True)

    def difference(self, other: "Bitmap") -> "Bitmap":
        def f(a, b):
            if a is None:
                return None
            if b is None:
                return _shared_copy(a)
            return _difference(a, b)
        return self._binary_op(other, f, union_keys=True)

    def xor(self, other: "Bitmap") -> "Bitmap":
        def f(a, b):
            if a is None:
                return _shared_copy(b)
            if b is None:
                return _shared_copy(a)
            return _xor(a, b)
        return self._binary_op(other, f, union_keys=True)

    # -- iteration

    def __iter__(self) -> TIterator[int]:
        for key, c in zip(self.keys, self.containers):
            base = key << 16
            for v in c.values():
                yield base + int(v)

    def iterator_from(self, seek: int) -> TIterator[int]:
        """Iterate values >= seek."""
        hi = highbits(seek)
        i = self._index(hi)
        for k in range(i, len(self.keys)):
            key, c = self.keys[k], self.containers[k]
            base = key << 16
            vals = c.values()
            if key == hi:
                vals = vals[vals >= lowbits(seek)]
            for v in vals:
                yield base + int(v)

    def shared(self) -> "Bitmap":
        """A bitmap sharing this one's containers copy-on-write (both
        sides are marked; whichever mutates first copies). O(containers)
        — the executor's result-cache handout."""
        out = Bitmap()
        out.keys = list(self.keys)
        out.containers = [_shared_copy(c) for c in self.containers]
        return out

    def unmap(self) -> None:
        """Copy all mapped container data out of the backing buffer.

        Only required before an operation that INVALIDATES the mapping
        — ftruncate of the backing file (the fragment torn-tail trim)
        or an explicit mmap.close() (numpy views pin the buffer;
        close() raises BufferError otherwise). Ordinary close/snapshot/
        restore paths just drop references instead: live views keep the
        mapping alive, and a copy-out would pay a whole-fragment heap
        copy for nothing (fragment._close_storage).
        """
        self._table = None  # copies move every mapped buffer
        for c in self.containers:
            c._unmap()

    # -- representation optimization (run containers)

    def optimize(self, keys: Optional[np.ndarray] = None) -> dict[str, int]:
        """Cardinality-adaptive representation pass (the whole-bitmap
        runOptimize of the Roaring papers): each container picks the
        smallest of array/bitmap/run. Called after mutation batches
        (fragment import contract); point-op and bulk write paths
        transparently upgrade runs back to legacy kinds, so this is the
        single place run containers are (re)introduced. When ``keys``
        (sorted container keys) is given only those containers are
        visited — the bulk-import path passes the touched keys so a
        small import into a huge fragment stays O(touched), not O(all
        containers). Returns visited-container counts by kind."""
        self.version += 1
        counts = {"array": 0, "bitmap": 0, "run": 0}
        changed = False
        if keys is None:
            visit = self.containers
        else:
            ka = self._keys_np()
            keys = np.asarray(keys, dtype=np.uint64)
            idx = np.searchsorted(ka, keys)
            ok = idx < len(ka)
            sel = idx[ok][ka[idx[ok]] == keys[ok]]
            visit = [self.containers[int(i)] for i in sel.tolist()]
        # Vectorized prefilter for array containers (the bulk-import
        # common case): run counts for EVERY visited array in one
        # concatenated diff + prefix-sum pass, then only the winners
        # pay the per-container conversion. Per-container np.diff on
        # import-sized arrays spent ~10x the work in numpy fixed
        # overhead (measured: the optimize pass was 40% of a 1M-bit
        # import).
        arr_cs: list = []
        others: list = []
        for c in visit:
            if not c.n:
                continue
            if c.runs is None and c.bitmap is None:
                arr_cs.append(c)
            else:
                others.append(c)
        if arr_cs:
            lens = np.fromiter((len(c.array) for c in arr_cs),
                               np.int64, len(arr_cs))
            bounds = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=bounds[1:])
            cat = np.concatenate([c.array for c in arr_cs]).astype(
                np.int64, copy=False)
            adj = (np.diff(cat) == 1).astype(np.int64)
            cum = np.zeros(len(cat), np.int64)
            np.cumsum(adj, out=cum[1:])
            # Adjacent pairs WITHIN container i are adj[s : e-1] —
            # cross-container boundary diffs never enter the slice.
            adj_i = cum[bounds[1:] - 1] - cum[bounds[:-1]]
            # Same size model as Container.optimize — run form
            # (2 + 4R bytes) vs the array form (4n; arrays are <=4096
            # by invariant, so the bitmap form never prices in here) —
            # but with a conversion margin: random data lands enough
            # accidental adjacency that the run form wins by a handful
            # of bytes per container (312 scattered values carry ~1.5
            # adjacent pairs), and paying the ~35us interval build per
            # container for a <2% byte win turned this pass into 40%
            # of a 1M-bit import. Genuinely run-shaped data (timestamp
            # views, sequential ids) clears 8/7 by orders of
            # magnitude; point-op optimize() and _settle keep the
            # exact smallest-size rule.
            win = (2 + 4 * (lens - adj_i)) * 8 < 4 * lens * 7
            for c, w in zip(arr_cs, win.tolist()):
                if w:
                    after = c.optimize()
                    counts[after] += 1
                    changed = changed or after != "array"
                else:
                    counts["array"] += 1
        for c in others:
            before = c.kind()
            after = c.optimize()
            counts[after] += 1
            changed = changed or after != before
        if changed:
            # Types/pointers moved wholesale; the serialization table
            # rebuilds on next read.
            self._table = None
            self._table_dirty.clear()
        return counts

    def container_stats(self) -> dict[str, dict[str, int]]:
        """Live-container counts, resident bytes, and run-interval
        totals by kind — the data source for the
        pilosa_roaring_containers_live / _container_bytes gauges and
        the CLI inspect summary."""
        counts = {"array": 0, "bitmap": 0, "run": 0}
        bytes_ = {"array": 0, "bitmap": 0, "run": 0}
        intervals = 0
        for c in self.containers:
            if not c.n:
                continue
            if c.runs is not None:
                counts["run"] += 1
                bytes_["run"] += int(c.runs.size) * 2
                intervals += (len(c.runs) - 1) >> 1
            elif c.bitmap is None:
                counts["array"] += 1
                bytes_["array"] += len(c.array) * 4
            else:
                counts["bitmap"] += 1
                bytes_["bitmap"] += BITMAP_N * 8
        return {"counts": counts, "bytes": bytes_,
                "intervals": {"run": intervals}}

    # -- integrity

    def check(self) -> None:
        if len(self.keys) != len(self.containers):
            raise ValueError("bitmap: keys/containers length mismatch")
        for k in range(1, len(self.keys)):
            if self.keys[k] <= self.keys[k - 1]:
                raise ValueError("bitmap: keys out of order")
        for c in self.containers:
            c.check()

    # -- serialization (reference-compatible; roaring.go:475-614)

    def write_to(self, w, footer: bool = False) -> int:
        # Normalize representation so the n<=4096⇒array load rule holds even
        # for bitmaps produced by set algebra (run containers are
        # exempt — the runs flag bitset identifies them on disk).
        self._table = None  # normalization may swap representations
        for c in self.containers:
            c._maybe_convert()
        live = []
        for k, c in zip(self.keys, self.containers):
            if c.n <= 0:
                continue
            if c.runs is not None:
                live.append((k, 2, c.runs, c.n))
            elif c.bitmap is not None:
                live.append((k, 1, c.bitmap, c.n))
            else:
                live.append((k, 0, c.array, c.n))
        return _write_snapshot(live, w, footer=footer)

    def _flush_table_dirty(self) -> None:
        """Patch point-mutated containers' entries into the
        serialization table — MUST run before any table read (freeze,
        the batch gather prep). Containers point ops CREATED since the
        last read are first spliced in with ONE vectorized
        table.insert, then patched like any dirty entry. A dirty set
        rivaling the table size falls back to wholesale invalidation
        (rebuild costs the same)."""
        t = self._table
        new = self._table_new
        if new:
            if t is not None:
                new_keys = np.fromiter(new, np.uint64, len(new))
                new_keys.sort()
                ka = self._keys_np()
                idx_now = np.searchsorted(ka, new_keys)
                # Position in the PRE-insert table: each earlier new
                # key shifted this one right by one.
                pos_old = idx_now - np.arange(len(new_keys))
                t = self._table = t.insert(pos_old.astype(np.int64),
                                           len(new_keys))
                self._table_dirty.update(new)
            new.clear()
        dirty = self._table_dirty
        if not dirty:
            return
        if t is None:
            dirty.clear()
            return
        if len(dirty) * 2 >= len(self.keys):
            # Patching costs ~1 us/key (bisect + 4 field stores) vs
            # ~1.2 us/container for a wholesale rebuild — only punt to
            # the rebuild when most of the table is dirty anyway.
            self._table = None
            dirty.clear()
            return
        keys = self.keys
        conts = self.containers
        for key in dirty:
            i = bisect.bisect_left(keys, key)
            if i >= len(keys) or keys[i] != key:
                continue
            c = conts[i]
            if c.runs is not None:
                b = c.runs
                t.types[i] = 2
                t.has_runs = True
            else:
                b = c.bitmap if c.bitmap is not None else c.array
                t.types[i] = 0 if c.bitmap is None else 1
            t.bufs[i] = b
            t.ns[i] = c.n
            t.ptrs[i] = b.__array_interface__["data"][0]
        dirty.clear()

    def _rebuild_table(self) -> "_SerTable":
        """Full rebuild of the serialization table (one pass; after this
        the batched write path keeps it current incrementally and
        freeze() is O(1))."""
        self._table_dirty.clear()
        n = len(self.containers)
        ns = np.empty(n, dtype=np.int64)
        types = np.empty(n, dtype=np.uint8)
        ptrs = np.empty(n, dtype=np.uint64)
        bufs: list = [None] * n
        for i, c in enumerate(self.containers):
            if c.runs is not None:
                b = c.runs
                bufs[i] = b
                ns[i] = c.n
                types[i] = 2
                ptrs[i] = b.__array_interface__["data"][0]
                continue
            if c.n and (c.bitmap is not None) != (c.n > ARRAY_MAX_SIZE):
                c._maybe_convert()
            b = c.bitmap if c.bitmap is not None else c.array
            bufs[i] = b
            ns[i] = c.n
            types[i] = 0 if c.bitmap is None else 1
            ptrs[i] = b.__array_interface__["data"][0]
        self._table = _SerTable(ns, types, ptrs, bufs)
        return self._table

    def freeze(self) -> "_Frozen":
        """Consistent point-in-time capture for ASYNC serialization,
        O(1)+O(point-dirtied entries) when the serialization table is
        current (the batched write path maintains it in place; point
        mutations park their container key in _table_dirty and
        _flush_table_dirty patches just those entries here — only
        structural changes from point ops, i.e. new containers,
        invalidate wholesale). Instead of marking every container
        mapped, freezing bumps the COW epoch: any later in-place
        bitmap-word mutation copies its buffer first (Container.cow),
        and array buffers are replaced, never mutated — so the captured
        pointers stay valid with no per-container work. write_frozen
        serializes the capture with no lock held
        (fragment.snapshot's background path)."""
        self._flush_table_dirty()
        t = self._table
        if t is None:
            t = self._rebuild_table()
        self._cow_epoch += 1
        return _Frozen(self._keys_np().copy(), t.ns.copy(),
                       t.types.copy(), t.ptrs.copy(), list(t.bufs))


    def marshal(self) -> bytes:
        buf = io.BytesIO()
        self.write_to(buf)
        return buf.getvalue()

    @staticmethod
    def unmarshal(data, mapped: bool = False,
                  tolerate_torn_tail: bool = False,
                  verify_body: bool = False) -> "Bitmap":
        """Decode a snapshot (+trailing op-log) from a bytes-like buffer.

        With ``mapped=True`` container data are zero-copy views into ``data``
        (e.g. an mmap); they are copy-on-write on first mutation.

        With ``tolerate_torn_tail=True``, a trailing partial op record
        (< 13 bytes — the signature of a crash mid-append) stops parsing
        instead of raising; the number of dangling bytes is reported in
        ``.torn_bytes`` so the caller can truncate the file. A bad checksum
        on a *complete* record is still corruption and still raises.

        A footered snapshot (storage.integrity) always has its footer
        self-crc + header-region crc verified; ``verify_body=True``
        additionally checks the whole-body digest (one crc pass over
        the file — the cold-open verification; per-block checks run
        lazily on first read and on the scrub cadence).
        """
        buf = memoryview(data)
        hdr_arr, run_mask, ns, offs, sizes, ops_offset, body_end = \
            parse_snapshot_layout(buf)
        key_n = len(hdr_arr)
        is_arr_mask = ns <= ARRAY_MAX_SIZE
        b = Bitmap()
        b.keys = hdr_arr["key"].tolist()
        containers = b.containers
        run_list = (run_mask.tolist() if run_mask is not None
                    else [False] * key_n)
        for off, n, is_arr, is_run in zip(offs.tolist(), ns.tolist(),
                                          is_arr_mask.tolist(),
                                          run_list):
            c = Container.__new__(Container)
            c.runs = None
            if is_run:
                n_runs = int.from_bytes(buf[off:off + 2], "little")
                runs = np.frombuffer(buf, dtype="<u2",
                                     count=1 + 2 * n_runs, offset=off)
                c.runs = runs if mapped else runs.copy()
                c.array = None
                c.bitmap = None
            elif is_arr:
                arr = np.frombuffer(buf, dtype="<u4", count=n,
                                    offset=off)
                c.array = arr if mapped else arr.copy()
                c.bitmap = None
            else:
                words = np.frombuffer(buf, dtype="<u8", count=BITMAP_N,
                                      offset=off)
                c.array = None
                c.bitmap = words if mapped else words.copy()
            c.n = n
            c.mapped = mapped
            c.cow = 0
            containers.append(c)
        # Integrity footer (storage.integrity), if one sits between the
        # container blocks and the op-log. Vintage files parse None and
        # replay straight from the body end; a footer truncated at EOF
        # is a torn tail (trimmed like a torn op record); a complete
        # footer failing its own or the header-region crc is
        # CORRUPTION and raises — the fragment open path quarantines.
        ops_start = body_end
        try:
            info = _integrity.parse_and_verify_footer(
                buf, key_n, ops_offset, offs, sizes, body_end,
                check_body=verify_body)
        except _integrity.TornFooterError as e:
            if not tolerate_torn_tail:
                raise
            b.torn_bytes = e.torn_bytes
            return b
        if info is not None:
            b.footer = info
            ops_start = body_end + info.size
        # Trailing op-log (bytes after the body / footer).
        _replay_ops(b, buf[ops_start:], tolerate_torn_tail)
        return b


def parse_snapshot_layout(buf) -> tuple:
    """Vectorized parse of a snapshot's header region WITHOUT building
    containers: ``(hdr_arr, run_mask, ns, offs, sizes, ops_offset,
    body_end)``. The ONE layout parser shared by the decoder
    (Bitmap.unmarshal) and the integrity scrubber
    (storage.scrub.scrub_buffer), so a format change cannot
    desynchronize corruption DETECTION from decoding. Raises
    ValueError on any structural violation. ``buf`` must be a
    memoryview.

    The per-container int.from_bytes loop this vectorization replaced
    cost ~100 ms on a 15 K-container fragment — the bulk of every
    open() and of the synchronous remap reopen (the write path's
    worst per-op outlier)."""
    if len(buf) < HEADER_SIZE:
        raise ValueError("data too small")
    cookie = int.from_bytes(buf[0:4], "little")
    if cookie not in (COOKIE, COOKIE_RUNS):
        raise ValueError("invalid roaring file")
    key_n = int.from_bytes(buf[4:8], "little")
    hdr_off = HEADER_SIZE
    run_mask = None
    if cookie == COOKIE_RUNS:
        flag_len = _run_flags_len(key_n)
        if HEADER_SIZE + flag_len > len(buf):
            raise ValueError(
                f"run flags out of bounds: keyN={key_n},"
                f" len={len(buf)}")
        run_mask = np.unpackbits(
            np.frombuffer(buf, np.uint8, count=flag_len,
                          offset=HEADER_SIZE),
            bitorder="little")[:key_n].astype(bool)
        hdr_off += flag_len
    if hdr_off + key_n * 16 > len(buf):
        raise ValueError(
            f"header out of bounds: keyN={key_n}, len={len(buf)}")
    hdr_arr = np.frombuffer(buf, dtype=_HDR_DTYPE, count=key_n,
                            offset=hdr_off)
    ns = (hdr_arr["n"].astype(np.int64) + 1)
    offs = np.frombuffer(buf, dtype="<u4", count=key_n,
                         offset=hdr_off + key_n * 12
                         ).astype(np.int64)
    sizes = _container_sizes(ns)
    if run_mask is not None and run_mask.any():
        # Run block sizes come from each block's own numRuns
        # prefix (2 + 4R bytes); validate the prefix read first.
        sizes = sizes.copy()
        for i in np.flatnonzero(run_mask).tolist():
            off = int(offs[i])
            if off + 2 > len(buf):
                raise ValueError(
                    f"run block out of bounds: off={off},"
                    f" len={len(buf)}")
            sizes[i] = 2 + 4 * int.from_bytes(buf[off:off + 2],
                                              "little")
    if key_n and int((offs + sizes).max()) > len(buf):
        bad = int(offs[np.argmax(offs + sizes)])
        raise ValueError(
            f"offset out of bounds: off={bad}, len={len(buf)}")
    ops_offset = hdr_off + key_n * 16
    body_end = max(ops_offset,
                   int(offs[-1] + sizes[-1]) if key_n
                   else HEADER_SIZE)
    return hdr_arr, run_mask, ns, offs, sizes, ops_offset, body_end


def _shared_view(c: Container) -> Container:
    """A container sharing c's data, mapped (copy-on-write)."""
    out = Container()
    out.array, out.bitmap, out.n, out.mapped = c.array, c.bitmap, c.n, True
    out.runs = c.runs
    return out


def _shared_copy(c: Container) -> Container:
    c.mapped = True
    return _shared_view(c)


class _SerTable:
    """Serialization table aligned with Bitmap.containers: per-container
    (n, type, buffer pointer, buffer ref), maintained incrementally by
    apply_batch so the MAX_OP_N snapshot freeze is O(1) instead of
    O(all containers). Point mutations record their container key in
    Bitmap._table_dirty for bulk patching before any table read; only
    structural changes (new containers from point ops, bulk rewrites)
    invalidate wholesale."""

    __slots__ = ("ns", "types", "ptrs", "bufs", "has_runs")

    def __init__(self, ns, types, ptrs, bufs):
        self.ns = ns          # int64: container cardinality
        self.types = types    # uint8: 0=array, 1=bitmap, 2=run
        self.ptrs = ptrs      # uint64: buffer data pointers
        self.bufs = bufs      # the buffer objects (keep pointers alive)
        # Pessimistic run-presence flag gating native fast paths that
        # only speak array/bitmap; patch sites may only raise it.
        self.has_runs = bool((types == 2).any())

    def insert(self, pos: np.ndarray, empties: int) -> "_SerTable":
        """New table with empty-array entries inserted at ``pos``
        (aligned with Bitmap._insert_containers)."""
        z64 = np.zeros(len(pos), dtype=np.int64)
        ns = np.insert(self.ns, pos, z64)
        types = np.insert(self.types, pos, z64.astype(np.uint8))
        empty_ptr = _EMPTY_U32.__array_interface__["data"][0]
        ptrs = np.insert(self.ptrs, pos,
                         np.full(len(pos), empty_ptr, dtype=np.uint64))
        bufs: list = []
        prev = 0
        old = self.bufs
        for p in pos.tolist():
            bufs.extend(old[prev:p])
            bufs.append(_EMPTY_U32)
            prev = p
        bufs.extend(old[prev:])
        return _SerTable(ns, types, ptrs, bufs)


class _Frozen:
    """Point-in-time snapshot capture (keys + serialization table copy).
    Buffer refs pin the captured arrays; the COW epoch bump taken at
    freeze() time guarantees no in-place mutation of them."""

    __slots__ = ("keys", "ns", "types", "ptrs", "bufs", "has_runs")

    def __init__(self, keys, ns, types, ptrs, bufs):
        self.keys = keys
        self.ns = ns
        self.types = types
        self.ptrs = ptrs
        self.bufs = bufs
        self.has_runs = bool((types == 2).any())

    def as_live_tuples(self) -> list[tuple]:
        """(key, kind, buf, n) rows — the Python-serializer form."""
        out = []
        for k, n, t, b in zip(self.keys.tolist(), self.ns.tolist(),
                              self.types.tolist(), self.bufs):
            if n:
                out.append((k, t, b, n))
        return out


def write_frozen(frozen, w, footer: bool = False) -> int:
    """Serialize a Bitmap.freeze() capture (no locks needed). Real
    files take the native writev path (zero copy, no GIL during the
    write); BytesIO targets, native-less hosts, and captures holding
    run containers (the C writer speaks the legacy cookie only)
    serialize via the Python writer. ``footer=True`` appends the
    storage-integrity footer on BOTH paths (the native body write
    stays C; the footer crcs compute from the frozen buffers, no file
    re-read)."""
    if isinstance(frozen, list):  # legacy tuple-list form
        live = [t if isinstance(t[1], (int, np.integer))
                else (t[0], 0 if t[2] is None else 1,
                      t[1] if t[2] is None else t[2], t[3])
                for t in frozen]
        return _write_snapshot(live, w, footer=footer)
    fileno = getattr(w, "fileno", None)
    if fileno is not None and native.available() and not frozen.has_runs:
        try:
            fd = w.fileno()
        except (OSError, io.UnsupportedOperation):
            fd = None
        if fd is not None:
            w.flush()
            total = native.write_snapshot_fd(fd, frozen.keys, frozen.ns,
                                             frozen.types, frozen.ptrs)
            if total < 0:
                raise OSError("write_snapshot_fd failed")
            if footer:
                # The C writer advanced the shared fd offset past the
                # body; append straight through the fd (the buffered
                # wrapper was flushed above and holds nothing).
                import os as _os
                _os.write(fd, _live_footer(frozen.as_live_tuples()))
            return total
    return _write_snapshot(frozen.as_live_tuples(), w, footer=footer)


def _base_u8_window(base: np.ndarray, ptr: int, nbytes: int) -> np.ndarray:
    """Byte window [ptr, ptr+nbytes) of a contiguous base buffer as a
    u8 view — the coalesced-run form of per-container u8 views in
    _write_snapshot."""
    b8 = base.view(np.uint8) if base.dtype != np.uint8 else base
    off = ptr - b8.__array_interface__["data"][0]
    return b8[off:off + nbytes]


def _run_flags_len(n_cont: int) -> int:
    """Bytes the runs-cookie flag bitset occupies for ``n_cont``
    containers: ceil(n/8), rounded up to a multiple of 8 so every
    container block that follows stays even-aligned."""
    return ((n_cont + 7) >> 3) + (-((n_cont + 7) >> 3) % 8)


_BLOCK_DTYPES = ("<u4", "<u8", "<u2")  # kind 0=array, 1=bitmap, 2=run


def _snapshot_head(live: list[tuple]) -> tuple[bytes, np.ndarray, int]:
    """(header-region bytes, per-block sizes, total body bytes) for a
    snapshot of (key, kind, buf, n) rows — the ONE place the on-disk
    header layout is computed, shared by the Python writer and the
    footer builder for the native writev path (whose C code writes a
    byte-identical header; a format tweak here desynchronizing them is
    caught by the footer verifying against the real file bytes)."""
    n_cont = len(live)
    # Header via numpy, payload via one join + one write: a snapshot
    # used to issue one write() per container (16 K syscalls for a
    # 200 K-bit fragment) and pack headers int-by-int — together
    # most of the snapshot cost on the write path's MAX_OP_N cadence.
    hdr = np.empty(n_cont, dtype=_HDR_DTYPE)
    hdr["key"] = np.fromiter((t[0] for t in live), np.uint64, n_cont)
    ns = np.fromiter((t[3] for t in live), np.uint32, n_cont)
    hdr["n"] = ns - 1
    kinds = np.fromiter((t[1] for t in live), np.uint8, n_cont)
    has_runs = bool((kinds == 2).any())
    if has_runs:
        sizes = np.where(
            kinds == 2,
            np.fromiter((t[2].size * 2 if t[1] == 2 else 0
                         for t in live), np.int64, n_cont),
            _container_sizes(ns))
        flags = np.zeros(_run_flags_len(n_cont), dtype=np.uint8)
        flags[:((n_cont + 7) >> 3)] = np.packbits(kinds == 2,
                                                  bitorder="little")
        flag_bytes = flags.tobytes()
        cookie = COOKIE_RUNS
    else:
        sizes = _container_sizes(ns)
        flag_bytes = b""
        cookie = COOKIE
    data_start = HEADER_SIZE + len(flag_bytes) + n_cont * 12 + n_cont * 4
    offsets = data_start + np.concatenate(
        ([0], np.cumsum(sizes[:-1], dtype=np.int64))) \
        if n_cont else np.empty(0, np.int64)
    # Header + one np.concatenate of per-container byte VIEWS, two
    # buffer-protocol writes: the per-container slice-assign loop
    # this replaces cost ~2x more at 13 K+ containers (concatenate
    # iterates the list in C). LE byte views are free on LE hosts;
    # the rare BE/non-contiguous container falls back to a cast.
    head = (cookie.to_bytes(4, "little")
            + n_cont.to_bytes(4, "little")
            + flag_bytes
            + hdr.tobytes() + offsets.astype("<u4").tobytes())
    total = data_start + int(sizes.sum()) if n_cont \
        else HEADER_SIZE + len(flag_bytes)
    return head, sizes, total


def _live_footer(live: list[tuple]) -> bytes:
    """The integrity footer for a snapshot body of ``live`` rows,
    computed from the in-memory buffers (no file re-read) — the
    native writev path's footer builder."""
    head, _sizes, total = _snapshot_head(live)
    crcs: list[int] = []
    body_crc = zlib.crc32(head)
    for _, kind, buf, _n in live:
        arr = buf
        dt = _BLOCK_DTYPES[kind]
        if arr.dtype.str != dt or not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr, dtype=dt)
        crcs.append(zlib.crc32(arr) & 0xFFFFFFFF)
        body_crc = zlib.crc32(arr, body_crc)
    return _integrity.build_footer(head, crcs, body_crc, total)


def _write_snapshot(live: list[tuple], w, footer: bool = False) -> int:
    """Serialize (key, kind, buf, n) rows. With no run containers the
    output is byte-identical to the legacy 12346 format; any run
    container switches the snapshot to the 12347 runs cookie, which
    inserts the run-flag bitset between keyN and the headers.

    ``footer=True`` (the fragment FILE snapshot paths) appends the
    storage-integrity footer — per-container-block crc32 table +
    whole-body digest (storage.integrity) — after the body. Wire
    serialization (marshal, /fragment/data) stays footer-free, so
    golden vectors and the exchange format are byte-unchanged."""
    n_cont = len(live)
    head, sizes, total = _snapshot_head(live)
    w.write(head)
    block_crcs: list[int] = []
    body_crc = zlib.crc32(head) if footer else 0
    if n_cont:
        # Coalesce runs of payloads that are adjacent views of one
        # shared base buffer (the bulk-import global merge leaves every
        # rebuilt array container a consecutive slice of one lows
        # vector): one memoryview per RUN instead of a u8 view + list
        # append per container, checked by raw pointer continuity so
        # any later per-container mutation (fresh buffer ⇒ new base)
        # simply breaks the run.
        parts = []
        run_base = None
        run_start = 0
        run_len = 0
        for _, kind, buf, _n in live:
            arr = buf
            dt = _BLOCK_DTYPES[kind]
            if arr.dtype.str != dt or not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr, dtype=dt)
            if footer:
                block_crcs.append(zlib.crc32(arr) & 0xFFFFFFFF)
            ptr = arr.__array_interface__["data"][0]
            nbytes = arr.nbytes
            b = arr.base
            base = (b if isinstance(b, np.ndarray)
                    and b.flags.c_contiguous else arr)
            if base is run_base and ptr == run_start + run_len:
                run_len += nbytes
                continue
            if run_base is not None:
                parts.append(_base_u8_window(run_base, run_start,
                                             run_len))
            run_base, run_start, run_len = base, ptr, nbytes
        if run_base is not None:
            parts.append(_base_u8_window(run_base, run_start, run_len))
        if footer:
            for p in parts:
                body_crc = zlib.crc32(p, body_crc)
        w.write(memoryview(np.concatenate(parts))
                if len(parts) > 1 else parts[0])
    if footer:
        w.write(_integrity.build_footer(head, block_crcs,
                                        body_crc, total))
    return total
