"""Roaring bitmap engine — host-side storage layer, numpy-vectorized.

File-format compatible with the reference implementation
(/root/reference/roaring/roaring.go) so fragment data files interchange:

    snapshot  := cookie(u32 LE = 12346) keyN(u32 LE)
                 { key(u64 LE) n-1(u32 LE) } * keyN          # container headers
                 { offset(u32 LE) } * keyN                    # container offsets
                 container blocks                             # see below
    container := array  : n * u32 LE   (low-16-bit values widened to u32)
               | bitmap : 1024 * u64 LE
    op-log    := { typ(u8: 0=add 1=remove) value(u64 LE) fnv1a32(u32 LE) } *
                 appended after the snapshot body, replayed on load.

(Reference format sections: roaring.go:475-614 for snapshot,
roaring.go:1560-1626 for the op-log.)

Design departure from the reference: containers are numpy arrays, not
pointer-chased structs — an array container is a sorted ``np.uint32`` vector
(values < 2^16), a bitmap container is an ``np.uint64[1024]`` word vector.
All set algebra is vectorized (numpy or the optional C++ kernel lib in
``pilosa_tpu.native``); the same dense-word orientation is what packs straight
onto the TPU (see pilosa_tpu.ops.packed).
"""

from __future__ import annotations

import bisect
import io
import struct
from typing import Callable, Iterator as TIterator, Optional

import numpy as np

from . import native
from ..fault import failpoints as _fp
from ..obs import accounting as _accounting
from ..utils.arrays import searchsorted_membership, sort_dedupe


def _wal_write(writer, blob: bytes) -> None:
    """Every op-log append funnels through here so the ``wal.append``
    failpoint can inject errors and TORN writes (a prefix of the
    record hits the file, then the write "crashes") exactly where a
    real crash would tear the log. Disarmed cost: one module-attr
    read."""
    if _fp.ACTIVE is not None:
        _fp.ACTIVE.hit("wal.append", writer=writer, data=blob)
    writer.write(blob)

# --- constants (match reference wire format) ---------------------------------

COOKIE = 12346               # roaring.go:30
HEADER_SIZE = 8              # roaring.go:33
BITMAP_N = 1024              # u64 words per bitmap container (roaring.go:36)
ARRAY_MAX_SIZE = 4096        # roaring.go:833
OP_SIZE = 13                 # 1 + 8 + 4 (roaring.go:1626)

OP_ADD = 0
OP_REMOVE = 1

_EMPTY_U32 = np.empty(0, dtype=np.uint32)
_EMPTY_U64 = np.empty(0, dtype=np.uint64)

# FNV-1a 32-bit (op-log checksums).
_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)

# Snapshot container-header record (key u64 LE + n-1 u32 LE, packed) —
# one definition shared by the reader (unmarshal) and the writer
# (_write_snapshot) so a format tweak cannot desynchronize them.
_HDR_DTYPE = np.dtype([("key", "<u8"), ("n", "<u4")])


def _container_sizes(ns: np.ndarray) -> np.ndarray:
    """On-disk payload bytes per container from its value count
    (n<=4096 ⇒ u32 array block, else 1024 u64 words)."""
    return np.where(ns <= ARRAY_MAX_SIZE, ns * 4, BITMAP_N * 8)


def fnv1a32(data: bytes) -> int:
    h = int(_FNV_OFFSET)
    for b in data:
        h = ((h ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFF
    return h


def highbits(v: int) -> int:
    return v >> 16


def lowbits(v: int) -> int:
    return v & 0xFFFF


# --- container ---------------------------------------------------------------


class Container:
    """One 2^16-value container: sorted u32 array or 1024-word u64 bitmap.

    ``mapped`` marks data backed by an external (mmap'd) buffer; any mutation
    first copies (copy-on-write), mirroring the reference's ``mapped`` flag
    (roaring.go:536-614) and BitmapSegment.writable (bitmap.go:384-392).
    """

    __slots__ = ("array", "bitmap", "n", "mapped", "cow")

    def __init__(self):
        self.array: Optional[np.ndarray] = _EMPTY_U32  # sorted u32, or None
        self.bitmap: Optional[np.ndarray] = None       # u64[1024], or None
        self.n: int = 0
        self.mapped: bool = False
        # Copy-on-write token for frozen-snapshot captures: when this
        # lags the owning Bitmap's _cow_epoch, an in-place bitmap-word
        # mutation must copy the buffer first (a background snapshot
        # serializes the captured buffer by pointer). Array buffers are
        # replaced, never mutated in place, so they need no token check.
        self.cow: int = 0

    # -- representation management

    def is_array(self) -> bool:
        return self.bitmap is None

    def _unmap(self) -> None:
        if self.mapped:
            if self.array is not None:
                self.array = self.array.copy()
            if self.bitmap is not None:
                self.bitmap = self.bitmap.copy()
            self.mapped = False

    def _to_bitmap(self) -> None:
        """array → bitmap conversion (roaring.go:951-976)."""
        if self.bitmap is not None:
            return
        self.bitmap = self.as_words()
        self.array = None
        self.mapped = False

    def _to_array(self) -> None:
        """bitmap → array conversion (roaring.go:1023-1048)."""
        if self.bitmap is None:
            return
        self.array = bitmap_words_to_values(self.bitmap)
        self.bitmap = None
        self.mapped = False

    def _maybe_convert(self) -> None:
        # Invariant (required by the file format, where n<=4096 ⇒ array
        # block): array containers hold at most ARRAY_MAX_SIZE values, bitmap
        # containers strictly more. Matches reference arrayAdd/bitmapRemove
        # boundaries (roaring.go:951-953,1023-1025).
        if self.bitmap is None:
            if self.n > ARRAY_MAX_SIZE:
                self._to_bitmap()
        else:
            if self.n <= ARRAY_MAX_SIZE:
                self._to_array()

    # -- point ops

    def add(self, v: int) -> bool:
        # The single-op write hot path: manual copy-insert instead of
        # np.insert (which is a Python-level helper costing ~15 us per
        # call), and plain Python ints on the bitmap branch (numpy
        # scalar ops pay ~2 us each). Building a fresh array also
        # detaches from a mapped buffer, so no _unmap() copy on the
        # array branch.
        if self.bitmap is None:
            # Manual numpy copy-insert: the ctypes pointer prep for the
            # native kernel costs ~4 us/call (arr.ctypes construction +
            # cast) — more than the whole insert at container sizes, so
            # the C path only pays for bulk ops, not point adds.
            a = self.array
            i = int(np.searchsorted(a, v))
            if i < len(a) and a[i] == v:
                return False
            grown = np.empty(len(a) + 1, dtype=np.uint32)
            grown[:i] = a[:i]
            grown[i] = v
            grown[i + 1:] = a[i:]
            self.array = grown
            self.mapped = False
            self.n += 1
            self._maybe_convert()
            return True
        w = v >> 6
        word = int(self.bitmap[w])
        bit = 1 << (v & 63)
        if word & bit:
            return False
        self._unmap()
        self.bitmap[w] = word | bit
        self.n += 1
        return True

    def remove(self, v: int) -> bool:
        if self.bitmap is None:
            a = self.array
            i = int(np.searchsorted(a, v))
            if i >= len(a) or a[i] != v:
                return False
            self._unmap()
            self.array = np.delete(self.array, i)
            self.n -= 1
            return True
        w, b = v >> 6, np.uint64(1) << np.uint64(v & 63)
        if not (self.bitmap[w] & b):
            return False
        self._unmap()
        self.bitmap[w] &= ~b
        self.n -= 1
        self._maybe_convert()
        return True

    def contains(self, v: int) -> bool:
        if self.bitmap is None:
            a = self.array
            i = int(np.searchsorted(a, v))
            return i < len(a) and a[i] == v
        return bool((self.bitmap[v >> 6] >> np.uint64(v & 63)) & np.uint64(1))

    # -- bulk access

    def values(self) -> np.ndarray:
        """All set low-16-bit values, sorted, as u32."""
        if self.bitmap is None:
            return self.array
        return bitmap_words_to_values(self.bitmap)

    def as_words(self) -> np.ndarray:
        """Dense u64[1024] word view (built on demand for array containers)."""
        if self.bitmap is not None:
            return self.bitmap
        a = self.array
        if a is None or not len(a):
            return np.zeros(BITMAP_N, dtype=np.uint64)
        # One pass through a byte mask + packbits beats a u64 or.at
        # scatter ~4x: both are O(range), but packbits runs at memcpy
        # speed while or.at is a per-element C loop.
        bits = np.zeros(1 << 16, dtype=np.uint8)
        bits[a] = 1
        # "<u8": packbits emits value 64w+8i+j as byte 8w+i bit j, which
        # is a little-endian u64 word regardless of host endianness.
        return np.packbits(bits, bitorder="little").view("<u8")

    def count_range(self, start: int, end: int) -> int:
        """Number of set values in [start, end) within this container."""
        start, end = max(start, 0), min(end, 1 << 16)
        if start >= end:
            return 0
        if self.bitmap is None:
            a = self.array
            return int(np.searchsorted(a, end) - np.searchsorted(a, start))
        # Whole-word popcount with masked edge words — O(words), no
        # cardinality-proportional allocation.
        w0, w1 = start >> 6, (end - 1) >> 6
        words = self.bitmap[w0:w1 + 1].copy()
        words[0] &= ~np.uint64(0) << np.uint64(start & 63)
        last_bits = ((end - 1) & 63) + 1
        if last_bits < 64:
            words[-1] &= ~(~np.uint64(0) << np.uint64(last_bits))
        return int(np.bitwise_count(words).sum())

    def size_bytes(self) -> int:
        """Serialized size (roaring.go container size())."""
        return self.n * 4 if self.bitmap is None else BITMAP_N * 8

    def check(self) -> None:
        """Internal consistency (roaring.go:653-674 spirit)."""
        if self.bitmap is None:
            a = self.array
            if a is None:
                raise ValueError("container: nil array")
            if len(a) != self.n:
                raise ValueError(f"container: array len {len(a)} != n {self.n}")
            if len(a) > 1 and not np.all(a[1:] > a[:-1]):
                raise ValueError("container: array not strictly sorted")
            if len(a) and int(a[-1]) > 0xFFFF:
                raise ValueError("container: array value out of range")
        else:
            got = int(np.bitwise_count(self.bitmap).sum())
            if got != self.n:
                raise ValueError(f"container: bitmap count {got} != n {self.n}")

    @staticmethod
    def from_array(a: np.ndarray, mapped: bool = False) -> "Container":
        c = Container()
        c.array = a
        c.n = len(a)
        c.mapped = mapped
        return c

    @staticmethod
    def from_bitmap(words: np.ndarray, n: Optional[int] = None,
                    mapped: bool = False) -> "Container":
        c = Container()
        c.array = None
        c.bitmap = words
        c.n = int(np.bitwise_count(words).sum()) if n is None else n
        c.mapped = mapped
        return c


def bitmap_words_to_values(words: np.ndarray) -> np.ndarray:
    """Expand u64 words → sorted u32 value vector (vectorized)."""
    nz = np.flatnonzero(words)
    if not len(nz):
        return _EMPTY_U32
    # Expand each non-zero word into its set bit positions.
    w = words[nz]
    bits = ((w[:, None] >> np.arange(64, dtype=np.uint64)) &
            np.uint64(1)).astype(bool)
    word_idx, bit_idx = np.nonzero(bits)
    return (nz[word_idx].astype(np.uint32) * np.uint32(64)
            + bit_idx.astype(np.uint32))


# --- container set algebra (vectorized; native C++ when available) -----------

# Per-(op, operand-kind) call counters — the per-container-type
# statistics the Roaring library paper (arXiv:1709.07821) credits for
# making its optimizations tractable. Pre-seeded plain ints bumped
# inline (GIL-coarse increments; a rare lost count is acceptable for
# metrics), published as pilosa_roaring_container_ops_total by the
# runtime collector (obs.runtime).
OP_KINDS = ("array_array", "array_bitmap", "bitmap_bitmap")
_OPS = ("intersect", "intersection_count", "union", "difference", "xor")
_OP_COUNTS: dict[tuple[str, str], int] = {
    (op, kind): 0 for op in _OPS for kind in OP_KINDS}


def _op_kind(a: Container, b: Container) -> str:
    if a.is_array():
        return "array_array" if b.is_array() else "array_bitmap"
    return "array_bitmap" if b.is_array() else "bitmap_bitmap"


def op_counts() -> dict[tuple[str, str], int]:
    """Snapshot of the container set-algebra op counters."""
    return dict(_OP_COUNTS)


# Fixed bitmap-container word count (65536 bits / 64-bit words) — the
# scan cost a bitmap operand contributes to the per-query ledger.
_BITMAP_WORDS = 1024


def _scan_words(c: Container) -> int:
    """Word-equivalents one operand contributes: a bitmap container is
    a full 1024-word scan; an array container counts its elements at
    64 per word (the comparable memory-traffic unit)."""
    if c.is_array():
        return (len(c.array) + 63) >> 6
    return _BITMAP_WORDS


def _bump(op: str, a: Container, b: Container) -> None:
    """One site feeding BOTH accountings: the process-global counters
    (pilosa_roaring_container_ops_total via the runtime collector) and
    the current query's cost ledger (obs.accounting) when one is bound
    to this thread — per-query container-kind attribution is the whole
    point of the ledger (arXiv:1709.07821's per-container-type
    statistics, per query)."""
    kind = _op_kind(a, b)
    _OP_COUNTS[(op, kind)] += 1
    cost = _accounting.current_cost()
    if cost is not None:
        cost.note_container_op(op, kind,
                               _scan_words(a) + _scan_words(b))


def _intersect(a: Container, b: Container) -> Container:
    _bump("intersect", a, b)
    if a.is_array() and b.is_array():
        out = native.intersect_sorted_u32(a.array, b.array)
        return Container.from_array(out)
    if a.is_array() != b.is_array():
        arr, bmp = (a, b) if a.is_array() else (b, a)
        av = arr.array
        hit = (bmp.bitmap[av >> np.uint32(6)] >>
               (av.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
        return Container.from_array(av[hit.astype(bool)])
    words = a.bitmap & b.bitmap
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


def _intersection_count(a: Container, b: Container) -> int:
    _bump("intersection_count", a, b)
    if a.is_array() and b.is_array():
        return native.intersection_count_sorted_u32(a.array, b.array)
    if a.is_array() != b.is_array():
        arr, bmp = (a, b) if a.is_array() else (b, a)
        av = arr.array
        hit = (bmp.bitmap[av >> np.uint32(6)] >>
               (av.astype(np.uint64) & np.uint64(63))) & np.uint64(1)
        return int(hit.sum())
    return native.popcnt_and(a.bitmap, b.bitmap)


def _union(a: Container, b: Container) -> Container:
    _bump("union", a, b)
    if a.is_array() and b.is_array():
        out = np.union1d(a.array, b.array).astype(np.uint32)
        c = Container.from_array(out)
        c._maybe_convert()
        return c
    words = a.as_words() | b.as_words()
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


def _difference(a: Container, b: Container) -> Container:
    _bump("difference", a, b)
    if a.is_array():
        av = a.array
        if b.is_array():
            keep = ~np.isin(av, b.array, assume_unique=True)
        else:
            keep = ~((b.bitmap[av >> np.uint32(6)] >>
                      (av.astype(np.uint64) & np.uint64(63))) &
                     np.uint64(1)).astype(bool)
        return Container.from_array(av[keep])
    words = a.bitmap & ~b.as_words()
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


def _xor(a: Container, b: Container) -> Container:
    _bump("xor", a, b)
    if a.is_array() and b.is_array():
        out = np.setxor1d(a.array, b.array, assume_unique=True).astype(np.uint32)
        c = Container.from_array(out)
        c._maybe_convert()
        return c
    words = a.as_words() ^ b.as_words()
    c = Container.from_bitmap(words)
    c._maybe_convert()
    return c


# --- op-log ------------------------------------------------------------------


_OP_BODY = struct.Struct("<BQ")  # op type + u64 value (13-byte record w/ checksum)


def _wal_blob(values: np.ndarray, typ: int) -> bytes:
    """13-byte op records for a value vector, checksummed, vectorized —
    the group-commit form of Op.marshal (verified byte-identical in
    tests; 0.1 us/record vs ~2 us through the scalar path)."""
    n = len(values)
    rec = np.zeros((n, OP_SIZE), dtype=np.uint8)
    rec[:, 0] = typ
    rec[:, 1:9] = values.astype("<u8").view(np.uint8).reshape(n, 8)
    h = np.full(n, int(_FNV_OFFSET), dtype=np.uint32)
    for i in range(9):
        h = (h ^ rec[:, i].astype(np.uint32)) * _FNV_PRIME
    rec[:, 9:13] = h.astype("<u4").view(np.uint8).reshape(n, 4)
    return rec.tobytes()


class Op:
    """One op-log record (roaring.go:1560-1626)."""

    __slots__ = ("typ", "value")

    def __init__(self, typ: int, value: int):
        self.typ = typ
        self.value = value

    def marshal(self) -> bytes:
        body = _OP_BODY.pack(self.typ, self.value)
        return body + fnv1a32(body).to_bytes(4, "little")

    @staticmethod
    def unmarshal(buf: memoryview) -> "Op":
        if len(buf) < OP_SIZE:
            raise ValueError(f"op data out of bounds: len={len(buf)}")
        body = bytes(buf[:9])
        chk = int.from_bytes(buf[9:13], "little")
        want = fnv1a32(body)
        if chk != want:
            raise ValueError(f"checksum mismatch: exp={want:08x}, got={chk:08x}")
        return Op(body[0], int.from_bytes(body[1:9], "little"))

    def apply(self, b: "Bitmap") -> bool:
        if self.typ == OP_ADD:
            return b._add(self.value)
        if self.typ == OP_REMOVE:
            return b._remove(self.value)
        raise ValueError(f"invalid op type: {self.typ}")


# --- bitmap ------------------------------------------------------------------


class Bitmap:
    """Two-level roaring bitmap: sorted high-48-bit keys → containers.

    ``op_writer`` (a binary file-like) mirrors the reference's OpWriter hook
    (roaring.go:51,616-628): when set, every add/remove appends an op record.
    """

    def __init__(self, *values: int):
        self.keys: list[int] = []
        self.containers: list[Container] = []
        self.op_writer = None
        self.op_n = 0      # ops appended/replayed since last snapshot
        self.torn_bytes = 0  # dangling tail bytes found during unmarshal
        # Monotonic mutation counter: bumped by every mutating entry
        # point so derived-value memos (e.g. the fragment src-key
        # cache) can validate against in-place mutation instead of
        # trusting object identity.
        self.version = 0
        # Frozen-capture COW epoch (see Container.cow) and the
        # incrementally-maintained serialization table (see _SerTable).
        # Point mutations record their container key in _table_dirty
        # instead of invalidating the table — the entries are patched
        # in bulk before any table read (_flush_table_dirty), keeping
        # the MAX_OP_N freeze O(dirty) instead of O(all containers)
        # for per-op write workloads.
        self._cow_epoch = 0
        self._table: Optional[_SerTable] = None
        self._table_dirty: set[int] = set()
        for v in values:
            self._add(v)

    def _guard_inplace(self, c: Container) -> None:
        """Make c's bitmap words safe to mutate in place: copy out of an
        mmap (the mapped flag) or out of a frozen snapshot capture (the
        cow token)."""
        if c.mapped:
            c._unmap()
            c.cow = self._cow_epoch
        elif c.cow != self._cow_epoch:
            if c.bitmap is not None:
                c.bitmap = c.bitmap.copy()
            c.cow = self._cow_epoch

    # -- container lookup

    def _index(self, key: int) -> int:
        """Bisect keys; returns insertion point."""
        return bisect.bisect_left(self.keys, key)

    def container(self, key: int) -> Optional[Container]:
        i = self._index(key)
        if i < len(self.keys) and self.keys[i] == key:
            return self.containers[i]
        return None

    def _container_or_create(self, key: int) -> Container:
        i = self._index(key)
        if i < len(self.keys) and self.keys[i] == key:
            return self.containers[i]
        c = Container()
        self.keys.insert(i, key)
        self.containers.insert(i, c)
        return c

    # -- point ops (public ops write to the op-log; _ops do not)

    def add(self, v: int) -> bool:
        changed = self._add(v)
        if changed:
            self._write_op(Op(OP_ADD, v))
        return changed

    def _add(self, v: int) -> bool:
        self.version += 1
        key = highbits(v)
        if self._table is not None:
            n0 = len(self.keys)
            c = self._container_or_create(key)
            if len(self.keys) != n0:
                self._table = None  # new container: indices shifted
            else:
                self._table_dirty.add(key)
        else:
            c = self._container_or_create(key)
        if c.bitmap is not None:
            self._guard_inplace(c)
        return c.add(lowbits(v))

    def remove(self, v: int) -> bool:
        changed = self._remove(v)
        if changed:
            self._write_op(Op(OP_REMOVE, v))
        return changed

    def _remove(self, v: int) -> bool:
        self.version += 1
        key = highbits(v)
        c = self.container(key)
        if c is None:
            return False
        if self._table is not None:
            self._table_dirty.add(key)
        if c.bitmap is not None:
            self._guard_inplace(c)
        return c.remove(lowbits(v))

    def contains(self, v: int) -> bool:
        c = self.container(highbits(v))
        return c.contains(lowbits(v)) if c is not None else False

    def _write_op(self, op: Op) -> None:
        if self.op_writer is not None:
            _wal_write(self.op_writer, op.marshal())
            self.op_n += 1

    # -- bulk ops

    def add_many(self, values: np.ndarray) -> int:
        """Vectorized bulk add of a u64 value vector. Returns #newly set.

        The import hot path (reference: fragment.go:924-989 detaches the op
        writer and bulk-adds); callers snapshot afterwards.
        """
        values = np.asarray(values, dtype=np.uint64)
        if not len(values):
            return 0
        values = sort_dedupe(values)
        self.version += 1
        self._table = None
        highs = values >> np.uint64(16)
        lows = (values & np.uint64(0xFFFF)).astype(np.uint32)
        bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(values)]))
        # One vectorized key probe for every group (sparse imports touch
        # hundreds of thousands of containers; per-group bisect +
        # list.insert was quadratic in the table size).
        uniq = highs[starts]
        key_arr = self._keys_np()
        exists, idx = searchsorted_membership(key_arr, uniq)
        if not exists.all():
            self._insert_containers(uniq[~exists].tolist())
            idx = np.searchsorted(self._keys_np(), uniq)
        containers = self.containers
        conts = [containers[i] for i in idx.tolist()]
        added = 0
        n_g = len(conts)
        bm_mask = np.fromiter((c.bitmap is not None for c in conts),
                              bool, n_g)
        for gi in np.flatnonzero(bm_mask).tolist():
            # OR-scatter straight into the word vector: O(chunk + words),
            # no representation churn for the dense-import hot path.
            chunk = lows[starts[gi]:ends[gi]]
            c = conts[gi]
            before = c.n
            self._guard_inplace(c)
            np.bitwise_or.at(
                c.bitmap, chunk >> np.uint32(6),
                np.uint64(1) << (chunk.astype(np.uint64) & np.uint64(63)))
            c.n = int(np.bitwise_count(c.bitmap).sum())
            c._maybe_convert()
            added += c.n - before
        arr_gis = np.flatnonzero(~bm_mask)
        if len(arr_gis) > 256:
            added += self._merge_array_groups_global(
                conts, arr_gis, uniq, values, bm_mask,
                (ends - starts).astype(np.int64))
        else:
            for gi in arr_gis.tolist():
                chunk = lows[starts[gi]:ends[gi]]
                c = conts[gi]
                before = c.n
                if c.n == 0:
                    # Copy the chunk out of the batch vector: a view
                    # would pin the WHOLE batch buffer for the
                    # container's lifetime (review finding — a few tiny
                    # surviving containers must not hold a 10 M-value
                    # batch's 80 MB alive). The global-merge path keeps
                    # views because there the base is collectively
                    # covered by its containers.
                    c.array, c.bitmap, c.n = chunk.copy(), None, len(chunk)
                    c.mapped = False
                else:
                    merged = np.union1d(c.array, chunk).astype(np.uint32)
                    c._unmap()
                    c.array, c.n = merged, len(merged)
                c._maybe_convert()
                added += c.n - before
        return added

    def _merge_array_groups_global(self, conts, arr_gis, uniq, values,
                                   bm_mask, group_lens) -> int:
        """Merge a large batch of value groups into their array-form
        containers in ONE vectorized pass: gather every target
        container's current values into a single u64 position vector,
        union it with the incoming values, then re-slice the result
        back into per-container views. Replaces a per-group union1d
        (~8 us/group — the import long pole at 10^5..10^6 touched
        containers, e.g. a 100 K-row sparse frame) with work that is
        O(total values) regardless of group count."""
        sel_conts = [conts[g] for g in arr_gis.tolist()]
        lens = np.fromiter((c.n for c in sel_conts), np.int64,
                           len(sel_conts))
        old_total = int(lens.sum())
        key_sel = uniq[arr_gis]
        if old_total:
            old_low = np.concatenate(
                [c.array for c in sel_conts if c.n])
            old_vals = ((np.repeat(key_sel, lens) << np.uint64(16))
                        | old_low.astype(np.uint64))
        else:
            old_vals = _EMPTY_U64
        new_vals = values[np.repeat(~bm_mask, group_lens)]
        merged = np.union1d(old_vals, new_vals)
        mh = merged >> np.uint64(16)
        ml = (merged & np.uint64(0xFFFF)).astype(np.uint32)
        b2 = np.flatnonzero(mh[1:] != mh[:-1]) + 1
        s2 = np.concatenate(([0], b2))
        e2 = np.concatenate((b2, [len(merged)]))
        # Every selected group contributes >=1 incoming value and every
        # gathered value came from a selected container, so the merged
        # key set equals key_sel exactly and stays aligned by sort order.
        ns2 = (e2 - s2)
        for c, s, e, n in zip(sel_conts, s2.tolist(), e2.tolist(),
                              ns2.tolist()):
            c.array, c.bitmap, c.n, c.mapped = ml[s:e], None, n, False
        for g in np.flatnonzero(ns2 > ARRAY_MAX_SIZE).tolist():
            sel_conts[g]._to_bitmap()
        return len(merged) - old_total

    def remove_many(self, values: np.ndarray) -> int:
        """Vectorized bulk remove of a u64 value vector; returns #cleared.

        The anti-entropy bulk-repair path (reference fragment.go:802-920
        applies merge diffs through the fragment with the op log handled
        by the caller) — callers detach the op writer and snapshot after,
        exactly like add_many's import contract."""
        values = np.asarray(values, dtype=np.uint64)
        if not len(values):
            return 0
        values = sort_dedupe(values)
        self.version += 1
        self._table = None
        highs = values >> np.uint64(16)
        bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(values)]))
        # Vectorized key probe, dropping groups with no (live) container
        # — the same shape as add_many's (a sparse anti-entropy repair
        # touches 10^5+ containers; per-group bisect was the long pole).
        uniq = highs[starts]
        key_arr = self._keys_np()
        present, idx = searchsorted_membership(key_arr, uniq)
        removed = 0
        containers = self.containers
        pres = np.flatnonzero(present)
        pres_conts = [containers[int(i)] for i in idx[pres]]
        n_p = len(pres_conts)
        live = np.fromiter((c.n > 0 for c in pres_conts), bool, n_p)
        is_bm = np.fromiter((c.bitmap is not None for c in pres_conts),
                            bool, n_p)
        bm_gis = pres[live & is_bm].tolist()
        arr_gis = pres[live & ~is_bm].tolist()
        for gi in bm_gis:
            c = containers[int(idx[gi])]
            chunk = (values[starts[gi]:ends[gi]]
                     & np.uint64(0xFFFF)).astype(np.uint32)
            before = c.n
            # AND-NOT scatter; duplicate words in chunk compose fine
            # because each element clears only its own bit.
            self._guard_inplace(c)
            np.bitwise_and.at(
                c.bitmap, chunk >> np.uint32(6),
                ~(np.uint64(1) << (chunk.astype(np.uint64)
                                   & np.uint64(63))))
            c.n = int(np.bitwise_count(c.bitmap).sum())
            c._maybe_convert()
            removed += before - c.n
        if len(arr_gis) > 256:
            removed += self._remove_array_groups_global(
                [containers[int(idx[g])] for g in arr_gis],
                uniq[arr_gis], values, starts, ends, arr_gis)
        else:
            for gi in arr_gis:
                c = containers[int(idx[gi])]
                chunk = (values[starts[gi]:ends[gi]]
                         & np.uint64(0xFFFF)).astype(np.uint32)
                keep = ~np.isin(c.array, chunk, assume_unique=False)
                if keep.all():
                    continue
                c._unmap()
                before = c.n
                c.array = c.array[keep]
                c.n = len(c.array)
                removed += before - c.n
        return removed

    def _remove_array_groups_global(self, sel_conts, key_sel, values,
                                    starts, ends, arr_gis) -> int:
        """Global-pass removal from array containers: gather all target
        containers' values into one u64 vector, drop members of the
        incoming batch with ONE searchsorted membership test, and
        re-slice the survivors back per container (spans recovered by
        key-boundary searchsorted, so fully-emptied containers come out
        naturally empty). The remove-side twin of
        _merge_array_groups_global."""
        lens = np.fromiter((c.n for c in sel_conts), np.int64,
                           len(sel_conts))
        old_low = np.concatenate([c.array for c in sel_conts if c.n])
        old_vals = ((np.repeat(key_sel, lens) << np.uint64(16))
                    | old_low.astype(np.uint64))
        g_arr = np.zeros(len(ends), dtype=bool)
        g_arr[arr_gis] = True
        new_vals = values[np.repeat(g_arr,
                                    (ends - starts).astype(np.int64))]
        hit, _ = searchsorted_membership(new_vals, old_vals)
        merged = old_vals[~hit]
        ml = (merged & np.uint64(0xFFFF)).astype(np.uint32)
        # Survivor spans derived from the gather layout itself (count
        # of hits per original container span), NOT from key
        # arithmetic: (key+1)<<16 would wrap u64 for the max container
        # key 2^48-1 (review finding). Every selected container has
        # n>0 (live_gis filter), so reduceat's index vector is strictly
        # increasing.
        gstarts = np.concatenate(([0], np.cumsum(lens)[:-1]))
        surv = lens - np.add.reduceat(hit.astype(np.int64), gstarts)
        e2 = np.cumsum(surv)
        s2 = e2 - surv
        for c, s, e in zip(sel_conts, s2.tolist(), e2.tolist()):
            c.array, c.bitmap, c.n, c.mapped = ml[s:e], None, e - s, False
        return len(old_vals) - len(merged)

    @staticmethod
    def from_sorted(values: np.ndarray) -> "Bitmap":
        b = Bitmap()
        b.add_many(values)
        return b

    # -- batched mutation engine (native write path) --------------------------

    def _keys_np(self) -> np.ndarray:
        """Sorted keys as u64 for vectorized container lookup. Cached
        by key-list length: keys are only ever inserted (empty
        containers persist), so any structural change grows the list
        and invalidates the cache."""
        kc = getattr(self, "_keys_np_cache", None)
        if kc is not None and kc[0] == len(self.keys):
            return kc[1]
        arr = np.array(self.keys, dtype=np.uint64)
        self._keys_np_cache = (len(self.keys), arr)
        return arr

    def _insert_containers(self, new_keys: list[int]) -> None:
        """Insert fresh empty containers for the given (sorted, absent)
        keys. Few keys take bisect inserts; a storm (cold fragment's
        first batches) merges wholesale — one vectorized key merge that
        also refreshes the _keys_np cache in place (rebuilding it from
        the Python list each batch was most of the cold-write cost)."""
        new_arr = np.array(new_keys, dtype=np.uint64)
        old_arr = self._keys_np()
        pos = np.searchsorted(old_arr, new_arr)
        merged = np.insert(old_arr, pos, new_arr)
        if len(new_keys) <= 64:
            # Positions are original-list-relative; each earlier insert
            # shifts later ones by one.
            for j, (k, p) in enumerate(zip(new_keys, pos.tolist())):
                self.keys.insert(p + j, k)
                self.containers.insert(p + j, Container())
        else:
            # Mask-based two-list merge: one boolean scatter places every
            # new slot, then two zip loops of plain stores — the
            # extend-per-insertion walk this replaces cost ~2.5 us per
            # new key (the add_many long pole when a sparse import
            # creates 10^5..10^6 containers at once).
            total = len(old_arr) + len(new_keys)
            is_new = np.zeros(total, dtype=bool)
            is_new[pos + np.arange(len(new_keys))] = True
            out: list[Container] = [None] * total
            for p, c in zip(np.flatnonzero(~is_new).tolist(),
                            self.containers):
                out[p] = c
            for p in np.flatnonzero(is_new).tolist():
                out[p] = Container()
            self.keys = merged.tolist()
            self.containers = out
        self._keys_np_cache = (len(self.keys), merged)
        if self._table is not None:
            self._table = self._table.insert(pos.astype(np.int64),
                                             len(new_keys))

    def apply_batch(self, values: np.ndarray, set: bool = True,
                    wal: bool = True) -> np.ndarray:
        """Apply a whole batch of adds (or removes) in ONE native
        crossing: container merges, changed-value detection, and WAL
        record construction all happen in bitops.cpp, then the op-log
        gets a single group-commit append covering exactly the changed
        values (idempotent re-sets never hit the WAL, same as the
        per-op path, roaring.go:1560-1626).

        Returns the sorted changed positions. ``wal=False`` (bulk
        import / merge-apply contract, fragment.go:924-989) skips
        record construction entirely; callers snapshot afterwards.
        """
        values = sort_dedupe(np.asarray(values, dtype=np.uint64))
        if not len(values):
            return _EMPTY_U64
        self.version += 1

        highs = values >> np.uint64(16)
        bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
        starts = np.concatenate(([0], bounds, [len(values)]))
        group_keys = highs[starts[:-1]]
        chunk_vals = (values & np.uint64(0xFFFF)).astype(np.uint32)

        keys_np = self._keys_np()
        idx = np.searchsorted(keys_np, group_keys)
        present = ((idx < len(keys_np))
                   & (keys_np[np.minimum(idx, len(keys_np) - 1)]
                      == group_keys)) if len(keys_np) else \
            np.zeros(len(group_keys), dtype=bool)
        if set:
            if not present.all():
                self._insert_containers(
                    group_keys[~present].tolist())
                keys_np = self._keys_np()
                idx = np.searchsorted(keys_np, group_keys)
        else:
            if not present.all():
                # Removes against absent containers are no-ops; drop
                # those groups (and their chunk spans).
                keep_g = present
                if not keep_g.any():
                    return _EMPTY_U64
                keep_vals = np.repeat(keep_g,
                                      np.diff(starts).astype(np.int64))
                values = values[keep_vals]
                chunk_vals = chunk_vals[keep_vals]
                highs = values >> np.uint64(16)
                bounds = np.flatnonzero(highs[1:] != highs[:-1]) + 1
                starts = np.concatenate(([0], bounds, [len(values)]))
                group_keys = highs[starts[:-1]]
                idx = np.searchsorted(keys_np, group_keys)

        changed = self._apply_groups(group_keys, idx.tolist(),
                                     chunk_vals, starts, set, wal)
        if wal and len(changed):
            self.op_n += len(changed)
        return changed

    def _apply_groups(self, group_keys, idx_list, chunk_vals, starts,
                      set: bool, wal: bool) -> np.ndarray:
        from . import native
        n_g = len(group_keys)
        chunk_ns = np.diff(starts).astype(np.int64)
        containers = self.containers
        conts: list[Container] = [containers[i] for i in idx_list]
        if not native.available():
            # The fallback neither uses nor maintains the table; prep
            # work (rebuild, pointer gather) would be pure waste here.
            return self._apply_groups_python(conts, group_keys,
                                             chunk_vals, starts, set,
                                             wal)
        # Point mutations since the last table read are parked in the
        # dirty set; their entries MUST be patched before the gather
        # below trusts table pointers/counts (stale entries feed the
        # native engine wrong buffers).
        self._flush_table_dirty()
        if self._table is None and n_g * 4 >= len(containers):
            # Rebuilding once makes this and every later batch's prep
            # fully vectorized; below the ratio a point-op-heavy mix
            # would thrash O(all containers) rebuilds, so fall through
            # to the per-group prep instead.
            self._rebuild_table()
        table = self._table
        if table is not None:
            # Vectorized prep: the serialization table already tracks
            # (type, ptr, n) per container — gather instead of walking
            # groups in Python. Only mapped/frozen bitmap containers
            # need per-group attention (in-place mutation safety).
            gi = np.asarray(idx_list, dtype=np.int64)
            types = table.types[gi]
            ptrs = table.ptrs[gi].copy()
            ns = table.ns[gi].copy()
            epoch = self._cow_epoch
            for g in np.flatnonzero(types == 1).tolist():
                c = conts[g]
                if c.mapped or c.cow != epoch:
                    self._guard_inplace(c)
                    p = c.bitmap.__array_interface__["data"][0]
                    ptrs[g] = p
                    table.ptrs[gi[g]] = p
                    table.bufs[gi[g]] = c.bitmap
        else:
            types = np.empty(n_g, dtype=np.uint8)
            ptrs = np.empty(n_g, dtype=np.uint64)
            ns = np.empty(n_g, dtype=np.int64)
            for g in range(n_g):
                c = conts[g]
                if c.bitmap is not None:
                    # native mutates bitmap words in place: copy out of
                    # an mmap or a frozen capture first
                    self._guard_inplace(c)
                    types[g] = 1
                    ptrs[g] = c.bitmap.__array_interface__["data"][0]
                    ns[g] = c.n
                else:
                    a = c.array
                    types[g] = 0
                    ptrs[g] = a.__array_interface__["data"][0]
                    ns[g] = len(a)

        arr_mask = types == 0
        total_chunk = len(chunk_vals)
        changed = np.empty(total_chunk, dtype=np.uint64)
        wal_buf = (np.empty(total_chunk * OP_SIZE, dtype=np.uint8)
                   if wal else np.empty(0, dtype=np.uint8))
        wal_type = ((OP_ADD if set else OP_REMOVE) if wal else -1)
        out_offsets = np.empty(n_g, dtype=np.int64)
        out_ns = np.empty(n_g, dtype=np.int64)
        out_kind = np.empty(n_g, dtype=np.uint8)
        gk = np.ascontiguousarray(group_keys, dtype=np.uint64)
        cstarts = starts.astype(np.int64)
        if set:
            cap = int((ns[arr_mask] + chunk_ns[arr_mask]).sum())
            out_vals = np.empty(max(cap, 1), dtype=np.uint32)
            n_conv = int((arr_mask
                          & (ns + chunk_ns > ARRAY_MAX_SIZE)).sum())
            out_bitmaps = np.empty((max(n_conv, 1), BITMAP_N),
                                   dtype=np.uint64)
            out_bm_idx = np.empty(n_g, dtype=np.int64)
            n_changed = native.batch_add(
                gk, types, ptrs, ns, chunk_vals, cstarts, out_vals,
                out_offsets, out_ns, out_kind, out_bitmaps, out_bm_idx,
                changed, wal_buf, wal_type)
        else:
            cap = int(ns[arr_mask].sum()) + \
                int((~arr_mask).sum()) * ARRAY_MAX_SIZE
            out_vals = np.empty(max(cap, 1), dtype=np.uint32)
            out_bitmaps = out_bm_idx = None
            n_changed = native.batch_remove(
                gk, types, ptrs, ns, chunk_vals, cstarts, out_vals,
                out_offsets, out_ns, out_kind, changed, wal_buf,
                wal_type)

        offs = out_offsets.tolist()
        kinds = out_kind.tolist()
        new_ns = out_ns.tolist()
        bm_idx = out_bm_idx.tolist() if out_bm_idx is not None else None
        table = self._table
        epoch = self._cow_epoch
        for g, c in enumerate(conts):
            kind = kinds[g]
            if kind == 0:
                off = offs[g]
                # Copy out of the shared batch buffer: a view would pin
                # the WHOLE out_vals allocation for as long as any one
                # container from this batch survives (review r5) —
                # per-slice memcpy of <=16 KB is noise next to that.
                c.array = out_vals[off:off + new_ns[g]].copy()
                c.bitmap = None
                c.mapped = False
            elif kind == 1:
                c.bitmap = out_bitmaps[bm_idx[g]].copy()
                c.array = None
                c.mapped = False
                c.cow = epoch
            c.n = new_ns[g]
            if table is not None:
                buf = c.bitmap if c.bitmap is not None else c.array
                table.bufs[idx_list[g]] = buf
                # Pointer taken from the attached buffer itself (the
                # copies above own fresh allocations; an offset into
                # the dead batch buffer would dangle once it's GC'd).
                ptrs[g] = buf.__array_interface__["data"][0]
        if table is not None:
            gi = np.asarray(idx_list, dtype=np.int64)
            table.ns[gi] = out_ns
            table.types[gi] = (out_kind != 0).astype(np.uint8)
            table.ptrs[gi] = ptrs
        if wal and n_changed and self.op_writer is not None:
            _wal_write(self.op_writer,
                       wal_buf[:n_changed * OP_SIZE].tobytes())
        return changed[:n_changed]

    def _apply_groups_python(self, conts, group_keys, chunk_vals,
                             starts, set: bool, wal: bool) -> np.ndarray:
        """Numpy fallback for apply_batch when the native library is
        unavailable — identical semantics, per-group vectorized ops."""
        self._table = None
        changed_parts: list[np.ndarray] = []
        starts_l = starts.tolist()
        for g, c in enumerate(conts):
            chunk = chunk_vals[starts_l[g]:starts_l[g + 1]]
            base = np.uint64(int(group_keys[g]) << 16)
            if set:
                if c.bitmap is not None:
                    hit = ((c.bitmap[chunk >> np.uint32(6)]
                            >> (chunk.astype(np.uint64) & np.uint64(63)))
                           & np.uint64(1)).astype(bool)
                    new = chunk[~hit]
                    if len(new):
                        self._guard_inplace(c)
                        np.bitwise_or.at(
                            c.bitmap, new >> np.uint32(6),
                            np.uint64(1) << (new.astype(np.uint64)
                                             & np.uint64(63)))
                        c.n += len(new)
                else:
                    new = chunk[~np.isin(chunk, c.array,
                                         assume_unique=True)]
                    if len(new):
                        merged = np.empty(c.n + len(new),
                                          dtype=np.uint32)
                        merged[:c.n] = c.array
                        merged[c.n:] = new
                        merged.sort()
                        c.array = merged
                        c.n = len(merged)
                        c.mapped = False
                        c._maybe_convert()
                if len(new):
                    changed_parts.append(base + new.astype(np.uint64))
            else:
                if c.bitmap is not None:
                    hit = ((c.bitmap[chunk >> np.uint32(6)]
                            >> (chunk.astype(np.uint64) & np.uint64(63)))
                           & np.uint64(1)).astype(bool)
                    gone = chunk[hit]
                    if len(gone):
                        self._guard_inplace(c)
                        np.bitwise_and.at(
                            c.bitmap, gone >> np.uint32(6),
                            ~(np.uint64(1) << (gone.astype(np.uint64)
                                               & np.uint64(63))))
                        c.n -= len(gone)
                        c._maybe_convert()
                else:
                    hit = np.isin(c.array, chunk, assume_unique=True)
                    gone = c.array[hit]
                    if len(gone):
                        c._unmap()
                        c.array = c.array[~hit]
                        c.n = len(c.array)
                if len(gone):
                    changed_parts.append(base + gone.astype(np.uint64))
        if not changed_parts:
            return _EMPTY_U64
        changed = np.concatenate(changed_parts)
        if wal and self.op_writer is not None:
            _wal_write(self.op_writer,
                       _wal_blob(changed, OP_ADD if set else OP_REMOVE))
        return changed

    def values(self) -> np.ndarray:
        """All set positions as a sorted u64 vector."""
        parts = list(self.value_chunks())
        if not parts:
            return _EMPTY_U64
        return np.concatenate(parts)

    def all_positions(self) -> np.ndarray:
        """Every set position as one sorted u64 vector, built with
        minimal per-container Python (one three-list append pass vs
        value_chunks' ~4 us generator step — the difference is the
        whole first-query cost on ultra-sparse fragments: BASELINE c5
        has ~434 K near-empty containers, and the per-container walk
        alone once cost the first src-TopN ~1.8 s). One concatenate +
        one repeat; peak memory is 8 B per set bit, so callers with
        100 M-bit fragments should prefer value_chunks (see
        fragment._host_src_count_map's size gate)."""
        # ONE pass appending to three plain lists: the previous
        # tuple-listcomp + two fromiter(genexpr) re-walks cost ~1 us
        # per container, which WAS the cold src-TopN query at 434 K
        # near-empty containers per c5 fragment sweep.
        keys_l: list = []
        vals_l: list = []
        ns_l: list = []
        for k, c in zip(self.keys, self.containers):
            if c.n:
                keys_l.append(k)
                vals_l.append(c.array if c.bitmap is None
                              else bitmap_words_to_values(c.bitmap))
                ns_l.append(c.n)
        if not keys_l:
            return _EMPTY_U64
        vals = np.concatenate(vals_l, dtype=np.uint64)
        bases = np.repeat(
            np.array(keys_l, dtype=np.uint64) << np.uint64(16),
            np.array(ns_l, dtype=np.int64))
        return bases + vals

    def positions_for_key_ranges(self, key_lo: np.ndarray,
                                 key_hi: np.ndarray) -> np.ndarray:
        """Set positions from every container whose key falls in any
        [key_lo[i], key_hi[i]) range, as one sorted u64 vector —
        all_positions restricted to key spans (fragment.fold_rows
        gathers the target rows' spans through this instead of
        duplicating the container-decoding walk)."""
        key_arr = self._keys_np()
        lo = np.searchsorted(key_arr, key_lo)
        hi = np.searchsorted(key_arr, key_hi)
        conts = self.containers
        skeys = self.keys
        keys_l: list = []
        vals_l: list = []
        ns_l: list = []
        for s, e in zip(lo.tolist(), hi.tolist()):
            for i in range(s, e):
                c = conts[i]
                if c.n:
                    keys_l.append(skeys[i])
                    vals_l.append(c.array if c.bitmap is None
                                  else bitmap_words_to_values(c.bitmap))
                    ns_l.append(c.n)
        if not keys_l:
            return _EMPTY_U64
        return (np.repeat(np.array(keys_l, dtype=np.uint64)
                          << np.uint64(16),
                          np.array(ns_l, dtype=np.int64))
                | np.concatenate(vals_l, dtype=np.uint64))

    def value_chunks(self):
        """Sorted set positions as one u64 array per container — the
        streaming form of values() for exports that must not
        materialize a whole 100M+-bit fragment (reference streams
        exports bit-by-bit, handler.go:985-1025)."""
        for key, c in zip(list(self.keys), list(self.containers)):
            if c.n:
                yield np.uint64(key << 16) + c.values().astype(np.uint64)

    # -- counts / ranges

    def count(self) -> int:
        return sum(c.n for c in self.containers)

    def max(self) -> int:
        """Largest set position, or 0 if empty (reference roaring.go Max)."""
        for key, c in zip(reversed(self.keys), reversed(self.containers)):
            if c.n:
                if c.is_array():
                    return (key << 16) + int(c.array[-1])
                w = int(np.flatnonzero(c.bitmap)[-1])
                return (key << 16) + w * 64 + int(c.bitmap[w]).bit_length() - 1
        return 0

    def count_range(self, start: int, end: int) -> int:
        """Set bits in [start, end)."""
        if start >= end:
            return 0
        total = 0
        hi0, hi1 = highbits(start), highbits(end - 1)
        i = self._index(hi0)
        while i < len(self.keys) and self.keys[i] <= hi1:
            key, c = self.keys[i], self.containers[i]
            lo = lowbits(start) if key == hi0 else 0
            hi = lowbits(end - 1) + 1 if key == hi1 else 1 << 16
            total += c.count_range(lo, hi)
            i += 1
        return total

    def slice_range(self, start: int, end: int) -> np.ndarray:
        """Sorted u64 vector of set positions in [start, end)."""
        if start >= end:
            return _EMPTY_U64
        parts = []
        hi0, hi1 = highbits(start), highbits(end - 1)
        i = self._index(hi0)
        while i < len(self.keys) and self.keys[i] <= hi1:
            key, c = self.keys[i], self.containers[i]
            vals = c.values().astype(np.uint64) + np.uint64(key << 16)
            if key == hi0 or key == hi1:
                vals = vals[(vals >= start) & (vals < end)]
            if len(vals):
                parts.append(vals)
            i += 1
        if not parts:
            return _EMPTY_U64
        return np.concatenate(parts)

    def offset_range(self, offset: int, start: int, end: int) -> "Bitmap":
        """New bitmap of bits in [start,end) rebased to ``offset``
        (reference: roaring.go:253-285 — the Fragment.row() primitive).

        offset/start/end must be container-aligned (multiples of 2^16).
        Containers are shared (not copied) and marked mapped for
        copy-on-write, so this is O(containers in range).
        """
        for x, nm in ((offset, "offset"), (start, "start"), (end, "end")):
            if x & 0xFFFF:
                raise ValueError(f"{nm} must be multiple of 2^16")
        off_hi, hi0, hi1 = highbits(offset), highbits(start), highbits(end)
        out = Bitmap()
        i = self._index(hi0)
        while i < len(self.keys) and self.keys[i] < hi1:
            c = self.containers[i]
            if c.n:
                out.keys.append(off_hi + (self.keys[i] - hi0))
                c.mapped = True  # force copy-on-write in both holders
                out.containers.append(_shared_view(c))
            i += 1
        return out

    # -- set algebra

    def _binary_op(self, other: "Bitmap",
                   containers_fn: Callable, union_keys: bool) -> "Bitmap":
        out = Bitmap()
        i = j = 0
        ak, bk = self.keys, other.keys
        while i < len(ak) or j < len(bk):
            if j >= len(bk) or (i < len(ak) and ak[i] < bk[j]):
                if union_keys:
                    r = containers_fn(self.containers[i], None)
                    if r is not None and r.n:
                        out.keys.append(ak[i])
                        out.containers.append(r)
                i += 1
            elif i >= len(ak) or (j < len(bk) and bk[j] < ak[i]):
                if union_keys:
                    r = containers_fn(None, other.containers[j])
                    if r is not None and r.n:
                        out.keys.append(bk[j])
                        out.containers.append(r)
                j += 1
            else:
                r = containers_fn(self.containers[i], other.containers[j])
                if r is not None and r.n:
                    out.keys.append(ak[i])
                    out.containers.append(r)
                i += 1
                j += 1
        return out

    def intersect(self, other: "Bitmap") -> "Bitmap":
        return self._binary_op(other, lambda a, b: _intersect(a, b),
                               union_keys=False)

    def _table_for_read(self) -> Optional["_SerTable"]:
        """The serialization table, built on demand, for native
        whole-bitmap reads. The one-time O(containers) rebuild costs
        about as much as ONE Python container walk and then amortizes
        across every later read of this object (row-cache bitmaps are
        long-lived; the TopN src path re-reads the same source per
        slice)."""
        if not native.available():
            return None
        self._flush_table_dirty()
        if self._table is None:
            self._rebuild_table()
        return self._table

    def intersection_count(self, other: "Bitmap") -> int:
        # Whole-bitmap native crossing: the zip walk below pays ~3-6 us
        # of Python per container PAIR (the reference's inner loop is
        # nanoseconds, roaring.go:1192-1268); one call over both
        # container tables removes it entirely.
        if len(self.keys) and len(other.keys) and native.available():
            ta = self._table_for_read()
            tb = other._table_for_read()
            if ta is not None and tb is not None:
                return native.bitmap_intersection_count(
                    self._keys_np(), ta.types, ta.ptrs, ta.ns,
                    other._keys_np(), tb.types, tb.ptrs, tb.ns)
        total = 0
        i = j = 0
        while i < len(self.keys) and j < len(other.keys):
            if self.keys[i] < other.keys[j]:
                i += 1
            elif self.keys[i] > other.keys[j]:
                j += 1
            else:
                total += _intersection_count(self.containers[i],
                                             other.containers[j])
                i += 1
                j += 1
        return total

    def union(self, other: "Bitmap") -> "Bitmap":
        def f(a, b):
            if a is None:
                return _shared_copy(b)
            if b is None:
                return _shared_copy(a)
            return _union(a, b)
        return self._binary_op(other, f, union_keys=True)

    def difference(self, other: "Bitmap") -> "Bitmap":
        def f(a, b):
            if a is None:
                return None
            if b is None:
                return _shared_copy(a)
            return _difference(a, b)
        return self._binary_op(other, f, union_keys=True)

    def xor(self, other: "Bitmap") -> "Bitmap":
        def f(a, b):
            if a is None:
                return _shared_copy(b)
            if b is None:
                return _shared_copy(a)
            return _xor(a, b)
        return self._binary_op(other, f, union_keys=True)

    # -- iteration

    def __iter__(self) -> TIterator[int]:
        for key, c in zip(self.keys, self.containers):
            base = key << 16
            for v in c.values():
                yield base + int(v)

    def iterator_from(self, seek: int) -> TIterator[int]:
        """Iterate values >= seek."""
        hi = highbits(seek)
        i = self._index(hi)
        for k in range(i, len(self.keys)):
            key, c = self.keys[k], self.containers[k]
            base = key << 16
            vals = c.values()
            if key == hi:
                vals = vals[vals >= lowbits(seek)]
            for v in vals:
                yield base + int(v)

    def shared(self) -> "Bitmap":
        """A bitmap sharing this one's containers copy-on-write (both
        sides are marked; whichever mutates first copies). O(containers)
        — the executor's result-cache handout."""
        out = Bitmap()
        out.keys = list(self.keys)
        out.containers = [_shared_copy(c) for c in self.containers]
        return out

    def unmap(self) -> None:
        """Copy all mapped container data out of the backing buffer.

        Only required before an operation that INVALIDATES the mapping
        — ftruncate of the backing file (the fragment torn-tail trim)
        or an explicit mmap.close() (numpy views pin the buffer;
        close() raises BufferError otherwise). Ordinary close/snapshot/
        restore paths just drop references instead: live views keep the
        mapping alive, and a copy-out would pay a whole-fragment heap
        copy for nothing (fragment._close_storage).
        """
        self._table = None  # copies move every mapped buffer
        for c in self.containers:
            c._unmap()

    # -- integrity

    def check(self) -> None:
        if len(self.keys) != len(self.containers):
            raise ValueError("bitmap: keys/containers length mismatch")
        for k in range(1, len(self.keys)):
            if self.keys[k] <= self.keys[k - 1]:
                raise ValueError("bitmap: keys out of order")
        for c in self.containers:
            c.check()

    # -- serialization (reference-compatible; roaring.go:475-614)

    def write_to(self, w) -> int:
        # Normalize representation so the n<=4096⇒array load rule holds even
        # for bitmaps produced by set algebra.
        self._table = None  # normalization may swap representations
        for c in self.containers:
            c._maybe_convert()
        live = [(k, c.array, c.bitmap, c.n)
                for k, c in zip(self.keys, self.containers) if c.n > 0]
        return _write_snapshot(live, w)

    def _flush_table_dirty(self) -> None:
        """Patch point-mutated containers' entries into the
        serialization table — MUST run before any table read (freeze,
        the batch gather prep). A dirty set rivaling the table size
        falls back to wholesale invalidation (rebuild costs the
        same)."""
        t = self._table
        dirty = self._table_dirty
        if not dirty:
            return
        if t is None:
            dirty.clear()
            return
        if len(dirty) * 2 >= len(self.keys):
            # Patching costs ~1 us/key (bisect + 4 field stores) vs
            # ~1.2 us/container for a wholesale rebuild — only punt to
            # the rebuild when most of the table is dirty anyway.
            self._table = None
            dirty.clear()
            return
        keys = self.keys
        conts = self.containers
        for key in dirty:
            i = bisect.bisect_left(keys, key)
            if i >= len(keys) or keys[i] != key:
                continue
            c = conts[i]
            b = c.bitmap if c.bitmap is not None else c.array
            t.bufs[i] = b
            t.ns[i] = c.n
            t.types[i] = 0 if c.bitmap is None else 1
            t.ptrs[i] = b.__array_interface__["data"][0]
        dirty.clear()

    def _rebuild_table(self) -> "_SerTable":
        """Full rebuild of the serialization table (one pass; after this
        the batched write path keeps it current incrementally and
        freeze() is O(1))."""
        self._table_dirty.clear()
        n = len(self.containers)
        ns = np.empty(n, dtype=np.int64)
        types = np.empty(n, dtype=np.uint8)
        ptrs = np.empty(n, dtype=np.uint64)
        bufs: list = [None] * n
        for i, c in enumerate(self.containers):
            if c.n and (c.bitmap is not None) != (c.n > ARRAY_MAX_SIZE):
                c._maybe_convert()
            b = c.bitmap if c.bitmap is not None else c.array
            bufs[i] = b
            ns[i] = c.n
            types[i] = 0 if c.bitmap is None else 1
            ptrs[i] = b.__array_interface__["data"][0]
        self._table = _SerTable(ns, types, ptrs, bufs)
        return self._table

    def freeze(self) -> "_Frozen":
        """Consistent point-in-time capture for ASYNC serialization,
        O(1)+O(point-dirtied entries) when the serialization table is
        current (the batched write path maintains it in place; point
        mutations park their container key in _table_dirty and
        _flush_table_dirty patches just those entries here — only
        structural changes from point ops, i.e. new containers,
        invalidate wholesale). Instead of marking every container
        mapped, freezing bumps the COW epoch: any later in-place
        bitmap-word mutation copies its buffer first (Container.cow),
        and array buffers are replaced, never mutated — so the captured
        pointers stay valid with no per-container work. write_frozen
        serializes the capture with no lock held
        (fragment.snapshot's background path)."""
        self._flush_table_dirty()
        t = self._table
        if t is None:
            t = self._rebuild_table()
        self._cow_epoch += 1
        return _Frozen(self._keys_np().copy(), t.ns.copy(),
                       t.types.copy(), t.ptrs.copy(), list(t.bufs))


    def marshal(self) -> bytes:
        buf = io.BytesIO()
        self.write_to(buf)
        return buf.getvalue()

    @staticmethod
    def unmarshal(data, mapped: bool = False,
                  tolerate_torn_tail: bool = False) -> "Bitmap":
        """Decode a snapshot (+trailing op-log) from a bytes-like buffer.

        With ``mapped=True`` container data are zero-copy views into ``data``
        (e.g. an mmap); they are copy-on-write on first mutation.

        With ``tolerate_torn_tail=True``, a trailing partial op record
        (< 13 bytes — the signature of a crash mid-append) stops parsing
        instead of raising; the number of dangling bytes is reported in
        ``.torn_bytes`` so the caller can truncate the file. A bad checksum
        on a *complete* record is still corruption and still raises.
        """
        buf = memoryview(data)
        if len(buf) < HEADER_SIZE:
            raise ValueError("data too small")
        if int.from_bytes(buf[0:4], "little") != COOKIE:
            raise ValueError("invalid roaring file")
        key_n = int.from_bytes(buf[4:8], "little")
        if HEADER_SIZE + key_n * 16 > len(buf):
            raise ValueError(
                f"header out of bounds: keyN={key_n}, len={len(buf)}")
        b = Bitmap()
        # Vectorized header/offset parse: the per-container
        # int.from_bytes loop cost ~100 ms on a 15 K-container
        # fragment — the bulk of every open() and of the synchronous
        # remap reopen (the write path's worst per-op outlier).
        hdr_arr = np.frombuffer(buf, dtype=_HDR_DTYPE, count=key_n,
                                offset=HEADER_SIZE)
        ns = (hdr_arr["n"].astype(np.int64) + 1)
        offs = np.frombuffer(buf, dtype="<u4", count=key_n,
                             offset=HEADER_SIZE + key_n * 12
                             ).astype(np.int64)
        is_arr_mask = ns <= ARRAY_MAX_SIZE
        sizes = _container_sizes(ns)
        if key_n and int((offs + sizes).max()) > len(buf):
            bad = int(offs[np.argmax(offs + sizes)])
            raise ValueError(
                f"offset out of bounds: off={bad}, len={len(buf)}")
        b.keys = hdr_arr["key"].tolist()
        ops_offset = HEADER_SIZE + key_n * 16
        end = HEADER_SIZE
        containers = b.containers
        for off, n, is_arr in zip(offs.tolist(), ns.tolist(),
                                  is_arr_mask.tolist()):
            c = Container.__new__(Container)
            if is_arr:
                arr = np.frombuffer(buf, dtype="<u4", count=n,
                                    offset=off)
                c.array = arr if mapped else arr.copy()
                c.bitmap = None
            else:
                words = np.frombuffer(buf, dtype="<u8", count=BITMAP_N,
                                      offset=off)
                c.array = None
                c.bitmap = words if mapped else words.copy()
            c.n = n
            c.mapped = mapped
            c.cow = 0
            containers.append(c)
        if key_n:
            end = int(offs[-1] + sizes[-1])
        # Trailing op-log (bytes after the last container block).
        ops_end = max(ops_offset, end)
        rest = buf[ops_end:]
        while len(rest):
            if tolerate_torn_tail and len(rest) < OP_SIZE:
                b.torn_bytes = len(rest)
                break
            op = Op.unmarshal(rest)
            op.apply(b)
            b.op_n += 1
            rest = rest[OP_SIZE:]
        return b


def _shared_view(c: Container) -> Container:
    """A container sharing c's data, mapped (copy-on-write)."""
    out = Container()
    out.array, out.bitmap, out.n, out.mapped = c.array, c.bitmap, c.n, True
    return out


def _shared_copy(c: Container) -> Container:
    c.mapped = True
    return _shared_view(c)


class _SerTable:
    """Serialization table aligned with Bitmap.containers: per-container
    (n, type, buffer pointer, buffer ref), maintained incrementally by
    apply_batch so the MAX_OP_N snapshot freeze is O(1) instead of
    O(all containers). Point mutations record their container key in
    Bitmap._table_dirty for bulk patching before any table read; only
    structural changes (new containers from point ops, bulk rewrites)
    invalidate wholesale."""

    __slots__ = ("ns", "types", "ptrs", "bufs")

    def __init__(self, ns, types, ptrs, bufs):
        self.ns = ns          # int64: container cardinality
        self.types = types    # uint8: 0=array, 1=bitmap
        self.ptrs = ptrs      # uint64: buffer data pointers
        self.bufs = bufs      # the buffer objects (keep pointers alive)

    def insert(self, pos: np.ndarray, empties: int) -> "_SerTable":
        """New table with empty-array entries inserted at ``pos``
        (aligned with Bitmap._insert_containers)."""
        z64 = np.zeros(len(pos), dtype=np.int64)
        ns = np.insert(self.ns, pos, z64)
        types = np.insert(self.types, pos, z64.astype(np.uint8))
        empty_ptr = _EMPTY_U32.__array_interface__["data"][0]
        ptrs = np.insert(self.ptrs, pos,
                         np.full(len(pos), empty_ptr, dtype=np.uint64))
        bufs: list = []
        prev = 0
        old = self.bufs
        for p in pos.tolist():
            bufs.extend(old[prev:p])
            bufs.append(_EMPTY_U32)
            prev = p
        bufs.extend(old[prev:])
        return _SerTable(ns, types, ptrs, bufs)


class _Frozen:
    """Point-in-time snapshot capture (keys + serialization table copy).
    Buffer refs pin the captured arrays; the COW epoch bump taken at
    freeze() time guarantees no in-place mutation of them."""

    __slots__ = ("keys", "ns", "types", "ptrs", "bufs")

    def __init__(self, keys, ns, types, ptrs, bufs):
        self.keys = keys
        self.ns = ns
        self.types = types
        self.ptrs = ptrs
        self.bufs = bufs

    def as_live_tuples(self) -> list[tuple]:
        """(key, array, bitmap, n) rows — the Python-serializer form."""
        out = []
        for k, n, t, b in zip(self.keys.tolist(), self.ns.tolist(),
                              self.types.tolist(), self.bufs):
            if n:
                out.append((k, None if t else b, b if t else None, n))
        return out


def write_frozen(frozen, w) -> int:
    """Serialize a Bitmap.freeze() capture (no locks needed). Real
    files take the native writev path (zero copy, no GIL during the
    write); BytesIO targets and native-less hosts serialize via the
    Python writer."""
    if isinstance(frozen, list):  # legacy tuple-list form
        return _write_snapshot(frozen, w)
    fileno = getattr(w, "fileno", None)
    if fileno is not None and native.available():
        try:
            fd = w.fileno()
        except (OSError, io.UnsupportedOperation):
            fd = None
        if fd is not None:
            w.flush()
            total = native.write_snapshot_fd(fd, frozen.keys, frozen.ns,
                                             frozen.types, frozen.ptrs)
            if total < 0:
                raise OSError("write_snapshot_fd failed")
            return total
    return _write_snapshot(frozen.as_live_tuples(), w)


def _base_u8_window(base: np.ndarray, ptr: int, nbytes: int) -> np.ndarray:
    """Byte window [ptr, ptr+nbytes) of a contiguous base buffer as a
    u8 view — the coalesced-run form of per-container u8 views in
    _write_snapshot."""
    b8 = base.view(np.uint8) if base.dtype != np.uint8 else base
    off = ptr - b8.__array_interface__["data"][0]
    return b8[off:off + nbytes]


def _write_snapshot(live: list[tuple], w) -> int:
    n_cont = len(live)
    # Header via numpy, payload via one join + one write: a snapshot
    # used to issue one write() per container (16 K syscalls for a
    # 200 K-bit fragment) and pack headers int-by-int — together
    # most of the snapshot cost on the write path's MAX_OP_N cadence.
    hdr = np.empty(n_cont, dtype=_HDR_DTYPE)
    hdr["key"] = np.fromiter((t[0] for t in live), np.uint64, n_cont)
    ns = np.fromiter((t[3] for t in live), np.uint32, n_cont)
    hdr["n"] = ns - 1
    sizes = _container_sizes(ns)
    data_start = HEADER_SIZE + n_cont * 12 + n_cont * 4
    offsets = data_start + np.concatenate(
        ([0], np.cumsum(sizes[:-1], dtype=np.int64))) \
        if n_cont else np.empty(0, np.int64)
    # Header + one np.concatenate of per-container byte VIEWS, two
    # buffer-protocol writes: the per-container slice-assign loop
    # this replaces cost ~2x more at 13 K+ containers (concatenate
    # iterates the list in C). LE byte views are free on LE hosts;
    # the rare BE/non-contiguous container falls back to a cast.
    head = (COOKIE.to_bytes(4, "little")
            + n_cont.to_bytes(4, "little")
            + hdr.tobytes() + offsets.astype("<u4").tobytes())
    w.write(head)
    total = data_start + int(sizes.sum()) if n_cont else HEADER_SIZE
    if n_cont:
        # Coalesce runs of payloads that are adjacent views of one
        # shared base buffer (the bulk-import global merge leaves every
        # rebuilt array container a consecutive slice of one lows
        # vector): one memoryview per RUN instead of a u8 view + list
        # append per container, checked by raw pointer continuity so
        # any later per-container mutation (fresh buffer ⇒ new base)
        # simply breaks the run.
        parts = []
        run_base = None
        run_start = 0
        run_len = 0
        for _, array, bitmap, _n in live:
            arr = array if bitmap is None else bitmap
            dt = "<u4" if bitmap is None else "<u8"
            if arr.dtype.str != dt or not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr, dtype=dt)
            ptr = arr.__array_interface__["data"][0]
            nbytes = arr.nbytes
            b = arr.base
            base = (b if isinstance(b, np.ndarray)
                    and b.flags.c_contiguous else arr)
            if base is run_base and ptr == run_start + run_len:
                run_len += nbytes
                continue
            if run_base is not None:
                parts.append(_base_u8_window(run_base, run_start,
                                             run_len))
            run_base, run_start, run_len = base, ptr, nbytes
        if run_base is not None:
            parts.append(_base_u8_window(run_base, run_start, run_len))
        w.write(memoryview(np.concatenate(parts))
                if len(parts) > 1 else parts[0])
    return total
