"""Bit-sliced indexing (BSI) engine: integer fields as bit-plane rows.

A frame *field* (name, min, max) stores one integer per column in a
dedicated ``field_<name>`` view: row 0 is the existence (not-null) row
and rows 1..depth hold the binary planes of ``value - min`` (bit i of
the offset value lives in row ``1 + i``), with
``depth = ceil(log2(max - min + 1))``. Comparison queries are the
classic O(depth) bit-plane boolean circuit (O'Neil/Quass bit-sliced
range evaluation; pilosa 1.0 fragment.go fieldRange*), and Sum/Min/Max
aggregate by popcount-weighted plane folds.

This module is backend-agnostic on purpose: ``compare_expr`` builds the
circuit once as the executor's ``("and"|"or"|"andnot", a, b)`` /
``("leaf", i)`` expression tuples, which evaluate identically over
host roaring bitmaps (``eval_bitmap_expr``), over packed words in
numpy (ops.packed / kernels fallback), and as ONE XLA program on the
device mesh (parallel.mesh + executor._compile_device_expr) — the same
tree, three backends, so their semantics cannot drift.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..errors import PilosaError

# Row layout within a field_<name> view (pilosa 1.0's bsiExistsBit /
# bsiOffsetBit layout): row 0 = existence, row 1+i = offset-value bit i.
EXISTS_ROW = 0
PLANE_ROW_OFFSET = 1

# Offset values are unsigned 63-bit at most (predicates travel as PQL
# int64; a wider range would not round-trip the wire form).
MAX_BIT_DEPTH = 63

# The existence row as a circuit plane index (compare_expr leaf space).
EXISTS_PLANE = -1


def bit_depth(min_v: int, max_v: int) -> int:
    """Value-plane count for the inclusive range [min, max]."""
    if max_v < min_v:
        raise PilosaError("field max must be >= min")
    return (max_v - min_v).bit_length()


@dataclass
class ValCount:
    """A Sum/Min/Max aggregate result: ``value`` plus how many columns
    contributed (for Min/Max: how many columns hold the extreme).
    ``count == 0`` means no column matched (value is meaningless)."""
    value: int = 0
    count: int = 0

    def to_json(self) -> dict:
        return {"value": self.value, "count": self.count}


def clamp(op: str, predicate, min_v: int, max_v: int):
    """Normalize a comparison against the field's [min, max] domain.

    Returns ``"none"`` (no column can match), ``"all"`` (every column
    with a value matches — the existence row), or ``(op, upred)`` with
    the predicate shifted into unsigned offset space. ``><`` returns
    ``("><", (ulo, uhi))`` with both bounds clamped into the domain.
    """
    if op == "><":
        lo, hi = predicate
        if lo > hi or hi < min_v or lo > max_v:
            return "none"
        if lo <= min_v and hi >= max_v:
            return "all"
        return op, (max(lo, min_v) - min_v, min(hi, max_v) - min_v)
    p = predicate
    if op == "<":
        if p <= min_v:
            return "none"
        if p > max_v:
            return "all"
    elif op == "<=":
        if p < min_v:
            return "none"
        if p >= max_v:
            return "all"
    elif op == ">":
        if p >= max_v:
            return "none"
        if p < min_v:
            return "all"
    elif op == ">=":
        if p > max_v:
            return "none"
        if p <= min_v:
            return "all"
    elif op == "==":
        if p < min_v or p > max_v:
            return "none"
    elif op == "!=":
        if p < min_v or p > max_v:
            return "all"
    else:
        raise PilosaError(f"invalid range operator: {op!r}")
    return op, p - min_v


def _eq_lt_exprs(upred: int, depth: int, leaf) -> tuple:
    """(eq, lt) circuit pair: eq = columns whose offset value equals
    ``upred``; lt = columns strictly below it. One MSB→LSB pass — the
    classic bit-sliced comparison (fragment.go fieldRangeLT shape)."""
    eq = leaf(EXISTS_PLANE)
    lt = None
    for i in reversed(range(depth)):
        plane = leaf(i)
        if (upred >> i) & 1:
            term = ("andnot", eq, plane)  # equal so far, bit 0 < 1
            lt = term if lt is None else ("or", lt, term)
            eq = ("and", eq, plane)
        else:
            eq = ("andnot", eq, plane)  # a 1 here exceeds the predicate
    return eq, lt


def _eq_gt_exprs(upred: int, depth: int, leaf) -> tuple:
    eq = leaf(EXISTS_PLANE)
    gt = None
    for i in reversed(range(depth)):
        plane = leaf(i)
        if (upred >> i) & 1:
            eq = ("and", eq, plane)
        else:
            term = ("and", eq, plane)  # equal so far, bit 1 > 0
            gt = term if gt is None else ("or", gt, term)
            eq = ("andnot", eq, plane)
    return eq, gt


def compare_expr(op: str, upred, depth: int,
                 leaf: Callable[[int], tuple]) -> Optional[tuple]:
    """The comparison circuit as an executor expression tree.

    ``op``/``upred`` must already be clamped into offset space (see
    ``clamp``; "none"/"all" never reach here). ``leaf(i)`` yields the
    leaf expression of value plane ``i`` (``EXISTS_PLANE`` for the
    existence row); it is called at most once per plane per side, so a
    plain list-appending closure stays linear. Returns None for a
    provably-empty circuit (e.g. ``< 0`` in offset space).
    """
    if op == "==":
        return _eq_lt_exprs(upred, depth, leaf)[0]
    if op == "!=":
        eq = _eq_lt_exprs(upred, depth, leaf)[0]
        return ("andnot", leaf(EXISTS_PLANE), eq)
    if op == "<":
        return _eq_lt_exprs(upred, depth, leaf)[1]
    if op == "<=":
        eq, lt = _eq_lt_exprs(upred, depth, leaf)
        return eq if lt is None else ("or", lt, eq)
    if op == ">":
        return _eq_gt_exprs(upred, depth, leaf)[1]
    if op == ">=":
        eq, gt = _eq_gt_exprs(upred, depth, leaf)
        return eq if gt is None else ("or", gt, eq)
    if op == "><":
        ulo, uhi = upred
        ge = compare_expr(">=", ulo, depth, leaf)
        le = compare_expr("<=", uhi, depth, leaf)
        if ge is None or le is None:
            return None
        return ("and", ge, le)
    raise PilosaError(f"invalid range operator: {op!r}")


def eval_bitmap_expr(expr: tuple, leaf_fn: Callable[[int], object]):
    """Evaluate a circuit over result Bitmaps (storage.bitmap.Bitmap —
    or anything with intersect/union/difference): the host per-slice
    backend. ``leaf_fn(i)`` materializes leaf ``i``."""
    op = expr[0]
    if op == "leaf":
        return leaf_fn(expr[1])
    a = eval_bitmap_expr(expr[1], leaf_fn)
    b = eval_bitmap_expr(expr[2], leaf_fn)
    if op == "and":
        return a.intersect(b)
    if op == "or":
        return a.union(b)
    return a.difference(b)


def range_bitmap(op: str, predicate, min_v: int, max_v: int,
                 row: Callable[[int], object]):
    """One slice's Range(field OP predicate) result Bitmap.

    ``row(i)`` returns the Bitmap of circuit plane ``i``
    (``EXISTS_PLANE`` = existence). Returns None for a provably-empty
    result (the caller supplies its empty-Bitmap type).
    """
    clamped = clamp(op, predicate, min_v, max_v)
    if clamped == "none":
        return None
    if clamped == "all":
        return row(EXISTS_PLANE)
    cop, upred = clamped
    expr = compare_expr(cop, upred, bit_depth(min_v, max_v),
                        lambda i: ("leaf", i))
    if expr is None:
        return None
    return eval_bitmap_expr(expr, row)


def sum_count(min_v: int, max_v: int, row: Callable[[int], object],
              filter=None) -> ValCount:
    """Popcount-weighted plane fold: Sum = min*count + Σ 2^i · |plane_i
    ∩ filter| (plane bits are subsets of the existence row, so no
    explicit existence intersect is needed per plane)."""
    exists = row(EXISTS_PLANE)
    if filter is None:
        count = exists.count()
    else:
        count = exists.intersection_count(filter)
    if count == 0:
        return ValCount(0, 0)
    total = min_v * count
    for i in range(bit_depth(min_v, max_v)):
        plane = row(i)
        n = plane.count() if filter is None \
            else plane.intersection_count(filter)
        total += n << i
    return ValCount(total, count)


def min_max(min_v: int, max_v: int, row: Callable[[int], object],
            filter=None, want_min: bool = True) -> ValCount:
    """Extreme value among columns with a value (∩ filter), plus how
    many columns hold it: MSB→LSB, keep the sub-population that can
    still be extreme at each plane."""
    b = row(EXISTS_PLANE)
    if filter is not None:
        b = b.intersect(filter)
    if b.count() == 0:
        return ValCount(0, 0)
    value = 0
    for i in reversed(range(bit_depth(min_v, max_v))):
        plane = row(i)
        if want_min:
            z = b.difference(plane)
            if z.count():
                b = z  # someone has bit 0: the minimum does too
            else:
                value |= 1 << i  # every candidate has this bit set
        else:
            z = b.intersect(plane)
            if z.count():
                b = z
                value |= 1 << i
    return ValCount(value + min_v, b.count())


def sum_count_many(min_v: int, max_v: int,
                   legs: list) -> ValCount:
    """One (sum, count) partial over a whole node leg's slices in one
    pass — the batched form of per-slice ``sum_count`` + ``combine_sum``.
    ``legs`` is ``[(row_fn, filter_or_None), ...]``, one entry per
    owned slice. Slices with an empty (filtered) existence row drop
    out before any value plane is read."""
    live = []
    count = 0
    for row, filt in legs:
        exists = row(EXISTS_PLANE)
        n = exists.count() if filt is None \
            else exists.intersection_count(filt)
        if n:
            count += n
            live.append((row, filt))
    if count == 0:
        return ValCount(0, 0)
    total = min_v * count
    for i in range(bit_depth(min_v, max_v)):
        for row, filt in live:
            plane = row(i)
            n = plane.count() if filt is None \
                else plane.intersection_count(filt)
            total += n << i
    return ValCount(total, count)


def min_max_many(min_v: int, max_v: int, legs: list,
                 want_min: bool = True) -> ValCount:
    """One min/max partial over a whole node leg's slices — the
    MSB→LSB candidate walk of ``min_max`` run JOINTLY across slices,
    which prunes harder than per-slice + combine: the moment ANY slice
    still holds a candidate with the favorable bit, every slice whose
    candidates all carry the unfavorable one is dropped outright and
    pays nothing for the remaining planes."""
    cands = []
    for row, filt in legs:
        b = row(EXISTS_PLANE)
        if filt is not None:
            b = b.intersect(filt)
        if b.count():
            cands.append((row, b))
    if not cands:
        return ValCount(0, 0)
    value = 0
    for i in reversed(range(bit_depth(min_v, max_v))):
        nxt = []
        for row, b in cands:
            z = (b.difference(row(i)) if want_min
                 else b.intersect(row(i)))
            if z.count():
                nxt.append((row, z))
        if nxt:
            # Some slice can still improve the extreme at this bit:
            # the global extreme has the favorable value here, and
            # only those slices stay in play.
            cands = nxt
            if not want_min:
                value |= 1 << i
        elif want_min:
            value |= 1 << i  # every candidate everywhere has the bit
    return ValCount(value + min_v,
                    sum(b.count() for _row, b in cands))


def combine_sum(a: ValCount, b: ValCount) -> ValCount:
    return ValCount(a.value + b.value, a.count + b.count)


def combine_min_max(a: ValCount, b: ValCount,
                    want_min: bool = True) -> ValCount:
    """Cluster mapReduce merge of per-slice Min/Max partials: empty
    sides (count == 0) are identity; equal extremes sum their counts."""
    if a.count == 0:
        return b
    if b.count == 0:
        return a
    if a.value == b.value:
        return ValCount(a.value, a.count + b.count)
    keep_a = a.value < b.value if want_min else a.value > b.value
    return a if keep_a else b
