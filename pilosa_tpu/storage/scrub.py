"""Background storage scrubber: continuous re-verification of the
on-disk fragment files against their integrity footers.

Detection at open/first-read (storage.integrity, fragment lazy verify)
only fires when a fragment is (re)opened or first touched — a serving
fleet's hot fragments stay open for weeks, and bit rot under an mmap
is invisible until a page fault re-reads the rotten block. The
scrubber closes that window: a paced pass over every open fragment
(the PR-5 breaker/pacing discipline — a sleep between fragments so a
scrub never competes with serving for disk bandwidth) that re-reads
each DATA FILE through its own fd (an ``os.replace`` swap pins the old
inode, so the read is always a consistent append-only prefix),
re-computes every container block's crc32 against the footer table,
re-checks the whole-body digest, and cross-validates the WAL tail's
FNV checksums. Any mismatch quarantines the fragment
(detection → failover → repair; docs/FAULT_TOLERANCE.md).

``scrub_buffer`` / ``scrub_file`` are the standalone (lock-free)
verdict functions the CLI's offline ``check --deep`` shares.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..obs import metrics as obs_metrics
from ..utils import logger as logger_mod
from . import integrity
from . import roaring

DEFAULT_INTERVAL_S = 600.0   # seconds between passes
DEFAULT_PACE_S = 0.01        # sleep between fragments within a pass


def scrub_buffer(buf) -> dict:
    """Verify one data-file buffer (snapshot body [+footer] [+op-log
    tail]). Returns a verdict dict::

        {"corrupt": bool, "coverage": "full"|"none",
         "blocks": N, "badBlocks": [...], "error": "...",
         "walRecords": N, "walBad": N, "walTornBytes": N}

    ``coverage: none`` (a vintage un-footered file) is NOT corruption
    — the body simply predates checksums; the WAL tail still
    validates. A trailing partial op record (or a footer truncated at
    EOF) is a TEAR, reported but not corruption: it is exactly the
    state a crash mid-append leaves, and the reopen trim handles it.
    """
    out = {"corrupt": False, "coverage": "none", "blocks": 0,
           "badBlocks": [], "error": "", "walRecords": 0, "walBad": 0,
           "walTornBytes": 0}

    def bad(msg: str) -> dict:
        out["corrupt"] = True
        out["error"] = msg
        return out

    buf = memoryview(buf)
    try:
        # The SAME layout parser the decoder uses (roaring.
        # parse_snapshot_layout) — a format change cannot make the
        # scrubber mis-parse clean files the decoder accepts.
        (hdr, _run_mask, _ns, offs, sizes, ops_offset,
         body_end) = roaring.parse_snapshot_layout(buf)
    except ValueError as e:
        return bad(str(e))
    key_n = len(hdr)

    # Footer + block table — the SAME verification sequence the
    # decoder runs (integrity.parse_and_verify_footer), with the
    # per-block table checked up front for the badBlocks detail.
    ops_start = body_end
    try:
        info = integrity.parse_and_verify_footer(
            buf, key_n, ops_offset, offs, sizes, body_end)
    except integrity.TornFooterError as e:
        out["walTornBytes"] = e.torn_bytes
        return out  # torn footer at EOF: a tear, not corruption
    except integrity.CorruptionError as e:
        return bad(str(e))
    if info is not None:
        out["coverage"] = "full"
        out["blocks"] = info.block_n
        ops_start = body_end + info.size
        bad_blocks = integrity.verify_blocks(buf, info)
        if bad_blocks:
            out["badBlocks"] = bad_blocks
            return bad(f"{len(bad_blocks)} container blocks fail crc"
                       f" (first: {bad_blocks[:4]})")
        try:
            integrity.verify_body(buf, info)
        except integrity.CorruptionError as e:
            return bad(str(e))

    # WAL tail: every COMPLETE 13-byte op record must carry a valid
    # FNV-1a checksum and a known type; a trailing partial record is a
    # tear (in-flight append / crash), tolerated.
    rest = buf[ops_start:]
    n_rest = len(rest)
    n_ops = n_rest // roaring.OP_SIZE
    out["walRecords"] = n_ops
    out["walTornBytes"] += n_rest - n_ops * roaring.OP_SIZE
    if n_ops:
        recs = np.frombuffer(rest, dtype=np.uint8,
                             count=n_ops * roaring.OP_SIZE
                             ).reshape(n_ops, roaring.OP_SIZE)
        h = roaring.fnv_fold_records(recs)
        stored = np.ascontiguousarray(recs[:, 9:13]).view("<u4").ravel()
        bad_mask = (h != stored) | (recs[:, 0] > roaring.OP_REMOVE)
        n_bad = int(bad_mask.sum())
        if n_bad:
            out["walBad"] = n_bad
            return bad(f"{n_bad} WAL records fail their FNV checksum"
                       f" (first at record"
                       f" {int(np.flatnonzero(bad_mask)[0])})")
    return out


def scrub_file(path: str) -> dict:
    """Offline verdict for one data file (the CLI ``check --deep``
    lane — no locks, no registry side effects)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        return {"corrupt": True, "coverage": "none",
                "error": f"unreadable: {e}", "blocks": 0,
                "badBlocks": [], "walRecords": 0, "walBad": 0,
                "walTornBytes": 0}
    return scrub_buffer(data)


class Scrubber:
    """The background pass. One thread, paced; a pass walks a
    point-in-time snapshot of the holder's open fragments and defers
    each file's verification to ``Fragment.verify_on_disk`` (which
    quarantines on a corrupt verdict). ``on_corrupt(fragment)`` fires
    per newly-detected corruption so the server's repairer wakes
    without polling."""

    def __init__(self, holder, interval_s: float = DEFAULT_INTERVAL_S,
                 pace_s: float = DEFAULT_PACE_S, on_corrupt=None,
                 logger=logger_mod.NOP):
        self.holder = holder
        self.interval_s = max(0.05, float(interval_s))
        self.pace_s = max(0.0, float(pace_s))
        self.on_corrupt = on_corrupt
        self.logger = logger
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        # Serializes whole passes: an operator ?sync=1 pass racing the
        # background thread would otherwise interleave (doubled scrub
        # IO) and the first finisher would blank _pass_started while
        # the other still runs — blinding the watchdog's scrub_stall
        # detector for exactly the long pass it watches.
        self._pass_mu = threading.Lock()
        # Pass progress (the watchdog's scrub_stall detector reads
        # stall_age; /debug/integrity reads state()).
        self._pass_started: Optional[float] = None
        self._last_progress = 0.0
        self._passes = 0
        self._fragments_scrubbed = 0
        self._blocks_verified = 0
        self._corruptions = 0
        self._last_pass_at = 0.0
        self._last_pass_s = 0.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-scrub",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def trigger(self) -> None:
        """Request an immediate pass (tests, POST /debug/integrity)."""
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            woke = self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.pass_once()
            except Exception as e:  # noqa: BLE001 - scrub must not die
                self.logger.printf("scrub: pass failed: %s", e)
            del woke

    # -- the pass ------------------------------------------------------------

    def pass_once(self) -> dict:
        """One full scrub pass; returns the pass summary. Passes are
        serialized — a triggered sync pass waits out an in-flight
        background one instead of doubling its IO."""
        with self._pass_mu:
            return self._pass_locked()

    def _pass_locked(self) -> dict:
        t0 = time.monotonic()
        with self._mu:
            self._pass_started = t0
            self._last_progress = t0
        scrubbed = blocks = corrupt = 0
        try:
            for frag in self.holder.iter_fragments():
                if self._stop.is_set():
                    break
                if not frag._open or frag.quarantined:
                    continue
                try:
                    if getattr(frag, "tier_state", "hot") == "blob":
                        # Blob-tier fragment: no local file — verify
                        # the blob store's objects against the
                        # manifest crcs + footer digest instead
                        # (tier.manager.scrub_blob; same pace budget,
                        # same verdict shape). Cold fragments take
                        # the normal path: their file is local and
                        # complete, verify_on_disk reads it through
                        # its own fd without promoting anything.
                        tier = getattr(self.holder, "tier", None)
                        if tier is None:
                            continue
                        verdict = tier.scrub_blob(frag)
                    else:
                        verdict = frag.verify_on_disk()
                except Exception as e:  # noqa: BLE001 - keep walking
                    self.logger.printf(
                        "scrub: %s unverifiable: %s", frag.path, e)
                    continue
                scrubbed += 1
                n_blocks = int(verdict.get("blocks") or 0)
                blocks += n_blocks
                if n_blocks:
                    obs_metrics.STORAGE_SCRUB_BLOCKS.labels(
                        "scrub").inc(n_blocks)
                if verdict.get("corrupt"):
                    corrupt += 1
                    self.logger.printf(
                        "scrub: CORRUPT %s: %s", frag.path,
                        verdict.get("error"))
                    cb = self.on_corrupt
                    if cb is not None:
                        try:
                            cb(frag)
                        except Exception:  # noqa: BLE001 - advisory
                            pass
                with self._mu:
                    self._last_progress = time.monotonic()
                if self.pace_s:
                    # Pacing: serving traffic owns the disk; the scrub
                    # breathes between fragments.
                    if self._stop.wait(self.pace_s):
                        break
        finally:
            now = time.monotonic()
            with self._mu:
                self._pass_started = None
                self._passes += 1
                self._fragments_scrubbed += scrubbed
                self._blocks_verified += blocks
                self._corruptions += corrupt
                self._last_pass_at = time.time()
                self._last_pass_s = now - t0
        return {"fragments": scrubbed, "blocks": blocks,
                "corrupt": corrupt, "seconds": round(now - t0, 3)}

    # -- exposition ----------------------------------------------------------

    def stall_age(self) -> Optional[float]:
        """Seconds since an IN-FLIGHT pass last made progress, or None
        when no pass is running (the watchdog scrub_stall input)."""
        with self._mu:
            if self._pass_started is None:
                return None
            return time.monotonic() - self._last_progress

    def state(self) -> dict:
        with self._mu:
            in_flight = self._pass_started is not None
            return {"intervalS": self.interval_s,
                    "paceS": self.pace_s,
                    "passes": self._passes,
                    "inFlight": in_flight,
                    "fragmentsScrubbed": self._fragments_scrubbed,
                    "blocksVerified": self._blocks_verified,
                    "corruptionsFound": self._corruptions,
                    "lastPassAt": self._last_pass_at,
                    "lastPassSeconds": round(self._last_pass_s, 3)}
