"""Attribute store: row/column attribute K/V maps, SQLite-backed.

Reference: attr.go (BoltDB). Same model: per-id attribute maps stored as
protobuf ``AttrMap`` blobs keyed by big-endian u64 id, an in-memory map
cache in front, 100-id anti-entropy blocks with SHA1 checksums over
(key, value-blob) in id order, and merge-on-update semantics.

SQLite replaces BoltDB as the host-side embedded K/V — a natural fit here
since the store is metadata, not the compute path. The BE-u64 BLOB primary
key keeps cursor order identical to the reference's bucket scan.
"""

from __future__ import annotations

import hashlib
import os
import sqlite3
import threading
from typing import Optional

from ..proto import internal_pb2 as pb

# Attribute type codes (reference attr.go:34-40).
ATTR_TYPE_STRING = 1
ATTR_TYPE_INT = 2
ATTR_TYPE_BOOL = 3
ATTR_TYPE_FLOAT = 4

# Ids per anti-entropy block (reference attr.go:31).
ATTR_BLOCK_SIZE = 100


def _u64tob(v: int) -> bytes:
    return int(v).to_bytes(8, "big")


def _btou64(b: bytes) -> int:
    return int.from_bytes(b, "big")


def encode_attrs(m: dict) -> bytes:
    """Deterministic (key-sorted) AttrMap blob."""
    out = pb.AttrMap()
    for k in sorted(m):
        v = m[k]
        a = out.Attrs.add()
        a.Key = k
        if isinstance(v, bool):  # check before int — bool is an int subtype
            a.Type, a.BoolValue = ATTR_TYPE_BOOL, v
        elif isinstance(v, str):
            a.Type, a.StringValue = ATTR_TYPE_STRING, v
        elif isinstance(v, int):
            a.Type, a.IntValue = ATTR_TYPE_INT, v
        elif isinstance(v, float):
            a.Type, a.FloatValue = ATTR_TYPE_FLOAT, v
        # unknown types are dropped, matching reference encodeAttr
    return out.SerializeToString()


def decode_attrs(blob: bytes) -> dict:
    m = {}
    for a in pb.AttrMap.FromString(blob).Attrs:
        if a.Type == ATTR_TYPE_STRING:
            m[a.Key] = a.StringValue
        elif a.Type == ATTR_TYPE_INT:
            m[a.Key] = a.IntValue
        elif a.Type == ATTR_TYPE_BOOL:
            m[a.Key] = a.BoolValue
        elif a.Type == ATTR_TYPE_FLOAT:
            m[a.Key] = a.FloatValue
    return m


def diff_blocks(a: list[tuple[int, bytes]], b: list[tuple[int, bytes]]
                ) -> list[int]:
    """Block ids in ``a`` that differ from or are missing in ``b``
    (reference attr.go AttrBlocks.Diff)."""
    ids = []
    i = j = 0
    while i < len(a):
        if j >= len(b) or a[i][0] < b[j][0]:
            ids.append(a[i][0])
            i += 1
        elif b[j][0] < a[i][0]:
            j += 1
        else:
            if a[i][1] != b[j][1]:
                ids.append(a[i][0])
            i += 1
            j += 1
    return ids


class AttrStore:
    def __init__(self, path: str):
        self.path = path
        self._db: Optional[sqlite3.Connection] = None
        self._cache: dict[int, dict] = {}
        self._mu = threading.RLock()

    def open(self) -> None:
        with self._mu:
            if self._db is not None:
                return
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._db = sqlite3.connect(self.path, check_same_thread=False)
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS attrs "
                "(id BLOB PRIMARY KEY, value BLOB NOT NULL)")
            self._db.commit()

    def close(self) -> None:
        with self._mu:
            if self._db is not None:
                self._db.close()
                self._db = None
            self._cache.clear()

    def attrs(self, id: int) -> dict:
        """Attributes for an id (cached); {} when unset."""
        with self._mu:
            m = self._cache.get(id)
            if m is not None:
                return dict(m)
            row = self._db.execute(
                "SELECT value FROM attrs WHERE id = ?",
                (_u64tob(id),)).fetchone()
            m = decode_attrs(row[0]) if row else {}
            self._cache[id] = m
            return dict(m)

    def set_attrs(self, id: int, m: dict) -> None:
        """Merge m into the id's attributes; None values delete keys
        (reference attr.go txUpdateAttrs)."""
        with self._mu:
            merged = self._merge(id, m)
            self._db.commit()
            self._cache[id] = merged

    def set_bulk_attrs(self, m: dict[int, dict]) -> None:
        with self._mu:
            merged_all = {}
            for id in sorted(m):
                merged_all[id] = self._merge(id, m[id])
            self._db.commit()
            self._cache.update(merged_all)

    def _merge(self, id: int, m: dict) -> dict:
        row = self._db.execute("SELECT value FROM attrs WHERE id = ?",
                               (_u64tob(id),)).fetchone()
        current = decode_attrs(row[0]) if row else {}
        for k, v in m.items():
            if v is None:
                current.pop(k, None)
            else:
                current[k] = v
        self._db.execute(
            "INSERT OR REPLACE INTO attrs (id, value) VALUES (?, ?)",
            (_u64tob(id), encode_attrs(current)))
        return current

    # -- anti-entropy blocks --------------------------------------------------

    def blocks(self) -> list[tuple[int, bytes]]:
        """(block_id, sha1) per non-empty 100-id block; hash covers
        (BE key, value blob) pairs in key order (reference attr.go:181-209)."""
        with self._mu:
            out = []
            h = None
            cur_block = None
            for key, value in self._db.execute(
                    "SELECT id, value FROM attrs ORDER BY id"):
                bid = _btou64(key) // ATTR_BLOCK_SIZE
                if bid != cur_block:
                    if h is not None:
                        out.append((cur_block, h.digest()))
                    cur_block, h = bid, hashlib.sha1()
                h.update(key)
                h.update(value)
            if h is not None:
                out.append((cur_block, h.digest()))
            return out

    def block_data(self, block_id: int) -> dict[int, dict]:
        """All id→attrs in one block (reference attr.go:211-241)."""
        with self._mu:
            lo = _u64tob(block_id * ATTR_BLOCK_SIZE)
            hi = _u64tob((block_id + 1) * ATTR_BLOCK_SIZE)
            return {
                _btou64(k): decode_attrs(v)
                for k, v in self._db.execute(
                    "SELECT id, value FROM attrs WHERE id >= ? AND id < ?",
                    (lo, hi))
            }
