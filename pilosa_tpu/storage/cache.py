"""Row caches: TopN rank cache, LRU cache, and the Pair merge algebra.

Reference: cache.go. The rank cache keeps per-row bit counts above a dynamic
threshold so TopN can scan candidates in rank order without touching every
row; it is also the working-set signal for device residency (the top-ranked
rows are exactly the rows worth pinning in HBM — see
pilosa_tpu.parallel.residency).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Iterable

import numpy as np

# ThresholdFactor of maxEntries is how far the unsorted entry map may grow
# past maxEntries before a trim (reference cache.go:30-33, factor 1.1).
THRESHOLD_FACTOR = 1.1

# Default cache size per fragment (reference frame.go:39).
DEFAULT_CACHE_SIZE = 50000

CACHE_TYPE_LRU = "lru"
CACHE_TYPE_RANKED = "ranked"
DEFAULT_CACHE_TYPE = CACHE_TYPE_LRU


class Pair:
    """(id, count) result pair (reference cache.go:278-316)."""

    __slots__ = ("id", "count")

    def __init__(self, id: int, count: int):
        self.id = id
        self.count = count

    def __repr__(self):
        return f"Pair(id={self.id}, count={self.count})"

    def __eq__(self, other):
        return (isinstance(other, Pair) and self.id == other.id
                and self.count == other.count)


def pairs_add(a: list[Pair], b: list[Pair]) -> list[Pair]:
    """Merge two pair lists, summing counts by id (cache.go:343-361)."""
    m: dict[int, int] = {}
    for p in a:
        m[p.id] = m.get(p.id, 0) + p.count
    for p in b:
        m[p.id] = m.get(p.id, 0) + p.count
    return [Pair(k, v) for k, v in m.items()]


def pairs_sort(pairs: Iterable[Pair]) -> list[Pair]:
    """Descending by count, ascending id for ties (BitmapPairs sort order)."""
    return sorted(pairs, key=lambda p: (-p.count, p.id))


def _rank_arrays(keys, values, n: int) -> tuple[np.ndarray, np.ndarray]:
    """(ids, counts) in rank order — count desc, id asc on ties — via
    one numpy lexsort instead of a 50 K-object Python sort. This is the
    TopN candidate phase's hot loop at BASELINE config-3 scale."""
    ids = np.fromiter(keys, dtype=np.uint64, count=n)
    counts = np.fromiter(values, dtype=np.int64, count=n)
    order = np.lexsort((ids, -counts))
    return ids[order], counts[order]


def _pairs_from_arrays(ids: np.ndarray, counts: np.ndarray) -> list[Pair]:
    return [Pair(i, c) for i, c in zip(ids.tolist(), counts.tolist())]


class RankCache:
    """Keeps ids with counts above a dynamic threshold, ranked.

    Semantics follow reference cache.go:126-275: adds below thresholdValue
    are ignored; rankings are recomputed at most every 10 s (except via
    recalculate()); when the entry map outgrows maxEntries*1.1 it is trimmed
    to entries above the threshold.
    """

    def __init__(self, max_entries: int = DEFAULT_CACHE_SIZE):
        self.max_entries = max_entries
        self.threshold_buffer = int(THRESHOLD_FACTOR * max_entries)
        self.threshold_value = 0
        self.entries: dict[int, int] = {}
        self.rankings: list[Pair] | None = []
        self._rank_ids = np.empty(0, dtype=np.uint64)
        self._rank_counts = np.empty(0, dtype=np.int64)
        self._update_time = 0.0

    def add(self, id: int, n: int) -> None:
        if n < self.threshold_value:
            return
        self.entries[id] = n
        self.invalidate()

    def bulk_add(self, id: int, n: int) -> None:
        """Unsorted add; call recalculate() when done."""
        if n < self.threshold_value:
            return
        self.entries[id] = n

    def get(self, id: int) -> int:
        return self.entries.get(id, 0)

    def __len__(self):
        return len(self.entries)

    def ids(self) -> list[int]:
        return sorted(self.entries)

    def invalidate(self) -> None:
        # Rate-limited recalculation (cache.go:219-226).
        if time.monotonic() - self._update_time < 10:
            return
        self.recalculate()

    def recalculate(self) -> None:
        ids, counts = _rank_arrays(self.entries.keys(),
                                   self.entries.values(),
                                   len(self.entries))
        if len(ids) > self.max_entries:
            self.threshold_value = int(counts[self.max_entries])
            ids = ids[:self.max_entries]
            counts = counts[:self.max_entries]
        else:
            self.threshold_value = 1
        self._rank_ids, self._rank_counts = ids, counts
        self.rankings = None  # Pair list built lazily by top()
        self._update_time = time.monotonic()
        if len(self.entries) > self.threshold_buffer:
            self.entries = {i: c for i, c in self.entries.items()
                            if c > self.threshold_value}

    def top_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(ids, counts) in rank order, no per-entry objects."""
        return self._rank_ids, self._rank_counts

    def top(self) -> list[Pair]:
        if self.rankings is None:
            self.rankings = _pairs_from_arrays(self._rank_ids,
                                               self._rank_counts)
        return self.rankings


class LRUCache:
    """LRU id→count cache (reference cache.go:55-123 over groupcache/lru)."""

    def __init__(self, max_entries: int = DEFAULT_CACHE_SIZE):
        self.max_entries = max_entries
        self._od: OrderedDict[int, int] = OrderedDict()
        self._ranked = None  # cached (ids, counts) arrays, rank order

    def add(self, id: int, n: int) -> None:
        self._od[id] = n
        self._od.move_to_end(id)
        while len(self._od) > self.max_entries:
            self._od.popitem(last=False)
        self._ranked = None

    bulk_add = add

    def get(self, id: int) -> int:
        n = self._od.get(id, 0)
        if id in self._od:
            self._od.move_to_end(id)  # recency changes, counts don't
        return n

    def __len__(self):
        return len(self._od)

    def ids(self) -> list[int]:
        return sorted(self._od)

    def invalidate(self) -> None:
        pass

    def recalculate(self) -> None:
        pass

    def top_arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(ids, counts) in rank order; cached until the next mutation
        — the old per-call Python sort of 50 K entries dominated the
        TopN candidate phase."""
        if self._ranked is None:
            self._ranked = _rank_arrays(self._od.keys(),
                                        self._od.values(), len(self._od))
        return self._ranked

    def top(self) -> list[Pair]:
        ids, counts = self.top_arrays()
        return _pairs_from_arrays(ids, counts)


class SimpleCache:
    """Unbounded row-bitmap cache for write-heavy loads
    (reference cache.go:437-462)."""

    def __init__(self):
        self._m: dict[int, object] = {}

    def fetch(self, id: int):
        return self._m.get(id)

    def add(self, id: int, bm) -> None:
        self._m[id] = bm

    def invalidate(self, id: int) -> None:
        self._m.pop(id, None)

    def clear(self) -> None:
        self._m.clear()


def new_cache(cache_type: str, size: int):
    if cache_type == CACHE_TYPE_RANKED:
        return RankCache(size)
    if cache_type == CACHE_TYPE_LRU:
        return LRUCache(size)
    raise ValueError(f"unknown cache type: {cache_type!r}")


def top_n_heap_merge(pairs_lists: list[list[Pair]], n: int) -> list[Pair]:
    """Merge per-slice TopN pair lists: sum counts by id, keep top n
    (reference executor.go:319-334 reduce step)."""
    merged: list[Pair] = []
    for pl in pairs_lists:
        merged = pairs_add(merged, pl)
    merged = pairs_sort(merged)
    return merged[:n] if n else merged
