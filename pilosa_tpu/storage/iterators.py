"""Generic (row, column) pair iterators.

Reference: iterator.go. The ``Iterator`` contract is two methods —
``seek(row, col)`` positions at the first pair >= (row, col), and
``next()`` returns ``(row, col, eof)`` — plus three adapters:
``BufIterator`` (single-slot unread/peek, iterator.go:30-80),
``LimitIterator`` (EOF past a max pair, iterator.go:82-119),
``SliceIterator`` (parallel id arrays, iterator.go:122-172), and
``RoaringIterator`` (adapts bitmap positions to pairs via
pos = row*SLICE_WIDTH + col, iterator.go:175-194).

These serve host-side streaming paths (export, consensus merge input);
bulk compute never iterates bit-by-bit — it goes through the packed
device kernels (pilosa_tpu.ops).
"""

from __future__ import annotations

from .. import SLICE_WIDTH
from .roaring import Bitmap

EOF = (0, 0, True)


class BufIterator:
    """Buffered iterator supporting a one-deep unread (iterator.go:30-80)."""

    def __init__(self, itr):
        self._itr = itr
        self._buf = None          # last value read, if retained
        self._full = False

    def seek(self, row_id: int, column_id: int) -> None:
        self._full = False
        self._itr.seek(row_id, column_id)

    def next(self) -> tuple[int, int, bool]:
        if self._full:
            self._full = False
            return self._buf
        self._buf = self._itr.next()
        return self._buf

    def peek(self) -> tuple[int, int, bool]:
        out = self.next()
        self.unread()
        return out

    def unread(self) -> None:
        """Push the previous pair back; error if one is already buffered
        (iterator.go:73-80 panics) or nothing has been read yet."""
        if self._full:
            raise RuntimeError("BufIterator: buffer full")
        if self._buf is None:
            raise RuntimeError("BufIterator: nothing read yet")
        self._full = True


class LimitIterator:
    """EOF once the source passes (max_row, max_col) (iterator.go:82-119)."""

    def __init__(self, itr, max_row_id: int, max_column_id: int):
        self._itr = itr
        self.max_row_id = max_row_id
        self.max_column_id = max_column_id
        self._eof = False

    def seek(self, row_id: int, column_id: int) -> None:
        self._eof = False   # re-positioning revives a drained iterator
        self._itr.seek(row_id, column_id)

    def next(self) -> tuple[int, int, bool]:
        if self._eof:
            return EOF
        row, col, eof = self._itr.next()
        if eof or row > self.max_row_id or (
                row == self.max_row_id and col > self.max_column_id):
            self._eof = True
            return EOF
        return row, col, False


class SliceIterator:
    """Iterate parallel row/column id arrays (iterator.go:122-172)."""

    def __init__(self, row_ids, column_ids):
        if len(row_ids) != len(column_ids):
            raise ValueError(
                f"SliceIterator: pair length mismatch: "
                f"{len(row_ids)} != {len(column_ids)}")
        self._rows = row_ids
        self._cols = column_ids
        self._i = 0
        self._n = len(row_ids)

    def seek(self, row_id: int, column_id: int) -> None:
        for i in range(self._n):
            r, c = int(self._rows[i]), int(self._cols[i])
            if (row_id == r and column_id <= c) or row_id < r:
                self._i = i
                return
        self._i = self._n

    def next(self) -> tuple[int, int, bool]:
        if self._i >= self._n:
            return EOF
        out = (int(self._rows[self._i]), int(self._cols[self._i]), False)
        self._i += 1
        return out


class RoaringIterator:
    """Adapt a roaring bitmap's sorted positions into (row, col) pairs
    (iterator.go:175-194)."""

    def __init__(self, bitmap: Bitmap, slice_width: int = SLICE_WIDTH):
        self._bitmap = bitmap
        self._width = slice_width
        self._gen = iter(bitmap)

    def seek(self, row_id: int, column_id: int) -> None:
        self._gen = self._bitmap.iterator_from(
            row_id * self._width + column_id)

    def next(self) -> tuple[int, int, bool]:
        v = next(self._gen, None)
        if v is None:
            return EOF
        return v // self._width, v % self._width, False


def pairs(itr):
    """Drain an Iterator into a Python list of (row, col) tuples."""
    out = []
    while True:
        row, col, eof = itr.next()
        if eof:
            return out
        out.append((row, col))
