"""Node-to-node / external HTTP client: protobuf-over-HTTP data plane.

Reference: client.go. Used for remote query legs (executor.go:1001-1083),
slice-grouped bulk imports (client.go:304-389), anti-entropy block sync
(client.go:798-886), attr diffs (client.go:889-974), and backup/restore
streaming (client.go:463-674).
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from .. import SLICE_WIDTH
from ..utils.arrays import group_by_key
from ..errors import (FragmentNotFoundError, PilosaError,
                      QueryDeadlineError)
from ..fault import failpoints as _fp
from ..obs.accounting import COST_HEADER
from ..obs.trace import SPANS_HEADER, TRACE_HEADER
from ..plan import record as plan_record
from ..pql import parser as pql
from ..proto import internal_pb2 as pb
from ..sched import context as sched_context
from . import generations as gens_mod
from .topology import Node

_PROTOBUF = "application/x-protobuf"


class ClientError(PilosaError):
    pass


class CircuitOpenError(ClientError):
    """Failed fast: the target peer's circuit breaker is open (fault
    subsystem). Subclasses ClientError so every failover loop treats
    it exactly like the timeout it replaces — minus the wait."""


def _host_of(node) -> str:
    return node.host if isinstance(node, Node) else str(node)


class _StreamReader:
    """File-like over an HTTPResponse + its dedicated connection."""

    def __init__(self, resp, conn):
        self.resp = resp
        self.conn = conn
        self.status = resp.status

    def read(self, size: int = -1) -> bytes:
        return self.resp.read(size)

    def close(self) -> None:
        try:
            self.resp.close()
        finally:
            self.conn.close()


class Bit:
    """One (row, column, timestamp) triple for import
    (client.go:977-1005)."""

    __slots__ = ("row_id", "column_id", "timestamp")

    def __init__(self, row_id: int, column_id: int, timestamp: int = 0):
        self.row_id = row_id
        self.column_id = column_id
        self.timestamp = timestamp  # ns since epoch, 0 = none


class Client:
    """HTTP client against one host (plus owner discovery for imports).

    Connections are pooled per host with keep-alive: write-heavy flows
    (imports, `bench -op set-bit`, anti-entropy block sync) issue many
    small requests, and a fresh TCP connect per request dominates their
    latency. The pool is thread-safe (executor fan-out shares one
    Client across worker threads); a request that fails on a pooled
    connection retries once on a fresh one, since the server may have
    closed an idle socket.
    """

    def __init__(self, host: str, timeout: float = 30.0, fault=None,
                 gens=None):
        if not host:
            raise ClientError("host required")
        self.host = host
        self.timeout = timeout
        # Coordinator-side generation map (cluster.generations): when
        # set, every query/import response's piggybacked
        # X-Pilosa-Generations header lands here — unless the caller
        # passes gens_out to take custody (hedged reads must merge
        # the WINNING leg's tokens only). None (external clients,
        # CLI) skips the parse entirely.
        self.gens = gens
        # Fault-tolerance hook (fault.FaultManager): when set, every
        # request consults the target's circuit breaker (open = fail
        # fast with CircuitOpenError instead of paying the socket
        # timeout) and every attempt's outcome + latency feeds the
        # per-peer health EWMA. None (bare clients, tests, CLI) keeps
        # the plain transport behavior.
        self.fault = fault
        self._pool: dict[str, list[http.client.HTTPConnection]] = {}
        self._pool_mu = threading.Lock()
        # Hosts that 415'd the raw-array import format (reference-
        # shaped servers): remembered so every later slice goes
        # straight to protobuf.
        self._no_raw_import: set[str] = set()
        self._no_posn_import: set[str] = set()

    # -- low-level -----------------------------------------------------------

    _POOL_PER_HOST = 8

    def _conn_get(self, host: str) -> Optional[http.client.HTTPConnection]:
        with self._pool_mu:
            conns = self._pool.get(host)
            return conns.pop() if conns else None

    def _conn_put(self, host: str, conn: http.client.HTTPConnection) -> None:
        with self._pool_mu:
            conns = self._pool.setdefault(host, [])
            if len(conns) < self._POOL_PER_HOST:
                conns.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._pool_mu:
            for conns in self._pool.values():
                for c in conns:
                    c.close()
            self._pool.clear()

    _IDEMPOTENT = frozenset({"GET", "HEAD", "PUT", "DELETE"})

    def _do(self, method: str, path: str, body: Optional[bytes] = None,
            headers: Optional[dict] = None, host: Optional[str] = None,
            idempotent: Optional[bool] = None,
            deadline_s: Optional[float] = None,
            headers_out: Optional[list] = None) -> tuple[int, bytes]:
        """``idempotent`` overrides the per-method default for POST
        endpoints that are safe to replay (queries, attr diffs, create-
        if-not-exists) — those keep the transparent stale-keep-alive
        retry; everything else (e.g. /import op-log appends) does not.

        ``deadline_s`` is the query's remaining budget (sched
        subsystem): every attempt's socket timeout is clamped to what
        is left, and NO attempt — in particular no retry — starts once
        the budget is exhausted (an attempt whose timeout exceeded the
        remaining budget would overrun the caller's deadline). Budget
        exhaustion surfaces as QueryDeadlineError, distinct from
        ClientError so failover loops don't retry a dead query on a
        replica."""
        target = host or self.host
        if idempotent is None:
            idempotent = method in self._IDEMPOTENT
        # Circuit breaker (fault subsystem): an open circuit fails
        # fast — the whole point is to NOT pay the dead peer's socket
        # timeout again. allow() grants the half-open probe when the
        # backoff window has lapsed.
        if self.fault is not None and not self.fault.allow(target):
            ctx = sched_context.current()
            if ctx is not None:
                # Tail-sampling cross-link: this query touched an open
                # breaker — whatever happens next (failover, partial,
                # error), its trace is worth keeping (obs.sampler).
                ctx.note_flag("breaker")
            raise CircuitOpenError(
                f"{method} http://{target}{path}: circuit open")
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        # File-like bodies (streaming restore) must rewind between
        # attempts — http.client reads them destructively.
        body_start = body.tell() if hasattr(body, "seek") else None
        last_err = None
        for attempt in range(2):
            timeout = self.timeout
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise QueryDeadlineError(
                        f"{method} http://{target}{path}: deadline"
                        f" exceeded"
                        + (f" (after {last_err})" if last_err else ""))
                timeout = min(timeout, remaining)
            if body_start is not None:
                body.seek(body_start)
            conn = None if attempt else self._conn_get(target)
            fresh = conn is None
            if conn is None:
                try:
                    conn = http.client.HTTPConnection(
                        target, timeout=timeout)
                except Exception as e:  # bad host string
                    raise ClientError(f"{method} http://{target}{path}: {e}")
            else:
                # Pooled sockets carry whatever timeout their LAST use
                # armed (possibly a tiny clamped budget); re-arm every
                # attempt — both to clamp to this request's budget and
                # to restore the default for deadline-free requests.
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
            sent = False
            t0 = time.perf_counter()
            try:
                if _fp.ACTIVE is not None:
                    _fp.ACTIVE.hit("rpc.send", host=target)
                conn.request(method, path, body=body, headers=headers or {})
                sent = True
                resp = conn.getresponse()
                data = resp.read()
                if _fp.ACTIVE is not None:
                    _fp.ACTIVE.hit("rpc.recv", host=target)
                if headers_out is not None:
                    headers_out.extend(resp.getheaders())
                if resp.will_close:
                    conn.close()
                else:
                    self._conn_put(target, conn)
                if self.fault is not None:
                    # Any completed HTTP exchange means the peer is
                    # alive, whatever the status code says.
                    self.fault.record_rpc(target, True,
                                          time.perf_counter() - t0)
                return resp.status, data
            except (http.client.HTTPException, OSError) as e:
                conn.close()
                last_err = e
                deadline_hit = (deadline is not None
                                and time.monotonic() >= deadline)
                if self.fault is not None and not (
                        deadline_hit and isinstance(e, TimeoutError)):
                    # A timeout that merely exhausted the CALLER'S
                    # clamped budget says more about the budget than
                    # the peer — a healthy 80 ms peer serving 50 ms
                    # deadlines must not trip its breaker. Refused/
                    # reset/torn responses are real peer failures
                    # whatever the budget; the breaker-probe loop
                    # classifies its own timeouts explicitly.
                    self.fault.record_rpc(target, False)
                if deadline_hit:
                    # The attempt consumed the rest of the budget (e.g.
                    # a stalled peer ate the clamped socket timeout):
                    # this is a deadline expiry, not a node failure.
                    raise QueryDeadlineError(
                        f"{method} http://{target}{path}: deadline"
                        f" exceeded (after {e})")
                if fresh:  # a fresh connection failing is a real error
                    break
                if sent and not idempotent:
                    # The request reached the wire on a pooled socket and
                    # only the response failed — the server may already
                    # have processed it. Re-POSTing would re-execute a
                    # non-idempotent write (e.g. /import op-log appends),
                    # so surface the error instead (urllib3 safe-retry
                    # policy).
                    break
            except BaseException:
                # Anything else that escapes mid-request — a deadline
                # raised from a hook, KeyboardInterrupt, an unexpected
                # protocol error — leaves the socket in an unknown
                # state: DROP it. A broken connection must never
                # return to the pool where _conn_get would hand it to
                # the next request (pool-poisoning).
                conn.close()
                raise
        # Unreachable host → ClientError so failover loops can catch
        # and try the next owner.
        raise ClientError(f"{method} http://{target}{path}: {last_err}")

    def _do_stream(self, path: str, host: Optional[str] = None,
                   headers: Optional[dict] = None) -> "_StreamReader":
        """GET on a dedicated (unpooled) connection, returning the
        response as a file-like the caller reads in chunks and closes —
        the streaming leg of backup (client.go:552-580 attaches the
        response body as an io.ReadCloser)."""
        target = host or self.host
        try:
            conn = http.client.HTTPConnection(target, timeout=self.timeout)
            conn.request("GET", path, headers=headers or {})
            resp = conn.getresponse()
        except (http.client.HTTPException, OSError) as e:
            raise ClientError(f"GET http://{target}{path}: {e}")
        return _StreamReader(resp, conn)

    def _ok(self, status: int, body: bytes, what: str) -> bytes:
        if status != 200:
            raise ClientError(
                f"{what}: invalid status: code={status},"
                f" err={body.decode(errors='replace').strip()}")
        return body

    # Import-lane 429 handling: base/cap of the capped exponential
    # backoff (full jitter), and the total-wait ceiling when no query
    # deadline bounds the retry loop.
    _RETRY_429_BASE = 0.25
    _RETRY_429_CAP = 8.0

    def _do_429(self, method: str, path: str, body, headers: dict,
                host: Optional[str]) -> tuple[int, bytes]:
        """_do for import legs, honoring admission control's 429 +
        Retry-After — and the disk-full degradation's 507 + Retry-After
        (PR-14 write-unready: the peer is SHEDDING WRITES while it
        reclaims space, exactly as transient as an admission shed; a
        mid-import ENOSPC on one peer used to fail the whole import
        instead of waiting it out) — with capped exponential backoff +
        full jitter instead of surfacing the first rejection. The loop
        is bounded by the calling query's remaining deadline budget
        when one is bound to this thread (sched.context), and by
        ``self.timeout`` of total sleep otherwise — an overloaded
        server sheds load; the client must neither hammer it nor wait
        forever."""
        ctx = sched_context.current()
        budget = ctx.remaining() if ctx is not None else None
        if budget is None:
            budget = self.timeout
        deadline = time.monotonic() + max(budget, 0.0)
        backoff = self._RETRY_429_BASE
        while True:
            headers_out: list = []
            status, raw = self._do(method, path, body, headers,
                                   host=host, headers_out=headers_out)
            if status not in (429, 507):
                if self.gens is not None:
                    # Import acks piggyback the touched fragments'
                    # generation tokens (same contract as query legs)
                    # — one parse site covers every import form.
                    for hk, hv in headers_out:
                        if (hk.lower()
                                == gens_mod.GENERATIONS_HEADER.lower()):
                            self.gens.apply_wire(host or self.host, hv)
                return status, raw
            retry_after = 0.0
            for hk, hv in headers_out:
                if hk.lower() == "retry-after":
                    try:
                        retry_after = float(hv)
                    except ValueError:
                        pass
            # Full jitter over the exponential window, floored at the
            # server's own hold estimate.
            wait = max(retry_after, random.uniform(0.0, backoff))
            backoff = min(backoff * 2.0, self._RETRY_429_CAP)
            remaining = deadline - time.monotonic()
            if wait >= remaining:
                # Out of budget: surface the rejection (the caller's
                # _ok turns it into the usual ClientError).
                return status, raw
            if ctx is not None:
                ctx.check()
            time.sleep(wait)

    # -- queries (client.go:216-269) -----------------------------------------

    # Marker the executor checks before passing lifecycle kwargs —
    # scripted test fakes without the kwargs keep the plain call shape.
    deadline_aware = True
    # Same idea for the generation-token kwarg (gens_out).
    generation_aware = True

    def execute_query(self, node, index: str, query: str,
                      slices: Optional[list[int]] = None,
                      remote: bool = True,
                      column_attrs: bool = False,
                      pod_local: bool = False,
                      deadline_s: Optional[float] = None,
                      query_id: Optional[str] = None,
                      gens_out: Optional[list] = None) -> list:
        """``deadline_s``/``query_id`` propagate the coordinator's
        REMAINING budget and query identity to the peer (sched wire
        contract: X-Pilosa-Deadline / X-Pilosa-Query-Id), and clamp
        this leg's socket timeouts + retry budget to the deadline.

        ``gens_out`` (a list) takes custody of the response's
        generation tokens as ``(peer, payload)`` pairs INSTEAD of
        applying them to ``self.gens`` — the hedged-read path applies
        only the winning leg's tokens, so a stale loser can never
        poison the coordinator generation map."""
        from ..server import codec
        body = codec.encode_query_request(query, slices,
                                          column_attrs=column_attrs,
                                          remote=remote)
        path = f"/index/{index}/query"
        if pod_local:  # pod-internal leg (parallel.pod)
            path += "?podLocal=true"
        headers = {"Content-Type": _PROTOBUF, "Accept": _PROTOBUF}
        if deadline_s is not None:
            headers["X-Pilosa-Deadline"] = f"{deadline_s:.6f}"
        if query_id:
            headers["X-Pilosa-Query-Id"] = query_id
        # Distributed tracing + cost accounting: when the calling
        # thread carries a lifecycle-bound query (the executor binds it
        # via sched_context.use), ask the peer to trace its leg and
        # stitch the spans AND the cost ledger it piggybacks on the
        # response headers back into the originating trace/cost tree.
        ctx = sched_context.current()
        trace = getattr(ctx, "trace", None) if ctx is not None else None
        cost = getattr(ctx, "cost", None) if ctx is not None else None
        plan = getattr(ctx, "plan", None) if ctx is not None else None
        # Tenant principal (sched.tenants, the X-Pilosa-Deadline
        # pattern): the remote leg schedules its device work, accounts
        # its costs, and enforces cost ceilings under the SAME tenant
        # as the coordinator — forwarded legs bypass admission, but
        # never the accounting.
        tenant = getattr(ctx, "tenant", "") if ctx is not None else ""
        if tenant:
            headers[sched_context.TENANT_HEADER] = tenant
        headers_out: Optional[list] = None
        if trace is not None:
            headers[TRACE_HEADER] = "1"
        if (trace is not None or cost is not None or plan is not None
                or self.gens is not None or gens_out is not None):
            headers_out = []
        target = _host_of(node) if node is not None else self.host
        status, raw = self._do(
            "POST", path, body, headers,
            host=_host_of(node) if node is not None else None,
            idempotent=True,  # PQL writes set absolute state — replayable
            deadline_s=deadline_s, headers_out=headers_out)
        if cost is not None:
            cost.note_rpc(target, len(body), len(raw))
        if headers_out:
            for hk, hv in headers_out:
                lk = hk.lower()
                if trace is not None and lk == SPANS_HEADER.lower():
                    trace.add_remote_json(hv)
                elif cost is not None and lk == COST_HEADER.lower():
                    cost.add_remote_json(hv)
                elif (plan is not None
                      and lk == plan_record.PLAN_HEADER.lower()):
                    plan.add_remote_json(hv)
                elif lk == gens_mod.GENERATIONS_HEADER.lower():
                    if gens_out is not None:
                        gens_out.append((target, hv))
                    elif self.gens is not None:
                        self.gens.apply_wire(target, hv)
        self._ok(status, raw, "execute query")
        resp = pb.QueryResponse.FromString(raw)
        if resp.Err:
            raise ClientError(resp.Err)
        call_names = [c.name for c in pql.parse(query).calls]
        return codec.decode_query_results(resp, call_names)

    def queries(self, host: Optional[str] = None) -> dict:
        """GET /debug/queries: this node's in-flight queries + slow
        log (sched.registry)."""
        status, raw = self._do("GET", "/debug/queries", host=host)
        return json.loads(self._ok(status, raw, "debug queries"))

    # -- fleet observability (obs.federate; docs/OBSERVABILITY.md) -----------

    def metrics_text(self, host: Optional[str] = None,
                     deadline_s: Optional[float] = None) -> str:
        """GET /metrics: one peer's Prometheus exposition — the
        federation scrape leg (/metrics/cluster). ``deadline_s`` is
        the per-peer scrape budget; the breaker consult in _do makes a
        dead peer fail this fast instead of paying the timeout."""
        status, raw = self._do("GET", "/metrics", host=host,
                               deadline_s=deadline_s)
        return self._ok(status, raw, "metrics scrape").decode()

    def debug_cluster_local(self, host: Optional[str] = None,
                            deadline_s: Optional[float] = None
                            ) -> dict:
        """GET /debug/cluster?local=1: one peer's local debug rollup
        block (build, epoch, breakers, SLO burn, WAL health, resize
        phase) — the /debug/cluster fan-out leg."""
        status, raw = self._do("GET", "/debug/cluster?local=1",
                               host=host, deadline_s=deadline_s)
        return json.loads(self._ok(status, raw, "debug cluster"))

    def metrics_history(self, family: str = "", label: str = "",
                        window: str = "", step: str = "",
                        host: Optional[str] = None,
                        deadline_s: Optional[float] = None) -> dict:
        """GET /debug/metrics/history: one peer's metric-history
        series — the scope=cluster federation leg."""
        from urllib.parse import urlencode
        params = {k: v for k, v in (("family", family),
                                    ("label", label),
                                    ("window", window),
                                    ("step", step)) if v}
        path = "/debug/metrics/history"
        if params:
            path += "?" + urlencode(params)
        status, raw = self._do("GET", path, host=host,
                               deadline_s=deadline_s)
        return json.loads(self._ok(status, raw, "metrics history"))

    def capture_records(self, since: int = 0, limit: int = 500,
                        host: Optional[str] = None,
                        deadline_s: Optional[float] = None) -> dict:
        """GET /debug/capture/records: one peer's local capture page
        (obs.capture) — the scope=cluster federation leg, and the
        replay driver's export transport."""
        from urllib.parse import urlencode
        path = ("/debug/capture/records?"
                + urlencode({"since": since, "limit": limit}))
        status, raw = self._do("GET", path, host=host,
                               deadline_s=deadline_s)
        return json.loads(self._ok(status, raw, "capture records"))

    def cancel_query(self, query_id: str,
                     host: Optional[str] = None) -> dict:
        """DELETE /debug/queries/{id}: cancel a query on this node;
        the node re-broadcasts the cancel cluster-wide."""
        status, raw = self._do("DELETE",
                               f"/debug/queries/{query_id}", host=host)
        return json.loads(self._ok(status, raw, "cancel query"))

    def generations(self, index: str,
                    slices: Optional[list[int]] = None,
                    host: Optional[str] = None,
                    deadline_s: Optional[float] = None) -> dict:
        """GET /generations: a peer's current per-fragment generation
        tokens for the given slices — the cheap validation round-trip
        the coordinator result cache pays instead of a full fan-out
        re-execution. The probe's answer also refreshes ``self.gens``
        (it is the freshest possible knowledge of that peer)."""
        path = f"/generations?index={index}"
        if slices:
            path += "&slices=" + ",".join(str(s) for s in slices)
        status, raw = self._do("GET", path, host=host,
                               deadline_s=deadline_s)
        data = json.loads(self._ok(status, raw, "generations"))
        tokens = gens_mod.decode_tokens(data.get("tokens") or {})
        if self.gens is not None and tokens:
            self.gens.apply(host or self.host, index, tokens)
        return tokens

    # -- schema / slices (client.go:63-136) ----------------------------------

    def schema(self) -> list[dict]:
        status, raw = self._do("GET", "/schema")
        return json.loads(self._ok(status, raw, "schema"))["indexes"]

    def max_slices(self, inverse: bool = False) -> dict[str, int]:
        path = "/slices/max" + ("?inverse=true" if inverse else "")
        status, raw = self._do("GET", path)
        return json.loads(self._ok(status, raw, "max slices"))["maxSlices"]

    def frame_views(self, index: str, frame: str) -> list[str]:
        status, raw = self._do("GET",
                               f"/index/{index}/frame/{frame}/views")
        return json.loads(self._ok(status, raw, "frame views"))\
            .get("views", [])

    def create_index(self, index: str, options: Optional[dict] = None
                     ) -> None:
        body = json.dumps({"options": options or {}}).encode()
        status, raw = self._do("POST", f"/index/{index}", body,
                               idempotent=True)
        if status not in (200, 409):
            self._ok(status, raw, "create index")

    def create_frame(self, index: str, frame: str,
                     options: Optional[dict] = None) -> None:
        body = json.dumps({"options": options or {}}).encode()
        status, raw = self._do("POST", f"/index/{index}/frame/{frame}",
                               body, idempotent=True)
        if status not in (200, 409):
            self._ok(status, raw, "create frame")

    # -- import (client.go:304-389) ------------------------------------------

    def fragment_nodes(self, index: str, slice: int) -> list[dict]:
        status, raw = self._do(
            "GET", f"/fragment/nodes?index={index}&slice={slice}")
        return json.loads(self._ok(status, raw, "fragment nodes"))

    def import_bits(self, index: str, frame: str, bits: list[Bit]) -> None:
        """Group by slice, then POST each group to EVERY owner node."""
        self.import_arrays(
            index, frame,
            np.fromiter((b.row_id for b in bits), dtype=np.uint64,
                        count=len(bits)),
            np.fromiter((b.column_id for b in bits), dtype=np.uint64,
                        count=len(bits)),
            np.fromiter((b.timestamp for b in bits), dtype=np.int64,
                        count=len(bits)))

    def _import_slice(self, index: str, frame: str, slice: int,
                      rows: np.ndarray, cols: np.ndarray,
                      ts: np.ndarray) -> None:
        # Raw-array wire format first (proto/rawimport.py — protobuf's
        # per-u64 varint decode was the measured wire bound), falling
        # back to protobuf per host on 415 so reference-shaped servers
        # keep working. All-zero timestamps stay off the wire in both
        # forms (the server treats absent as None).
        from ..proto import rawimport
        from ..utils.arrays import sort_dedupe
        from .. import SLICE_WIDTH
        raw_body = posn_body = pb_body = None
        # Timestamp-free blocks ride the presorted positions form
        # (rawimport v2): half the wire bytes, and the sort happens
        # HERE — np.sort releases the GIL, so encoding slice N+1
        # overlaps the server applying slice N on the concurrent
        # per-slice legs.
        use_posn = not ts.any() and (
            not len(rows) or int(rows.max()) < (1 << 43))
        nodes = self.fragment_nodes(index, slice)
        if not nodes:
            raise ClientError(f"no owner for slice {slice}")
        for node in nodes:
            host = node["host"]
            if host not in self._no_raw_import:
                posn = use_posn and host not in self._no_posn_import
                if posn:
                    if posn_body is None:
                        W = np.uint64(SLICE_WIDTH)
                        posn_body = rawimport.encode_positions(
                            index, frame, slice,
                            sort_dedupe(rows * W + cols % W))
                    body = posn_body
                elif raw_body is None:
                    raw_body = body = rawimport.encode(
                        index, frame, slice, rows, cols,
                        ts if ts.any() else None)
                else:
                    body = raw_body
                status, raw = self._do_429(
                    "POST", "/import", body,
                    {"Content-Type": rawimport.CONTENT_TYPE,
                     "Accept": _PROTOBUF}, host)
                if posn and status == 400 and b"version" in raw:
                    # Pre-v2 raw server: drop to the v1 pair form for
                    # this host (same negotiation idiom as the 415
                    # protobuf fallback below).
                    self._no_posn_import.add(host)
                    if raw_body is None:
                        raw_body = rawimport.encode(
                            index, frame, slice, rows, cols,
                            ts if ts.any() else None)
                    status, raw = self._do_429(
                        "POST", "/import", raw_body,
                        {"Content-Type": rawimport.CONTENT_TYPE,
                         "Accept": _PROTOBUF}, host)
                if status != 415:
                    self._ok(status, raw, f"import slice {slice}")
                    resp = pb.ImportResponse.FromString(raw)
                    if resp.Err:
                        raise ClientError(resp.Err)
                    continue
                self._no_raw_import.add(host)
            if pb_body is None:
                pb_body = pb.ImportRequest(
                    Index=index, Frame=frame, Slice=slice,
                    RowIDs=rows.tolist(), ColumnIDs=cols.tolist(),
                    Timestamps=ts.tolist() if ts.any() else []
                ).SerializeToString()
            status, raw = self._do_429(
                "POST", "/import", pb_body,
                {"Content-Type": _PROTOBUF, "Accept": _PROTOBUF},
                host)
            self._ok(status, raw, f"import slice {slice}")
            resp = pb.ImportResponse.FromString(raw)
            if resp.Err:
                raise ClientError(resp.Err)

    def _import_slice_positions(self, index: str, frame: str,
                                slice: int,
                                positions: np.ndarray) -> None:
        """POST one slice's PRESORTED slice-local positions to every
        owner (rawimport v2). Fallbacks mirror _import_slice's
        per-host negotiation: a pre-v2 raw server (400 "version")
        gets the v1 pair form, a reference-shaped server (415) gets
        protobuf — both reconstructed from the positions with three
        vector ops."""
        from ..proto import rawimport
        from .. import SLICE_WIDTH
        W = np.uint64(SLICE_WIDTH)
        body = rawimport.encode_positions(index, frame, slice,
                                          positions)
        rows = cols = pb_body = raw_body = None
        nodes = self.fragment_nodes(index, slice)
        if not nodes:
            raise ClientError(f"no owner for slice {slice}")

        def pairs():
            nonlocal rows, cols
            if rows is None:
                rows = positions // W
                cols = np.uint64(slice) * W + (positions % W)
            return rows, cols

        for node in nodes:
            host = node["host"]
            if host not in self._no_raw_import:
                if host not in self._no_posn_import:
                    status, raw = self._do_429(
                        "POST", "/import", body,
                        {"Content-Type": rawimport.CONTENT_TYPE,
                         "Accept": _PROTOBUF}, host)
                    if not (status == 400 and b"version" in raw):
                        if status != 415:
                            self._ok(status, raw,
                                     f"import slice {slice}")
                            resp = pb.ImportResponse.FromString(raw)
                            if resp.Err:
                                raise ClientError(resp.Err)
                            continue
                        self._no_raw_import.add(host)
                    else:
                        self._no_posn_import.add(host)
                if host not in self._no_raw_import:
                    if raw_body is None:
                        r, c = pairs()
                        raw_body = rawimport.encode(
                            index, frame, slice, r, c, None)
                    status, raw = self._do_429(
                        "POST", "/import", raw_body,
                        {"Content-Type": rawimport.CONTENT_TYPE,
                         "Accept": _PROTOBUF}, host)
                    if status != 415:
                        self._ok(status, raw, f"import slice {slice}")
                        resp = pb.ImportResponse.FromString(raw)
                        if resp.Err:
                            raise ClientError(resp.Err)
                        continue
                    self._no_raw_import.add(host)
            if pb_body is None:
                r, c = pairs()
                pb_body = pb.ImportRequest(
                    Index=index, Frame=frame, Slice=slice,
                    RowIDs=r.tolist(), ColumnIDs=c.tolist(),
                    Timestamps=[]).SerializeToString()
            status, raw = self._do_429(
                "POST", "/import", pb_body,
                {"Content-Type": _PROTOBUF, "Accept": _PROTOBUF},
                host)
            self._ok(status, raw, f"import slice {slice}")
            resp = pb.ImportResponse.FromString(raw)
            if resp.Err:
                raise ClientError(resp.Err)

    def import_arrays(self, index: str, frame: str, row_ids, column_ids,
                      timestamps=None) -> None:
        """Array-native import: group by slice with one stable argsort
        (the vector form of Bits.GroupBySlice, client.go:1027-1040) and
        POST each slice's block to every owner."""
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(column_ids, dtype=np.uint64)
        ts = (np.zeros(len(rows), dtype=np.int64) if timestamps is None
              else np.asarray(timestamps, dtype=np.int64))
        if not len(rows):
            return
        from ..utils.arrays import sort_dedupe
        W = np.uint64(SLICE_WIDTH)
        slices_a = cols // W
        if (not ts.any() and int(rows.max()) < (1 << 24)
                and int(slices_a.max()) < (1 << 20)):
            # Timestamp-free fast lane (the bulk-load shape): pack
            # (slice, position) into one u64 — the same idiom as
            # frame.put_arrays — so ONE sort_dedupe (np.sort releases
            # the GIL) orders and dedupes every slice's span at once,
            # and each span ships PRESORTED as a rawimport-v2
            # positions body: no per-slice re-sort here, none on the
            # server (add_many's is-sorted check passes), half the
            # wire bytes of the (rows, cols) pair form.
            packed = sort_dedupe((slices_a << np.uint64(44))
                                 | (rows * W + cols % W))
            sl = packed >> np.uint64(44)
            b = np.flatnonzero(sl[1:] != sl[:-1]) + 1
            mask = np.uint64((1 << 44) - 1)
            jobs = [(self._import_slice_positions,
                     (index, frame, int(sl[s]), packed[s:e] & mask))
                    for s, e in zip(
                        np.concatenate(([0], b)).tolist(),
                        np.concatenate((b, [len(sl)])).tolist())]
            if len(jobs) == 1:
                fn, args = jobs[0]
                fn(*args)
            else:
                self._import_by_host(index, jobs)
            return
        groups = list(group_by_key(slices_a, rows, cols, ts))
        if len(groups) == 1:
            slice, rs, cs, tss = groups[0]
            self._import_slice(index, frame, slice, rs, cs, tss)
            return
        # Per-slice blocks go to different owners: POST them
        # concurrently (client.go imports slices on goroutines), so one
        # slice's server-side apply overlaps the next one's encode and
        # transfer. First failure wins; the reference surfaces one
        # error the same way.
        self._parallel_slices(
            [(self._import_slice, (index, frame, slice, rs, cs, tss))
             for slice, rs, cs, tss in groups])

    def _import_by_host(self, index: str, jobs: list[tuple]) -> None:
        """Host-aware scheduling for per-slice import legs: slices
        whose primary owner is the SAME node post sequentially —
        concurrent same-host posts convoy on the GIL (client encode
        and the server's decode/apply share cores; measured 23%
        slower at 4-way fan-out than queued posts) — while distinct
        nodes still fan out in parallel, where the concurrency is
        real. Grouping is by first owner only (a scheduling choice;
        each leg still posts to every owner itself)."""
        groups: dict[str, list] = {}
        for fn, args in jobs:
            nodes = self.fragment_nodes(index, args[2])
            key = nodes[0]["host"] if nodes else ""
            groups.setdefault(key, []).append((fn, args))
        if len(groups) == 1:
            for fn, args in jobs:
                fn(*args)
            return

        def run_group(group: list) -> None:
            for fn, args in group:
                fn(*args)

        self._parallel_slices(
            [(run_group, (g,)) for g in groups.values()])

    def _parallel_slices(self, jobs: list[tuple]) -> None:
        """Run per-slice import legs concurrently; on the first error,
        cancel legs that have not started so a failed import bounds its
        partial writes to the in-flight slices (ADVICE r5 #2) — the
        import API stays at-least-once either way."""
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(4, len(jobs))) as tp:
            futs = [tp.submit(fn, *args) for fn, args in jobs]
            try:
                for f in futs:
                    f.result()
            except BaseException:
                tp.shutdown(wait=False, cancel_futures=True)
                raise

    # -- BSI field values ----------------------------------------------------

    def create_field(self, index: str, frame: str, field: str,
                     min: int = 0, max: int = 0) -> None:
        body = json.dumps({"min": min, "max": max}).encode()
        status, raw = self._do(
            "POST", f"/index/{index}/frame/{frame}/field/{field}", body,
            idempotent=True)
        if status not in (200, 409):
            self._ok(status, raw, "create field")

    def field_import_slice(self, index: str, frame: str, field: str,
                           slice: int, cols: np.ndarray,
                           vals: np.ndarray) -> None:
        """POST one slice's ImportValueRequest to EVERY owner node."""
        body = pb.ImportValueRequest(
            Index=index, Frame=frame, Field=field, Slice=slice,
            ColumnIDs=cols.tolist(),
            Values=vals.tolist()).SerializeToString()
        nodes = self.fragment_nodes(index, slice)
        if not nodes:
            raise ClientError(f"no owner for slice {slice}")
        for node in nodes:
            status, raw = self._do_429(
                "POST", f"/index/{index}/frame/{frame}/field/{field}"
                        f"/import", body,
                {"Content-Type": _PROTOBUF, "Accept": _PROTOBUF},
                node["host"])
            self._ok(status, raw, f"import field slice {slice}")
            resp = pb.ImportResponse.FromString(raw)
            if resp.Err:
                raise ClientError(resp.Err)

    def import_field_values(self, index: str, frame: str, field: str,
                            column_ids, values) -> None:
        """Bulk integer-field import: group (column, value) pairs by
        slice and fan each group out to its owner nodes — the
        ImportValue analogue of import_arrays."""
        cols = np.asarray(column_ids, dtype=np.uint64)
        vals = np.asarray(values, dtype=np.int64)
        if len(cols) != len(vals):
            raise ClientError("column/value length mismatch")
        if not len(cols):
            return
        groups = list(group_by_key(cols // np.uint64(SLICE_WIDTH),
                                   cols, vals))
        if len(groups) == 1:
            slice, cs, vs = groups[0]
            self.field_import_slice(index, frame, field, slice, cs, vs)
            return
        self._parallel_slices(
            [(self.field_import_slice, (index, frame, field, slice,
                                        cs, vs))
             for slice, cs, vs in groups])

    # -- export (client.go:392-460) ------------------------------------------

    def export_csv_to(self, w, index: str, frame: str, view: str,
                      slice: int) -> None:
        """Stream one slice's CSV into text writer ``w``, trying each
        owner until one succeeds (client.go:392-460 streams through
        io.Copy; whole-slice CSV is too big to buffer). The download
        spools through a bounded temp file so an owner dying mid-body
        fails over without having written partial rows to ``w``."""
        import shutil
        import tempfile
        nodes = self.fragment_nodes(index, slice)
        random.shuffle(nodes)
        last_err = None
        for node in nodes:
            try:
                rd = self._do_stream(
                    f"/export?index={index}&frame={frame}&view={view}"
                    f"&slice={slice}", host=node["host"],
                    headers={"Accept": "text/csv"})
            except ClientError as e:
                last_err = e
                continue
            try:
                if rd.status != 200:
                    last_err = ClientError(f"export: status={rd.status}")
                    continue
                with tempfile.SpooledTemporaryFile(
                        max_size=self._SPOOL_MAX) as spool:
                    try:
                        shutil.copyfileobj(rd, spool, 1 << 20)
                    except (http.client.HTTPException, OSError) as e:
                        last_err = ClientError(
                            f"export from {node['host']}: {e}")
                        continue
                    spool.seek(0)
                    while True:
                        chunk = spool.read(1 << 20)
                        if not chunk:
                            return
                        w.write(chunk.decode())
            finally:
                rd.close()
        raise last_err or ClientError("no nodes")

    def export_csv(self, index: str, frame: str, view: str, slice: int
                   ) -> str:
        """Buffered convenience form of export_csv_to."""
        import io as _io
        buf = _io.StringIO()
        self.export_csv_to(buf, index, frame, view, slice)
        return buf.getvalue()

    # -- anti-entropy (client.go:798-974) ------------------------------------

    def fragment_blocks(self, index: str, frame: str, view: str,
                        slice: int, host: Optional[str] = None
                        ) -> list[tuple[int, bytes]]:
        from ..server import codec
        status, raw = self._do(
            "GET", f"/fragment/blocks?index={index}&frame={frame}"
                   f"&view={view}&slice={slice}", host=host)
        if status == 404:
            raise FragmentNotFoundError()
        return codec.blocks_from_json(
            json.loads(self._ok(status, raw, "fragment blocks"))
            .get("blocks") or [])

    def block_data(self, index: str, frame: str, view: str, slice: int,
                   block: int, host: Optional[str] = None
                   ) -> tuple[np.ndarray, np.ndarray]:
        req = pb.BlockDataRequest(Index=index, Frame=frame, View=view,
                                  Slice=slice, Block=block)
        status, raw = self._do(
            "GET", "/fragment/block/data", req.SerializeToString(),
            {"Content-Type": _PROTOBUF, "Accept": _PROTOBUF}, host=host)
        self._ok(status, raw, "block data")
        resp = pb.BlockDataResponse.FromString(raw)
        return (np.array(resp.RowIDs, dtype=np.uint64),
                np.array(resp.ColumnIDs, dtype=np.uint64))

    def fragment_import(self, index: str, frame: str, view: str,
                        slice: int, positions: np.ndarray,
                        host: Optional[str] = None) -> None:
        """Additive per-fragment import of slice-local bit positions
        (row*SLICE_WIDTH + col%SLICE_WIDTH) — the resize streamer's
        push lane (POST /fragment/import): unlike the /fragment/data
        restore it never replaces content (concurrent double-writes
        land between a diff read and this push), and unlike /import it
        applies to the EXACT (frame, view) fragment so time and
        inverse views migrate byte-faithfully. Idempotent (re-adding
        set bits is a no-op), so torn streams re-push safely."""
        body = np.asarray(positions, dtype="<u8").tobytes()
        status, raw = self._do(
            "POST", f"/fragment/import?index={index}&frame={frame}"
                    f"&view={view}&slice={slice}", body,
            {"Content-Type": "application/octet-stream"}, host=host,
            idempotent=True)
        if status == 404:
            raise FragmentNotFoundError()
        self._ok(status, raw, "fragment import")

    def post_message(self, data: bytes,
                     host: Optional[str] = None,
                     deadline_s: Optional[float] = None) -> None:
        """POST one marshaled broadcast envelope to a node's
        /messages — the resize coordinator's DIRECT, acked control
        sends (a 200 is the node's ack; any failure raises)."""
        status, raw = self._do(
            "POST", "/messages", data,
            {"Content-Type": "application/x-protobuf"}, host=host,
            idempotent=True, deadline_s=deadline_s)
        self._ok(status, raw, "post message")

    def column_attr_diff(self, index: str, blocks: list[tuple[int, bytes]],
                         host: Optional[str] = None) -> dict[int, dict]:
        return self._attr_diff(f"/index/{index}/attr/diff", blocks, host)

    def row_attr_diff(self, index: str, frame: str,
                      blocks: list[tuple[int, bytes]],
                      host: Optional[str] = None) -> dict[int, dict]:
        return self._attr_diff(f"/index/{index}/frame/{frame}/attr/diff",
                               blocks, host)

    def _attr_diff(self, path: str, blocks, host) -> dict[int, dict]:
        from ..server import codec
        body = json.dumps({"blocks": codec.blocks_to_json(blocks)}).encode()
        status, raw = self._do("POST", path, body, host=host,
                               idempotent=True)  # pure read
        if status == 404:
            raise FragmentNotFoundError()
        attrs = json.loads(self._ok(status, raw, "attr diff"))["attrs"]
        return {int(k): v for k, v in attrs.items()}

    # -- backup / restore (client.go:463-674) --------------------------------

    # Spool cap: slices smaller than this stay in memory; larger ones
    # roll to a temp file, so a 128 MB+ slice never sits in RAM whole.
    _SPOOL_MAX = 1 << 24

    def backup_slice(self, index: str, frame: str, view: str, slice: int,
                     snapshot: bool = False):
        """One slice's fragment tar as a seekable bounded spool (the
        caller closes it); None if the slice doesn't exist yet
        (client.go:541-580). The body downloads inside the per-owner
        loop so a node dying mid-transfer fails over to a replica.
        ``snapshot=True`` asks the owner to fold its WAL into a fresh
        footered snapshot first (the backup coordinator's barrier)."""
        import shutil
        import tempfile
        nodes = self.fragment_nodes(index, slice)
        random.shuffle(nodes)
        snap = "&snapshot=1" if snapshot else ""
        last_err: Optional[Exception] = None
        for node in nodes:
            try:
                rd = self._do_stream(
                    f"/fragment/data?index={index}&frame={frame}"
                    f"&view={view}&slice={slice}{snap}",
                    host=node["host"])
            except ClientError as e:
                last_err = e
                continue
            try:
                if rd.status == 404:
                    return None
                if rd.status != 200:
                    last_err = ClientError(
                        f"backup slice: status={rd.status}")
                    continue
                spool = tempfile.SpooledTemporaryFile(
                    max_size=self._SPOOL_MAX)
                try:
                    shutil.copyfileobj(rd, spool, 1 << 20)
                except (http.client.HTTPException, OSError) as e:
                    spool.close()
                    last_err = ClientError(
                        f"backup slice from {node['host']}: {e}")
                    continue
                spool.seek(0)
                return spool
            finally:
                rd.close()
        if last_err:
            raise last_err
        return None

    def restore_slice(self, index: str, frame: str, view: str, slice: int,
                      data) -> None:
        """POST one slice tar (bytes or a sized file-like) to this host."""
        headers = {"Content-Type": "application/octet-stream"}
        if not isinstance(data, bytes):
            # An explicit length keeps http.client from chunking, which
            # the WSGI server does not decode.
            pos = data.tell()
            data.seek(0, 2)
            headers["Content-Length"] = str(data.tell() - pos)
            data.seek(pos)
        status, raw = self._do(
            "POST", f"/fragment/data?index={index}&frame={frame}"
                    f"&view={view}&slice={slice}", data, headers)
        self._ok(status, raw, "restore slice")

    def backup_to(self, w, index: str, frame: str, view: str) -> None:
        """Stream every slice of (index, frame, view) into a tar whose
        entries are named by slice id (client.go:463-529). Slices spool
        through bounded temp files (tar headers need the size upfront);
        peak memory stays at the spool cap, not the slice size."""
        import tarfile
        tw = tarfile.open(fileobj=w, mode="w|")
        max_slice = self.max_slices().get(index, 0)
        for slice in range(max_slice + 1):
            spool = self.backup_slice(index, frame, view, slice)
            if spool is None:
                continue
            with spool:
                spool.seek(0, 2)
                size = spool.tell()
                spool.seek(0)
                info = tarfile.TarInfo(str(slice))
                info.size = size
                info.mode = 0o666
                tw.addfile(info, spool)
        tw.close()

    def restore_from(self, r, index: str, frame: str, view: str) -> None:
        """Restore a backup_to tar: push each slice entry to every owner
        (client.go:583-674). Entries spool through a bounded temp file
        (each goes to multiple owners, so the source must be re-readable)
        and POST as streaming bodies."""
        import shutil
        import tarfile
        import tempfile
        tr = tarfile.open(fileobj=r, mode="r|")
        for info in tr:
            if not info.name.isdigit():
                raise ClientError(f"invalid backup entry: {info.name}")
            slice = int(info.name)
            src = tr.extractfile(info)
            with tempfile.SpooledTemporaryFile(
                    max_size=self._SPOOL_MAX) as spool:
                shutil.copyfileobj(src, spool, 1 << 20)
                for node in self.fragment_nodes(index, slice):
                    spool.seek(0)
                    status, raw = self._do(
                        "POST", f"/fragment/data?index={index}"
                                f"&frame={frame}&view={view}"
                                f"&slice={slice}", spool,
                        {"Content-Type": "application/octet-stream",
                         "Content-Length": str(info.size)},
                        host=node["host"])
                    self._ok(status, raw, f"restore slice {slice}")

    def restore_frame(self, host: str, index: str, frame: str) -> None:
        """Ask this node to pull a frame from a remote cluster host
        (client.go:677-695 → POST /index/{i}/frame/{f}/restore)."""
        status, raw = self._do(
            "POST", f"/index/{index}/frame/{frame}/restore?host={host}",
            b"")
        self._ok(status, raw, "restore frame")
