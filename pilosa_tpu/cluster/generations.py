"""Mutation-generation tokens: cluster-wide cache-validity facts.

The single-node fast paths (result residency, the fused device count
fold, single-pass TopN) key every cached artifact by the backing
fragments' ``(uid, generation)`` pairs (parallel.residency): writes
bump the generation, reopen mints a fresh uid, and stale entries
simply stop being referenced. That staleness contract was *local* —
a slice owned by another node had an invisible generation, so
ownership-gated paths fell back to the slow fan-out the moment a
query touched a remote slice (ROADMAP item 3 / VERDICT r5 #4).

This module makes generations a *cluster-wide* fact:

- **Tokens**: per-fragment ``(uid, generation)`` pairs, grouped per
  slice as ``{"<frame>/<view>": [uid, gen]}`` dicts. uids are
  process-local counters, so a token is only meaningful relative to
  the peer that minted it — every consumer keys by ``(peer, uid,
  gen)``, never by the bare pair.
- **Wire**: serving nodes piggyback their current tokens for the
  served slices on internal query responses and import acks as the
  ``X-Pilosa-Generations`` header (the X-Pilosa-Cost /
  X-Pilosa-Trace-Spans stitching pattern), and answer the cheap
  ``GET /generations`` probe — the validation round-trip the
  coordinator result cache rides.
- **GenerationMap**: the coordinator-side per-peer map. Entries carry
  a monotonic receive timestamp; reads specify a staleness bound and
  get ``None`` past it, so a consumer can choose between
  bounded-staleness keying (executor._bitmap_result_key: serve from
  cache while the map is fresh) and round-trip validation (the
  cluster result cache: probe /generations and compare before
  serving).

Invalidation is by mismatch, not callbacks: a write to any replica
bumps that replica's generations, the next exchange with it (query
response, import ack, or probe) carries the new tokens, and every
cached artifact keyed by the old tokens stops matching.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

# Internal response header carrying the serving node's tokens for the
# slices it served (query legs and import acks).
GENERATIONS_HEADER = "X-Pilosa-Generations"

# Caps on one wire payload: fragment entries AND encoded bytes (the
# byte budget is the binding one — http.client rejects header LINES
# over 64 KiB, same rationale as the trace/cost 48 KiB budgets). Past
# either, whole slices are dropped (never a partial slice — a consumer
# either sees a slice's complete token dict or nothing) and the
# payload is marked truncated.
MAX_WIRE_FRAGMENTS = 4096
MAX_WIRE_BYTES = 48 << 10

# Default staleness bound (seconds) for map reads that do NOT pay a
# validation round-trip. Writes routed through this coordinator refresh
# the map on their own response, so the bound only governs writes that
# bypassed it (another coordinator, a direct client).
DEFAULT_STALENESS_S = 2.0


def frag_key(frame: str, view: str) -> str:
    return f"{frame}/{view}"


def slice_tokens(holder, index: str, slice: int) -> dict:
    """This node's current ``{frame/view: (uid, gen)}`` dict for one
    slice — every open fragment of every frame/view at that slice.
    Empty dict = no fragments there (a valid, comparable state)."""
    idx = holder.index(index)
    if idx is None:
        return {}
    out: dict[str, tuple[int, int]] = {}
    for fname in sorted(idx.frames):
        frame = idx.frames[fname]
        for vname in sorted(frame.views):
            frag = frame.views[vname].fragments.get(slice)
            if frag is not None:
                dev = frag.device
                out[frag_key(fname, vname)] = (dev.uid, dev.generation)
    return out


def local_tokens(holder, index: str, slices) -> dict:
    """``{slice: {frame/view: (uid, gen)}}`` for the given slices."""
    return {int(s): slice_tokens(holder, index, int(s)) for s in slices}


def encode_wire(index: str, tokens: dict,
                max_fragments: int = MAX_WIRE_FRAGMENTS,
                max_bytes: int = MAX_WIRE_BYTES) -> str:
    """Compact JSON for the header / probe body. ``tokens`` is the
    local_tokens shape. Slices are included whole, in ascending order,
    until the fragment cap OR the byte budget (whichever binds —
    header lines over 64 KiB would fail the very response carrying
    them); the rest are dropped and ``x`` marks the truncation
    (consumers treat absent slices as unknown, never as empty)."""
    t: dict = {}
    n = 0
    # Envelope + truncation marker overhead, counted up front so the
    # budget bounds the FINAL encoded size.
    size = len(json.dumps({"i": index, "t": {}, "x": 1},
                          separators=(",", ":")))
    truncated = False
    for s in sorted(tokens):
        m = tokens[s]
        chunk = json.dumps(
            {str(s): {k: [v[0], v[1]] for k, v in m.items()}},
            separators=(",", ":"))
        cost = len(chunk) - 1  # minus braces, plus the joining comma
        if size + cost > max_bytes:
            # Byte budget binds even for the FIRST slice: an
            # over-64KiB header line would fail the whole response.
            truncated = True
            break
        if t and n + len(m) > max_fragments:
            truncated = True
            break
        n += len(m)
        size += cost
        t[str(s)] = {k: [v[0], v[1]] for k, v in m.items()}
    out = {"i": index, "t": t}
    if truncated:
        out["x"] = 1
    return json.dumps(out, separators=(",", ":"))


def decode_wire(payload: str):
    """(index, {slice: {frag_key: (uid, gen)}}) or None on garbage —
    a malformed header must never fail the query that carried it."""
    try:
        data = json.loads(payload)
        index = data["i"]
        tokens = {}
        for s, m in (data.get("t") or {}).items():
            tokens[int(s)] = {str(k): (int(v[0]), int(v[1]))
                              for k, v in m.items()}
    except (ValueError, KeyError, TypeError, IndexError):
        return None
    if not isinstance(index, str):
        return None
    return index, tokens


def decode_tokens(raw: dict) -> dict:
    """The /generations probe's ``tokens`` object → the local_tokens
    shape (lenient: bad entries dropped, not raised)."""
    out: dict = {}
    for s, m in (raw or {}).items():
        try:
            out[int(s)] = {str(k): (int(v[0]), int(v[1]))
                           for k, v in m.items()}
        except (ValueError, TypeError, KeyError, IndexError):
            continue
    return out


class GenerationMap:
    """Coordinator-side per-peer generation knowledge.

    ``apply(peer, index, tokens)`` records a peer's tokens (from a
    response header or a probe) with a monotonic timestamp; readers
    pass a staleness bound and get None past it. Thread-safe; bounded
    per peer (oldest slices evicted beyond ``max_slices_per_peer``).
    """

    def __init__(self, staleness_s: float = DEFAULT_STALENESS_S,
                 max_slices_per_peer: int = 65536):
        self.staleness_s = staleness_s
        self.max_slices_per_peer = max_slices_per_peer
        self._mu = threading.Lock()
        # peer -> (index, slice) -> (tokens dict, monotonic ts)
        self._peers: dict[str, dict[tuple, tuple]] = {}

    def apply(self, peer: str, index: str, tokens: dict) -> int:
        """Record ``{slice: {frag_key: (uid, gen)}}`` for a peer;
        returns the number of slice entries applied."""
        if not peer or not tokens:
            return 0
        now = time.monotonic()
        with self._mu:
            m = self._peers.setdefault(peer, {})
            for s, toks in tokens.items():
                m[(index, int(s))] = (dict(toks), now)
            if len(m) > self.max_slices_per_peer:
                # Rare; evict oldest entries wholesale.
                drop = sorted(m.items(), key=lambda kv: kv[1][1])
                for k, _ in drop[:len(m) - self.max_slices_per_peer]:
                    del m[k]
        try:
            from ..obs import metrics as obs_metrics
            obs_metrics.GENERATION_UPDATES.labels(peer).inc(len(tokens))
        except Exception:  # noqa: BLE001 - accounting never fails a query
            pass
        return len(tokens)

    def apply_wire(self, peer: str, payload: str) -> int:
        """Record a piggybacked GENERATIONS_HEADER payload."""
        decoded = decode_wire(payload)
        if decoded is None:
            return 0
        index, tokens = decoded
        return self.apply(peer, index, tokens)

    def tokens(self, peer: str, index: str, slice: int,
               max_age_s: Optional[float] = None) -> Optional[dict]:
        """The freshest-known token dict for (peer, index, slice), or
        None when unknown or older than the staleness bound."""
        if max_age_s is None:
            max_age_s = self.staleness_s
        with self._mu:
            m = self._peers.get(peer)
            ent = m.get((index, slice)) if m else None
        if ent is None:
            return None
        toks, ts = ent
        if time.monotonic() - ts > max_age_s:
            return None
        return toks

    def token(self, peer: str, index: str, frame: str, view: str,
              slice: int,
              max_age_s: Optional[float] = None) -> Optional[tuple]:
        """One fragment's (uid, gen) at a peer, or None when the slice
        is unknown/stale. An absent fragment in a KNOWN slice reads as
        (0, 0) — same identity the local key path uses for absent
        fragments, and distinguishable from "unknown"."""
        toks = self.tokens(peer, index, slice, max_age_s=max_age_s)
        if toks is None:
            return None
        return toks.get(frag_key(frame, view), (0, 0))

    def newest(self, index: str, slice: int,
               min_ts: Optional[float] = None):
        """(peer, tokens, ts) with the newest knowledge of (index,
        slice) across peers, or None. ``min_ts`` filters to entries at
        least that fresh (the cluster result cache only snapshots
        tokens refreshed by the query being cached)."""
        best = None
        with self._mu:
            for peer, m in self._peers.items():
                ent = m.get((index, slice))
                if ent is None:
                    continue
                toks, ts = ent
                if min_ts is not None and ts < min_ts:
                    continue
                if best is None or ts > best[2]:
                    best = (peer, toks, ts)
        return best

    def snapshot(self) -> dict:
        with self._mu:
            return {"peers": len(self._peers),
                    "entries": sum(len(m)
                                   for m in self._peers.values()),
                    "stalenessS": self.staleness_s}
