"""Gossip membership backend: SWIM-lite memberlist equivalent.

Reference: gossip/gossip.go. There, ``GossipNodeSet`` is simultaneously a
NodeSet, Broadcaster, BroadcastReceiver, and memberlist.Delegate
(gossip.go:31-45): sync sends go direct-TCP to every member
(gossip.go:124-149), async sends ride a retransmit-limited gossip queue
(gossip.go:152-164), and full-state push/pull anti-entropy exchanges a
protobuf ``NodeStatus`` carrying schema + owned slices (gossip.go:193-222,
status built at server.go:306-323).

hashicorp/memberlist is Go-only, so this module implements the same
behavior directly on sockets — a deliberately small SWIM variant:

- **UDP** carries probes (ping/ack), piggybacked membership updates
  (alive/dead rumors with incarnation numbers), and piggybacked broadcast
  envelopes with a retransmit budget of ``retransmit_mult*ceil(log2(n+1))``
  (memberlist's TransmitLimitedQueue policy).
- **TCP** carries sync broadcasts (one frame per connection) and the
  push/pull full-state exchange used for join and periodic anti-entropy.
- Failure detection (full SWIM, memberlist semantics gossip.go:48-54):
  a member that misses ``suspect_after`` consecutive direct probes is
  probed INDIRECTLY through ``indirect_probes`` random relays (ping-req);
  only if no relay can reach it either is it marked *suspect* — a state
  gossiped like dead but reversible: the suspect hears the rumor and
  refutes with a higher incarnation within ``suspect_timeout``, or the
  window expires and the member is declared dead. An asymmetric or lossy
  direct path therefore cannot kill a node other peers still reach.
- Optional shared-key auth: with ``secret_key`` set, every UDP datagram
  and TCP frame carries an HMAC-SHA256 tag; unauthenticated or tampered
  frames are dropped before parsing (memberlist encrypts with its
  SecretKey; this build authenticates, which is the property the
  membership layer needs — a spoofed packet must not poison the view).

Membership stays a host-side CPU concern in the TPU build — it is
metadata over DCN; only bitmap reductions ride ICI (parallel.mesh).
"""

from __future__ import annotations

import base64
import hmac as hmac_mod
import hashlib
import json
import math
import random
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..fault import failpoints as _fp
from ..utils import logger as logger_mod
from .broadcast import marshal_message, unmarshal_message
from .topology import Node

DEFAULT_GOSSIP_PORT = 14000      # reference internal/gossip port default

STATE_ALIVE = "alive"
STATE_SUSPECT = "suspect"
STATE_DEAD = "dead"

# Merge precedence at equal incarnation (memberlist: dead beats suspect
# beats alive; an alive claim only un-suspects with a HIGHER incarnation).
_STATE_RANK = {STATE_ALIVE: 0, STATE_SUSPECT: 1, STATE_DEAD: 2}

_HMAC_TAG = b"PGS1"     # sealed-frame magic
_HMAC_TS_TAG = b"PGS2"  # sealed frame with replay-bound timestamp
_HMAC_LEN = 32


@dataclass
class Member:
    name: str                    # cluster identity: the node's HTTP host
    addr: str                    # gossip "host:port"
    incarnation: int = 0
    state: str = STATE_ALIVE
    fails: int = field(default=0, compare=False)
    suspect_at: float = field(default=0.0, compare=False)

    def to_wire(self) -> dict:
        return {"name": self.name, "addr": self.addr,
                "inc": self.incarnation, "state": self.state}

    @classmethod
    def from_wire(cls, d: dict) -> "Member":
        return cls(d["name"], d["addr"], int(d["inc"]), d["state"])


def _split_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "localhost", int(port)


def _recv_frame(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("short frame header")
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            raise ConnectionError("short frame body")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(struct.pack(">I", len(data)) + data)


class GossipNodeSet:
    """NodeSet + Broadcaster + BroadcastReceiver over SWIM-lite gossip.

    Mirrors gossip.go:31-243. ``host`` is the node's HTTP host (its
    cluster identity, like memberlist's node Name); ``gossip_host`` is the
    UDP/TCP bind for the membership protocol; ``seeds`` are peers'
    gossip addresses contacted on open (gossip.go:63-86 join).
    """

    def __init__(self, host: str, gossip_host: str = "",
                 seeds: Optional[list[str]] = None,
                 probe_interval: float = 1.0, probe_timeout: float = 0.5,
                 push_pull_interval: float = 15.0, suspect_after: int = 3,
                 retransmit_mult: int = 3, indirect_probes: int = 3,
                 suspect_timeout: Optional[float] = None,
                 secret_key: Optional[bytes] = None,
                 replay_window: Optional[float] = None,
                 logger=logger_mod.NOP):
        self.host = host
        self.logger = logger
        self.gossip_host = gossip_host or f"localhost:{DEFAULT_GOSSIP_PORT}"
        self.seeds = list(seeds or [])
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.push_pull_interval = push_pull_interval
        self.suspect_after = suspect_after
        self.retransmit_mult = retransmit_mult
        self.indirect_probes = indirect_probes
        # Refutation window before a suspect is declared dead. None =
        # auto-scale with cluster size, memberlist's SuspicionMult
        # policy: bigger clusters need more protocol periods for the
        # rumor to reach the suspect and the refutation to travel back
        # (advisor r4: a fixed 4-period window made refutation a no-op
        # under loss in clusters > 4 nodes).
        self.suspect_timeout = suspect_timeout
        if isinstance(secret_key, str):
            secret_key = secret_key.encode()
        # NOTE (replay): the HMAC tag authenticates frame CONTENTS only.
        # Without ``replay_window``, a captured frame (an old suspect
        # rumor, a stale push/pull) can be replayed verbatim by an
        # on-path attacker; incarnation rules bound the resulting churn
        # but do not eliminate it. Set ``replay_window`` (seconds) to
        # bind a timestamp under the MAC and reject frames older than
        # the window — requires member clocks within the window of each
        # other, which is why it is opt-in.
        self.secret_key = secret_key
        self.replay_window = replay_window
        # Test hook: loss_filter(dest_addr, pkt) -> True drops the
        # datagram (deterministic loss/asymmetry injection; UDP loss on
        # send is indistinguishable from loss on the wire).
        self.loss_filter = None
        # Fault-subsystem hook: on_state_change(member_name, state)
        # fires on every membership transition (joined/alive/suspect/
        # dead) so the server can fold gossip liveness into per-peer
        # health and open a dead peer's circuit breaker BEFORE any
        # query pays a timeout against it. Exceptions are swallowed —
        # an observer must never break the membership protocol.
        self.on_state_change = None

        self._handler = None          # server: BroadcastHandler+StatusHandler
        self._mu = threading.Lock()
        self._members: dict[str, Member] = {}   # keyed by name
        # Gossip queue entries: [msg-id, b64-envelope, remaining-transmits].
        self._queue: list[list] = []
        self._seen: dict[str, None] = {}  # bounded FIFO of delivered ids
        self._bcast_n = 0
        self._seq = 0
        self._acks: dict[int, threading.Event] = {}
        # ping-req relays in flight: our relay seq -> (origin addr, origin seq)
        self._relays: dict[int, tuple[str, int]] = {}
        self._udp: Optional[socket.socket] = None
        self._tcp: Optional[socket.socket] = None
        # Stall-watchdog signal (obs.watchdog "gossip_silence"): when
        # the membership layer last RECEIVED anything (UDP absorb or a
        # TCP push/pull). 0.0 until open(); single-member clusters
        # report no age (silence is not observable — nothing should be
        # talking).
        self._last_recv = 0.0
        self._send_pool = None          # lazy bounded sync-send pool
        self._send_pool_mu = threading.Lock()
        self._closing = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- BroadcastReceiver (broadcast.go:100-107) ----------------------------

    def start(self, handler) -> None:
        """Attach the server (BroadcastHandler + StatusHandler)."""
        self._handler = handler

    # -- NodeSet (broadcast.go:26-33) ----------------------------------------

    def open(self) -> None:
        bind_host, port = _split_addr(self.gossip_host)
        if port == 0:
            # ":0" support — pick a port the kernel grants in BOTH spaces
            # (a free UDP port may be TCP-taken; retry on EADDRINUSE).
            for _ in range(16):
                udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                udp.bind((bind_host, 0))
                actual = udp.getsockname()[1]
                tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                try:
                    tcp.bind((bind_host, actual))
                except OSError:
                    udp.close()
                    tcp.close()
                    continue
                self._udp, self._tcp, port = udp, tcp, actual
                break
            else:
                raise OSError("no port bindable for both UDP and TCP")
        else:
            self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._udp.bind((bind_host, port))
            self._tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._tcp.bind((bind_host, port))
        self._tcp.listen(16)
        # Advertise a peer-reachable address: a wildcard bind is useless
        # to remote nodes, so fall back to this host's primary IP.
        adv_host = bind_host
        if adv_host in ("", "0.0.0.0", "::"):
            adv_host = _primary_ip()
        self.gossip_host = f"{adv_host}:{port}"
        self.logger.printf("gossip: listening on %s (node %s)",
                           self.gossip_host, self.host)
        with self._mu:
            self._members[self.host] = Member(self.host, self.gossip_host)

        for name, target in (("udp", self._udp_loop),
                             ("tcp", self._tcp_loop),
                             ("probe", self._probe_loop),
                             ("pushpull", self._push_pull_loop)):
            t = threading.Thread(target=target, name=f"gossip-{name}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

        for seed in self.seeds:
            if seed and seed != self.gossip_host:
                try:
                    self._push_pull(seed)
                except OSError:
                    pass  # seed down; periodic push/pull will retry

    def close(self) -> None:
        self._closing.set()
        with self._send_pool_mu:
            pool, self._send_pool = self._send_pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        for s in (self._udp, self._tcp):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def nodes(self) -> list[Node]:
        # Suspect members are still cluster members (memberlist keeps
        # them in the node list until the refutation window confirms
        # death) — dropping them early would reshard slices on a blip.
        with self._mu:
            return [Node(m.name) for m in
                    sorted(self._members.values(), key=lambda m: m.name)
                    if m.state != STATE_DEAD]

    def join(self, nodes) -> None:  # parity with StaticNodeSet
        for n in nodes:
            addr = getattr(n, "internal_host", "") or ""
            if addr:
                try:
                    self._push_pull(addr)
                except OSError:
                    pass

    # -- Broadcaster (gossip.go:124-164) -------------------------------------

    # Concurrent sync-broadcast legs (the reference's errgroup fan-out,
    # gossip.go:124-149, is similarly unbounded, but a thread per peer
    # per message does not survive n=50 clusters under write load).
    _SEND_SYNC_WORKERS = 16

    def send_sync(self, m) -> None:
        """Direct TCP frame to every alive member (gossip.go:124-149),
        fanned out over a bounded worker pool."""
        import concurrent.futures as futures
        from concurrent.futures import ThreadPoolExecutor
        data = marshal_message(m)
        peers = self._alive_peers()
        if not peers:
            return
        errs: list[Exception] = []

        def send(addr: str) -> None:
            try:
                self._tcp_request(addr, {"t": "bcast",
                                         "data": _b64(data)})
            except Exception as e:  # noqa: BLE001 - collected below
                errs.append(e)

        with self._send_pool_mu:
            if self._closing.is_set():
                return  # close() owns the pool; don't resurrect it
            pool = self._send_pool
            if pool is None:
                pool = self._send_pool = ThreadPoolExecutor(
                    max_workers=self._SEND_SYNC_WORKERS,
                    thread_name_prefix="gossip-send")
        try:
            list(pool.map(send, [mem.addr for mem in peers]))
        except futures.CancelledError:
            return  # close() cancelled the fan-out mid-flight
        except RuntimeError:
            # close() shut the pool down between our _send_pool_mu check
            # and pool.map scheduling ("cannot schedule new futures after
            # shutdown"); during shutdown this is benign, same as a cancel.
            if self._closing.is_set():
                return
            raise
        if errs:
            raise errs[0]

    def send_async(self, m) -> None:
        """Queue for piggybacked gossip (TransmitLimitedQueue,
        gossip.go:152-164)."""
        data = marshal_message(m)
        with self._mu:
            self._bcast_n += 1
            msg_id = f"{self.host}#{self._bcast_n}"
            n = max(1, len(self._members))
            budget = self.retransmit_mult * max(
                1, math.ceil(math.log2(n + 1)))
            self._queue.append([msg_id, _b64(data), budget])
            self._mark_seen(msg_id)  # don't deliver our own rumor locally

    # -- membership internals ------------------------------------------------

    def _alive_peers(self) -> list[Member]:
        """Broadcast/gossip fan-out targets: every non-dead peer
        (suspects still receive traffic — they are probably alive)."""
        with self._mu:
            return [m for m in self._members.values()
                    if m.state != STATE_DEAD and m.name != self.host]

    def _merge_member(self, w: Member) -> None:
        """SWIM merge rule: higher incarnation wins; on a tie, dead beats
        alive. A dead rumor about *ourselves* is refuted by re-announcing
        alive with a bumped incarnation."""
        deliver_update = False
        log_line = None
        with self._mu:
            cur = self._members.get(w.name)
            if w.name == self.host:
                # Refute ANY non-alive rumor about ourselves (suspect or
                # dead) with a bumped incarnation — the SWIM refutation
                # that closes a suspect's window (gossip.go:48-54).
                me = self._members[self.host]
                if (w.state in (STATE_DEAD, STATE_SUSPECT)
                        and w.incarnation >= me.incarnation):
                    me.incarnation = w.incarnation + 1
                    deliver_update = True
                    log_line = (f"gossip: refuting {w.state} rumor about"
                                f" self (inc={me.incarnation})")
            elif cur is None:
                self._members[w.name] = m = Member(w.name, w.addr,
                                                   w.incarnation, w.state)
                if m.state == STATE_SUSPECT:
                    m.suspect_at = time.monotonic()
                deliver_update = True
                log_line = (f"gossip: member joined: {w.name} ({w.addr})"
                            f" state={w.state}")
            elif (w.incarnation > cur.incarnation
                  or (w.incarnation == cur.incarnation
                      and _STATE_RANK[w.state]
                      > _STATE_RANK[cur.state])):
                # dead > suspect > alive at equal incarnation; an alive
                # claim needs a HIGHER incarnation to clear suspicion.
                if cur.state != w.state:
                    log_line = (f"gossip: member {w.name} {cur.state}"
                                f" -> {w.state} (inc={w.incarnation})")
                if w.state == STATE_SUSPECT and cur.state != STATE_SUSPECT:
                    cur.suspect_at = time.monotonic()
                cur.incarnation = w.incarnation
                cur.state = w.state
                cur.addr = w.addr
                cur.fails = 0
                deliver_update = True
        if log_line:
            self.logger.printf("%s", log_line)
        if deliver_update:
            if w.name != self.host:
                self._notify_state(w.name)
            self._gossip_update(self._member_snapshot(w.name))

    def _notify_state(self, name: str) -> None:
        cb = self.on_state_change
        if cb is None:
            return
        with self._mu:
            m = self._members.get(name)
            state = m.state if m is not None else STATE_DEAD
        try:
            cb(name, state)
        except Exception:  # noqa: BLE001 - observers must not break SWIM
            pass

    def _member_snapshot(self, name: str) -> Member:
        with self._mu:
            m = self._members[name]
            return Member(m.name, m.addr, m.incarnation, m.state)

    def _gossip_update(self, m: Member) -> None:
        """Spread a membership rumor to a few random peers immediately.

        One-shot sends can die out under loss, but state rumors are NOT
        fire-and-forget overall: every probe/ack/push-pull piggybacks
        the full membership table (_packet), and every state-CHANGING
        merge re-triggers this spread — memberlist's retransmit-queue
        effect without a second queue. A non-alive rumor is ALSO sent
        straight to its subject, so the suspect learns of its suspicion
        in one hop and can refute within the window (advisor r4)."""
        pkt = self._packet("update", updates=[m.to_wire()])
        peers = self._alive_peers()
        for peer in random.sample(peers, min(3, len(peers))):
            self._udp_send(peer.addr, pkt)
        if m.state != STATE_ALIVE and m.name != self.host:
            self._udp_send(m.addr, pkt)

    # -- frame auth ----------------------------------------------------------

    def _seal(self, payload: bytes) -> bytes:
        """Tag a frame with HMAC-SHA256 when a secret key is set. With
        ``replay_window`` enabled, an 8-byte wall-clock timestamp is
        bound under the MAC so stale captures can be rejected (see the
        replay NOTE in __init__)."""
        if self.secret_key is None:
            return payload
        if self.replay_window is not None:
            ts = struct.pack(">d", time.time())
            body = ts + payload
            mac = hmac_mod.new(self.secret_key, body,
                               hashlib.sha256).digest()
            return _HMAC_TS_TAG + mac + body
        mac = hmac_mod.new(self.secret_key, payload,
                           hashlib.sha256).digest()
        return _HMAC_TAG + mac + payload

    def _open_sealed(self, data: bytes) -> Optional[bytes]:
        """Verify + strip the HMAC tag; None = drop the frame. With a
        key configured, untagged or bad-MAC frames never reach the
        parser (the spoofed-datagram hole in round 3's SWIM-lite)."""
        if self.secret_key is None:
            return data
        if self.replay_window is not None:
            if (len(data) < len(_HMAC_TS_TAG) + _HMAC_LEN + 8
                    or not data.startswith(_HMAC_TS_TAG)):
                return None
            mac = data[len(_HMAC_TS_TAG):len(_HMAC_TS_TAG) + _HMAC_LEN]
            body = data[len(_HMAC_TS_TAG) + _HMAC_LEN:]
            want = hmac_mod.new(self.secret_key, body,
                                hashlib.sha256).digest()
            if not hmac_mod.compare_digest(mac, want):
                return None
            (ts,) = struct.unpack(">d", body[:8])
            if abs(time.time() - ts) > self.replay_window:
                return None  # stale capture (or clocks beyond window)
            return body[8:]
        if (len(data) < len(_HMAC_TAG) + _HMAC_LEN
                or not data.startswith(_HMAC_TAG)):
            return None
        mac = data[len(_HMAC_TAG):len(_HMAC_TAG) + _HMAC_LEN]
        payload = data[len(_HMAC_TAG) + _HMAC_LEN:]
        want = hmac_mod.new(self.secret_key, payload,
                            hashlib.sha256).digest()
        if not hmac_mod.compare_digest(mac, want):
            return None
        return payload

    # -- packet plumbing -----------------------------------------------------

    def _mark_seen(self, msg_id: str) -> None:
        """Bounded FIFO dedup of delivered gossip message ids (must hold
        self._mu)."""
        self._seen[msg_id] = None
        while len(self._seen) > 4096:
            self._seen.pop(next(iter(self._seen)))

    def _packet(self, typ: str, **kw) -> dict:
        """Every UDP packet piggybacks membership + queued broadcasts."""
        with self._mu:
            updates = [m.to_wire() for m in self._members.values()]
            bcasts = []
            for entry in self._queue:
                bcasts.append({"id": entry[0], "data": entry[1]})
                entry[2] -= 1
            self._queue = [e for e in self._queue if e[2] > 0]
        return {"t": typ, "from": self.host,
                "updates": updates, "bcasts": bcasts, **kw}

    def _udp_send(self, addr: str, pkt: dict) -> None:
        if self.loss_filter is not None and self.loss_filter(addr, pkt):
            return  # injected datagram loss (tests)
        try:
            self._udp.sendto(self._seal(json.dumps(pkt).encode()),
                             _split_addr(addr))
        except OSError:
            pass

    def _udp_loop(self) -> None:
        while not self._closing.is_set():
            try:
                buf, src = self._udp.recvfrom(65536)
            except OSError:
                return
            try:
                buf = self._open_sealed(buf)
                if buf is None:
                    continue  # unauthenticated/tampered: drop pre-parse
                pkt = json.loads(buf.decode())
                self._absorb(pkt)
                typ = pkt.get("t")
                if typ == "ping":
                    self._udp_send("%s:%d" % src,
                                   self._packet("ack", seq=pkt.get("seq", 0)))
                elif typ == "pingreq":
                    # Relay an indirect probe: ping the target with our
                    # own seq; the eventual ack maps back to the origin.
                    target = pkt.get("target", "")
                    origin = pkt.get("origin") or "%s:%d" % src
                    with self._mu:
                        self._seq += 1
                        relay_seq = self._seq
                        self._relays[relay_seq] = (origin,
                                                   int(pkt.get("seq", 0)))
                        while len(self._relays) > 1024:
                            self._relays.pop(next(iter(self._relays)))
                    self._udp_send(target,
                                   self._packet("ping", seq=relay_seq))
                elif typ == "ack":
                    seq = pkt.get("seq", -1)
                    ev = self._acks.get(seq)
                    if ev is not None:
                        ev.set()
                    relay = self._relays.pop(seq, None)
                    if relay is not None:  # forward to the ping-req origin
                        self._udp_send(relay[0],
                                       self._packet("ack", seq=relay[1]))
            except Exception:  # noqa: BLE001 - a bad packet must not kill IO
                continue

    def last_activity_age(self) -> Optional[float]:
        """Seconds since the last received membership traffic, or
        None when silence is not meaningful (not open yet, or no
        known peers to hear from)."""
        if self._last_recv == 0.0:
            return None
        with self._mu:
            peers = sum(1 for m in self._members.values()
                        if m.name != self.host)
        if peers == 0:
            return None
        return time.monotonic() - self._last_recv

    def _absorb(self, pkt: dict) -> None:
        self._last_recv = time.monotonic()
        for w in pkt.get("updates", []):
            try:
                self._merge_member(Member.from_wire(w))
            except (KeyError, ValueError):
                continue
        for b in pkt.get("bcasts", []):
            try:
                msg_id, data = b["id"], base64.b64decode(b["data"])
            except (KeyError, TypeError, ValueError):
                continue
            self._deliver_gossip(msg_id, data)

    def _deliver_gossip(self, msg_id: str, data: bytes) -> None:
        """Deliver a gossiped envelope once per message id, then keep the
        rumor spreading with a fresh retransmit budget."""
        with self._mu:
            if msg_id in self._seen:
                return
            self._mark_seen(msg_id)
            n = max(1, len(self._members))
            budget = self.retransmit_mult * max(
                1, math.ceil(math.log2(n + 1)))
            self._queue.append([msg_id, _b64(data), budget])
        self._handle_envelope(data)

    def _handle_envelope(self, data: bytes) -> None:
        if _fp.ACTIVE is not None:
            try:
                _fp.ACTIVE.hit("gossip.deliver")
            except _fp.FailpointError:
                return  # injected drop: the envelope is lost in transit
        if self._handler is not None:
            try:
                self._handler.receive_message(unmarshal_message(data))
            except Exception:  # noqa: BLE001 - bad envelope must not kill IO
                pass

    # -- TCP: sync bcast + push/pull (gossip.go:124-149,193-222) -------------

    def _tcp_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._tcp.accept()
            except OSError:
                return
            threading.Thread(target=self._tcp_serve, args=(conn,),
                             daemon=True).start()

    def _tcp_serve(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(10.0)
                raw = self._open_sealed(_recv_frame(conn))
                if raw is None:
                    return  # unauthenticated frame: drop
                req = json.loads(raw.decode())
                if req.get("t") == "bcast":
                    # Sync sends are point-to-point: deliver directly,
                    # no gossip relay and no dedup (gossip.go:124-149).
                    self._handle_envelope(base64.b64decode(req["data"]))
                    _send_frame(conn, self._seal(b'{"t":"ok"}'))
                elif req.get("t") == "pushpull":
                    self._absorb_state(req)
                    _send_frame(conn, self._seal(
                        json.dumps(self._local_state()).encode()))
        except (OSError, ValueError, ConnectionError, KeyError):
            pass

    def _tcp_request(self, addr: str, req: dict,
                     timeout: float = 10.0) -> dict:
        with socket.create_connection(_split_addr(addr),
                                      timeout=timeout) as conn:
            _send_frame(conn, self._seal(json.dumps(req).encode()))
            raw = self._open_sealed(_recv_frame(conn))
            if raw is None:
                raise ConnectionError("unauthenticated gossip response")
            return json.loads(raw.decode())

    def _local_state(self) -> dict:
        """Full state for push/pull: membership + the protobuf
        ``NodeStatus`` (schema metas + owned slices) exactly as the
        reference's memberlist delegate marshals it (gossip.go:193-205
        LocalState; internal/private.proto:74-90). The protobuf rides
        base64 inside the JSON frame."""
        with self._mu:
            members = [m.to_wire() for m in self._members.values()]
        status_b64 = None
        if self._handler is not None and hasattr(self._handler,
                                                 "local_status"):
            try:
                status = self._handler.local_status()  # pb.NodeStatus
                status_b64 = _b64(status.SerializeToString())
            except Exception as e:  # noqa: BLE001 - status is best-effort
                self.logger.printf("gossip: error getting local state:"
                                   " %s", e)
        out = {"t": "pushpull", "members": members,
               "status_pb": status_b64}
        # Elastic-resize convergence (cluster.resize): the placement
        # epoch + in-flight/last-settled resize ride the push/pull, so
        # a node that missed the coordinator's control sends
        # (partitioned, restarted) converges within one exchange.
        if self._handler is not None and hasattr(
                self._handler, "resize_wire_state"):
            try:
                out["resize_state"] = self._handler.resize_wire_state()
            except Exception:  # noqa: BLE001 - piggyback best-effort
                pass
        # Build identity (obs.runtime.build_info): rides every
        # push/pull so version skew across a mixed-version fleet is
        # visible from any member during a rolling restart
        # (/debug/cluster's gossipBuilds block).
        if self._handler is not None and hasattr(
                self._handler, "build_wire_state"):
            try:
                out["build_state"] = self._handler.build_wire_state()
            except Exception:  # noqa: BLE001 - piggyback best-effort
                pass
        return out

    def _absorb_state(self, state: dict) -> None:
        """MergeRemoteState (gossip.go:208-222)."""
        self._last_recv = time.monotonic()
        for w in state.get("members", []):
            try:
                self._merge_member(Member.from_wire(w))
            except (KeyError, ValueError):
                continue
        rz = state.get("resize_state")
        if rz and self._handler is not None and hasattr(
                self._handler, "apply_resize_wire_state"):
            try:
                self._handler.apply_resize_wire_state(rz)
            except Exception as e:  # noqa: BLE001 - merge best-effort
                self.logger.printf("gossip: resize merge error: %s", e)
        bd = state.get("build_state")
        if bd and self._handler is not None and hasattr(
                self._handler, "apply_build_wire_state"):
            try:
                self._handler.apply_build_wire_state(bd)
            except Exception:  # noqa: BLE001 - piggyback best-effort
                pass
        status_b64 = state.get("status_pb")
        if status_b64 and self._handler is not None and hasattr(
                self._handler, "handle_remote_status"):
            from ..proto import internal_pb2 as pb
            try:
                ns = pb.NodeStatus.FromString(
                    base64.b64decode(status_b64))
                self._handler.handle_remote_status(ns)
            except Exception as e:  # noqa: BLE001 - merge is best-effort
                self.logger.printf("gossip: merge state error: %s", e)

    def _push_pull(self, addr: str) -> None:
        resp = self._tcp_request(addr, self._local_state())
        self._absorb_state(resp)

    def _push_pull_loop(self) -> None:
        while not self._closing.wait(self.push_pull_interval):
            peers = self._alive_peers()
            if not peers:
                continue
            try:
                self._push_pull(random.choice(peers).addr)
            except OSError:
                pass

    # -- failure detection (SWIM probe) --------------------------------------

    def _probe_loop(self) -> None:
        while not self._closing.wait(self.probe_interval):
            self._expire_suspects()
            peers = self._probe_targets()
            if not peers:
                continue
            self._probe(random.choice(peers))

    def _probe_targets(self) -> list[Member]:
        with self._mu:
            return [m for m in self._members.values()
                    if m.state in (STATE_ALIVE, STATE_SUSPECT)
                    and m.name != self.host]

    def _ping(self, addr: str) -> bool:
        """One direct ping/ack round trip."""
        with self._mu:
            self._seq += 1
            seq = self._seq
            ev = self._acks[seq] = threading.Event()
        self._udp_send(addr, self._packet("ping", seq=seq))
        ok = ev.wait(self.probe_timeout)
        self._acks.pop(seq, None)
        return ok

    def _ping_indirect(self, peer: Member) -> bool:
        """SWIM ping-req: ask k random other peers to probe ``peer``
        and relay the ack — a lossy/asymmetric direct path must not
        condemn a node the rest of the cluster reaches fine
        (memberlist's IndirectChecks, gossip.go:48-54)."""
        relays = [m for m in self._probe_targets()
                  if m.name != peer.name and m.state == STATE_ALIVE]
        if not relays or self.indirect_probes <= 0:
            return False
        relays = random.sample(relays,
                               min(self.indirect_probes, len(relays)))
        with self._mu:
            self._seq += 1
            seq = self._seq
            ev = self._acks[seq] = threading.Event()
        pkt = self._packet("pingreq", seq=seq, target=peer.addr,
                           origin=self.gossip_host)
        for r in relays:
            self._udp_send(r.addr, pkt)
        # Relays each pay one probe_timeout; allow one extra hop's worth.
        ok = ev.wait(2.0 * self.probe_timeout)
        self._acks.pop(seq, None)
        return ok

    def _probe(self, peer: Member) -> None:
        ok = self._ping(peer.addr)
        if not ok:
            ok = self._ping_indirect(peer)
        suspect = None
        with self._mu:
            cur = self._members.get(peer.name)
            if cur is None or cur.state == STATE_DEAD:
                return
            if ok:
                cur.fails = 0
                return
            cur.fails += 1
            if (cur.state == STATE_ALIVE
                    and cur.fails >= self.suspect_after):
                cur.state = STATE_SUSPECT
                cur.suspect_at = time.monotonic()
                suspect = Member(cur.name, cur.addr, cur.incarnation,
                                 STATE_SUSPECT)
        if suspect is not None:
            self.logger.printf(
                "gossip: node %s missed %d direct+indirect probes,"
                " marking suspect", suspect.name, self.suspect_after)
            self._notify_state(suspect.name)
            self._gossip_update(suspect)

    def _suspect_window(self, n_members: int) -> float:
        """Seconds a suspect has to refute. Explicit override wins;
        otherwise memberlist's SuspicionMult shape — protocol periods
        scaled by log(cluster size), so the rumor can reach the suspect
        and the refutation can travel back even with loss."""
        if self.suspect_timeout is not None:
            return self.suspect_timeout
        return 4.0 * self.probe_interval * max(
            1.0, math.log2(n_members + 1))

    def _expire_suspects(self) -> None:
        """Suspects whose refutation window lapsed are declared dead."""
        now = time.monotonic()
        dead = []
        with self._mu:
            window = self._suspect_window(len(self._members))
            for m in self._members.values():
                if (m.state == STATE_SUSPECT
                        and now - m.suspect_at > window):
                    m.state = STATE_DEAD
                    dead.append(Member(m.name, m.addr, m.incarnation,
                                       STATE_DEAD))
        for d in dead:
            self.logger.printf(
                "gossip: suspect %s not refuted in %.1fs, declaring"
                " dead", d.name, window)
            self._notify_state(d.name)
            self._gossip_update(d)


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def _primary_ip() -> str:
    """Best-effort primary interface IP for advertising a wildcard bind.
    The connect() on a UDP socket sends no packets; it only resolves the
    route."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"
