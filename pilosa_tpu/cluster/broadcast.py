"""Broadcast abstraction: cluster membership + schema-mutation messaging.

Reference: broadcast.go + httpbroadcast/messenger.go. The control plane
carries the reference's five message kinds (create-slice/index/frame,
delete-index/frame) plus the sched subsystem's query-cancel message
as a 1-byte type tag + protobuf envelope (broadcast.go:109-166). Backends:
``static`` (fixed node list, no messaging), ``http`` (direct POST of the
envelope to each peer's internal port). The data plane (queries, imports,
block sync) never rides this channel — it is protobuf-over-HTTP via
cluster.client.

This stays a host-side CPU concern in the TPU build: schema metadata is
tiny and latency-tolerant, so it travels over DCN-ordinary HTTP while
bitmap reductions ride ICI collectives (pilosa_tpu.parallel.mesh).
"""

from __future__ import annotations

import threading
import urllib.request
from typing import Optional

from ..proto import internal_pb2 as pb
from .topology import Node

MESSAGE_TYPE_CREATE_SLICE = 1
MESSAGE_TYPE_CREATE_INDEX = 2
MESSAGE_TYPE_DELETE_INDEX = 3
MESSAGE_TYPE_CREATE_FRAME = 4
MESSAGE_TYPE_DELETE_FRAME = 5
MESSAGE_TYPE_CANCEL_QUERY = 6
MESSAGE_TYPE_RESIZE = 7


class CancelQueryMessage:
    """Cluster-wide query cancellation (sched subsystem): the envelope
    body is the raw query id, so this rides the same 1-byte-tag wire
    format as the protobuf control messages without a schema change —
    it duck-types the SerializeToString/FromString pair
    marshal/unmarshal use."""

    __slots__ = ("id",)

    def __init__(self, id: str = ""):
        self.id = id

    def SerializeToString(self) -> bytes:  # noqa: N802 - protobuf parity
        return self.id.encode()

    @classmethod
    def FromString(cls, raw: bytes) -> "CancelQueryMessage":  # noqa: N802
        return cls(raw.decode())

    def __repr__(self) -> str:
        return f"CancelQueryMessage(id={self.id!r})"


class ResizeMessage:
    """Elastic-resize control message (cluster.resize;
    docs/CLUSTER_RESIZE.md): one wire form for every phase of the
    protocol — ``prepare`` installs the in-flight state (union writes,
    read fencing), ``flip`` switches placement epoch-atomically,
    ``finalize`` drops the union, ``abort`` backs out to the old
    epoch. The body is compact JSON riding the same 1-byte-tag
    envelope as the protobuf control messages (the CancelQueryMessage
    duck-typing pattern), so it travels over every broadcaster backend
    (static direct-POST, http, gossip) unchanged. The coordinator
    sends phases as DIRECT per-node POSTs of this envelope to
    ``/messages`` (each node's 200 is its ack) and re-broadcasts them
    async over gossip for stragglers."""

    __slots__ = ("id", "phase", "epoch", "old_hosts", "new_hosts",
                 "coordinator")

    def __init__(self, id: str = "", phase: str = "", epoch: int = 0,
                 old_hosts=None, new_hosts=None, coordinator: str = ""):
        self.id = id
        self.phase = phase            # prepare | flip | finalize | abort
        self.epoch = epoch            # the epoch the resize starts FROM
        self.old_hosts = list(old_hosts or [])
        self.new_hosts = list(new_hosts or [])
        self.coordinator = coordinator

    def SerializeToString(self) -> bytes:  # noqa: N802 - protobuf parity
        import json
        return json.dumps(
            {"id": self.id, "phase": self.phase, "epoch": self.epoch,
             "old": self.old_hosts, "new": self.new_hosts,
             "coordinator": self.coordinator},
            separators=(",", ":")).encode()

    @classmethod
    def FromString(cls, raw: bytes) -> "ResizeMessage":  # noqa: N802
        import json
        d = json.loads(raw.decode())
        return cls(id=str(d.get("id", "")),
                   phase=str(d.get("phase", "")),
                   epoch=int(d.get("epoch", 0)),
                   old_hosts=d.get("old") or [],
                   new_hosts=d.get("new") or [],
                   coordinator=str(d.get("coordinator", "")))

    def __repr__(self) -> str:
        return (f"ResizeMessage(id={self.id!r}, phase={self.phase!r},"
                f" epoch={self.epoch}, old={self.old_hosts},"
                f" new={self.new_hosts})")


_TYPE_BY_CLASS = {
    pb.CreateSliceMessage: MESSAGE_TYPE_CREATE_SLICE,
    pb.CreateIndexMessage: MESSAGE_TYPE_CREATE_INDEX,
    pb.DeleteIndexMessage: MESSAGE_TYPE_DELETE_INDEX,
    pb.CreateFrameMessage: MESSAGE_TYPE_CREATE_FRAME,
    pb.DeleteFrameMessage: MESSAGE_TYPE_DELETE_FRAME,
    CancelQueryMessage: MESSAGE_TYPE_CANCEL_QUERY,
    ResizeMessage: MESSAGE_TYPE_RESIZE,
}
_CLASS_BY_TYPE = {v: k for k, v in _TYPE_BY_CLASS.items()}


def marshal_message(m) -> bytes:
    """1-byte type tag + protobuf body (broadcast.go:118-139)."""
    typ = _TYPE_BY_CLASS.get(type(m))
    if typ is None:
        raise ValueError(f"message type not implemented: {type(m)}")
    return bytes([typ]) + m.SerializeToString()


def unmarshal_message(buf: bytes):
    cls = _CLASS_BY_TYPE.get(buf[0])
    if cls is None:
        raise ValueError(f"invalid message type: {buf[0]}")
    return cls.FromString(buf[1:])


class NopBroadcaster:
    """Default no-op broadcaster (broadcast.go:60-74)."""

    def send_sync(self, m) -> None:
        pass

    send_async = send_sync


NOP_BROADCASTER = NopBroadcaster()


class StaticNodeSet:
    """Fixed-membership NodeSet for single node / tests
    (broadcast.go:35-58)."""

    def __init__(self, nodes: Optional[list[Node]] = None):
        self._nodes = list(nodes or [])

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def nodes(self) -> list[Node]:
        return list(self._nodes)

    def join(self, nodes: list[Node]) -> None:
        self._nodes = list(nodes)


class HTTPBroadcaster:
    """POST the type-tagged envelope to every peer's internal host
    (httpbroadcast/messenger.go:43-121)."""

    def __init__(self, server, timeout: float = 5.0):
        # ``server`` supplies local host + cluster (server.py); matching
        # the reference, sends exclude the local node.
        self.server = server
        self.timeout = timeout

    def _peers(self) -> list[Node]:
        return [n for n in self.server.cluster.nodes
                if n.host != self.server.host]

    def send_sync(self, m) -> None:
        data = marshal_message(m)
        errs = []
        threads = []

        def post(node):
            try:
                host = node.internal_host or node.host
                req = urllib.request.Request(
                    f"http://{host}/messages", data=data, method="POST",
                    headers={"Content-Type": "application/x-protobuf"})
                urllib.request.urlopen(req, timeout=self.timeout).read()
            except Exception as e:  # noqa: BLE001 - collected below
                errs.append(e)

        for node in self._peers():
            t = threading.Thread(target=post, args=(node,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def send_async(self, m) -> None:
        # Best-effort fire-and-forget on a thread.
        threading.Thread(target=lambda: self._send_quiet(m),
                         daemon=True).start()

    def _send_quiet(self, m) -> None:
        try:
            self.send_sync(m)
        except Exception:  # noqa: BLE001 - async sends are best-effort
            pass
