"""Elastic cluster resize: the coordinator that takes a cluster from N
to N±1 nodes online, with zero wrong answers under live load
(docs/CLUSTER_RESIZE.md; ROADMAP item 5).

Protocol (every control step is a ``ResizeMessage`` POSTed directly to
each node's ``/messages`` — the 200 is that node's ack — and
re-broadcast async over gossip for stragglers):

1. **prepare** (all-ack required): every node (old ∪ new membership)
   installs the in-flight ``ResizeState``. From this moment writes to
   moving partitions fan to the union of old and new owners, reads of
   moving slices stay fenced to the old owners (the target copies are
   incomplete), and coordinators double-read moving slices.
2. **stream**: the coordinator walks the moving fragment set and
   pushes each one source→target with the FragmentSyncer block-diff
   protocol (server.syncer.FragmentStreamer — sets-only, additive,
   idempotent), paced by the PR-5 health EWMA/circuit breakers, until
   a whole pass moves zero bits (every pre-prepare bit is streamed;
   everything since double-writes).
3. **flip** (all-ack required): every node switches ``cluster.nodes``
   and bumps the placement epoch in ONE atomic step (topology
   ``flip_epoch``) and enters *draining* — reads route by the new
   placement, writes KEEP fanning to the union so a node that has not
   yet processed the flip cannot strand a write on the old copy only.
4. **drain-diff**: one more block-diff pass while everyone
   union-writes — closes the window where a write placed before its
   node processed *prepare* applied after its block had been streamed.
5. **finalize** (acked, stragglers converge via gossip + the
   write-accept grace window): the union drops; single-path writes
   resume; done.

Abort at any point before finalize completes broadcasts ``abort``:
nodes clear the resize state (reverting nodes/epoch if they had
flipped — safe, because every node union-writes until finalize, so the
old copies never missed a write). The coordinator journals every phase
transition to ``<data>/resize.json`` (atomic rename), so a coordinator
crash recovers deterministically: pre-flip resizes abort back to the
old epoch, post-flip resizes roll forward.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Optional

from ..errors import PilosaError
from ..obs import metrics as obs_metrics
from ..utils import logger as logger_mod
from .broadcast import ResizeMessage, marshal_message
from .topology import movement

# Coordinator phases (journal + /cluster/resize + the
# pilosa_cluster_resize_state gauge). Node-side ResizeState phases
# (migrating/draining) are a projection of these.
PHASE_IDLE = "idle"
PHASE_PREPARING = "preparing"
PHASE_STREAMING = "streaming"
PHASE_FLIPPING = "flipping"
PHASE_DRAINING = "draining"
PHASE_FINALIZING = "finalizing"
PHASE_DONE = "done"
PHASE_ABORTED = "aborted"

PHASES = (PHASE_IDLE, PHASE_PREPARING, PHASE_STREAMING, "migrating",
          PHASE_FLIPPING, PHASE_DRAINING, PHASE_FINALIZING, PHASE_DONE,
          PHASE_ABORTED)

JOURNAL_FILE = "resize.json"

# How many clean-pass attempts the streamer makes before giving up on
# convergence (each pass is a full block-diff; a pass that moves zero
# bits proves the pre-flip copies converged).
MAX_STREAM_PASSES = 6
# Control-send retry budget (per phase, per node) before the
# coordinator declares the phase unreachable.
ACK_RETRIES = 10
ACK_RETRY_SLEEP_S = 0.5


def set_state_gauge(phase: str) -> None:
    """One-hot the resize-state gauge across the known phase labels."""
    for p in PHASES:
        obs_metrics.RESIZE_STATE.labels(p).set(
            1.0 if p == phase else 0.0)


class ResizeError(PilosaError):
    pass


class ResizeJournal:
    """Crash-safe record of the coordinator's progress: one JSON file
    under the data dir, rewritten atomically (tmp + rename) on every
    phase transition and streamed-fragment batch. ``Server.open``
    replays it — an in-flight pre-flip resize aborts back to the old
    epoch, a post-flip one rolls forward."""

    VERSION = 1

    def __init__(self, path: str):
        self.path = path
        self.state: dict = {}
        # write() is reachable from the coordinator run thread AND the
        # HTTP abort thread concurrently — serialize both the state
        # mutation and the tmp+rename (an interleaved pair could land
        # a truncated file that load() rejects, silently abandoning an
        # in-flight resize at the next open).
        self._mu = threading.Lock()

    @classmethod
    def for_data_dir(cls, data_dir: str) -> "ResizeJournal":
        return cls(os.path.join(data_dir, JOURNAL_FILE))

    def load(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                loaded = json.load(f)
        except (OSError, ValueError):
            return None
        if loaded.get("version") != self.VERSION:
            return None
        with self._mu:
            self.state = loaded
        return self.state

    def write(self, **updates) -> None:
        with self._mu:
            self.state.update(updates)
            self.state["version"] = self.VERSION
            self.state["updatedAt"] = time.time()
            snapshot = dict(self.state)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    def in_flight(self) -> bool:
        phase = self.state.get("phase")
        if phase in (None, PHASE_DONE):
            return False
        if phase == PHASE_ABORTED:
            # An abort whose broadcast never reached every node leaves
            # peers holding the installed state (union writes, fenced
            # reads) — recovery must re-send it.
            return not self.state.get("abortAcked", True)
        return True


class ResizeCoordinator:
    """Drives one resize end-to-end against a live Server. One at a
    time per cluster (the prepare install enforces it cluster-wide:
    a second id raises on every node)."""

    def __init__(self, server, target_hosts: list[str],
                 resize_id: Optional[str] = None,
                 journal: Optional[ResizeJournal] = None,
                 pace_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 logger=None):
        self.server = server
        self.cluster = server.cluster
        self.target_hosts = list(target_hosts)
        self.id = resize_id or uuid.uuid4().hex[:12]
        self.journal = journal or ResizeJournal.for_data_dir(
            server.holder.path)
        self.pace_s = (pace_s if pace_s is not None
                       else getattr(server, "resize_pace_s", 0.0))
        self.grace_s = (grace_s if grace_s is not None
                        else getattr(server, "resize_grace_s", 30.0))
        self.logger = logger or getattr(server, "logger",
                                        logger_mod.NOP)
        self.old_hosts = [n.host for n in self.cluster.nodes]
        self.phase = PHASE_IDLE
        self.error: Optional[str] = None
        self.moving: dict = {}
        self.slices_moved = 0
        self._moved_groups: set = set()
        self.bits_streamed = 0
        self.bytes_streamed = 0
        self.stream_passes = 0
        self.started_at = 0.0
        self.finished_at = 0.0
        # Watchdog progress signal (obs.watchdog "resize_stall"): any
        # forward step — an ack, a streamed block, a phase move —
        # touches this.
        self.last_progress = time.monotonic()
        self._mu = threading.Lock()
        self._cancel = threading.Event()

    # -- plumbing -------------------------------------------------------------

    def cancel(self) -> None:
        """Cooperative stop (server close / operator abort): the run
        loop notices between control sends and streamed blocks, then
        aborts back to the old epoch."""
        self._cancel.set()

    def touch(self) -> None:
        self.last_progress = time.monotonic()

    def _on_stream_block(self, bits: int, nbytes: int) -> None:
        """Per-block streamer callback: live progress for status,
        bench, and the watchdog heartbeat."""
        self.bytes_streamed += nbytes
        self.touch()

    def _check_cancel(self) -> None:
        if self._cancel.is_set():
            raise ResizeError(f"resize {self.id}: cancelled")

    def _set_phase(self, phase: str, **journal_updates) -> None:
        # Terminal phases stamp the finish time BEFORE the phase
        # becomes visible: a status poll must never observe
        # phase=done with finishedAt still unset (review finding —
        # the bench's duration computation would go negative), and
        # recovery paths reach DONE without passing through run().
        if phase in (PHASE_DONE, PHASE_ABORTED) and not self.finished_at:
            self.finished_at = time.time()
        with self._mu:
            self.phase = phase
        set_state_gauge(phase)
        self.touch()
        self.journal.write(phase=phase, **journal_updates)
        self.logger.printf("resize %s: phase %s", self.id, phase)

    def _union_hosts(self) -> list[str]:
        seen = []
        for h in self.old_hosts + self.target_hosts:
            if h not in seen:
                seen.append(h)
        return seen

    def _message(self, phase: str) -> ResizeMessage:
        return ResizeMessage(id=self.id, phase=phase,
                             epoch=self.journal.state.get(
                                 "epochFrom", self.cluster.epoch),
                             old_hosts=self.old_hosts,
                             new_hosts=self.target_hosts,
                             coordinator=self.server.host)

    def _send_phase(self, msg: ResizeMessage, hosts: list[str],
                    require_all: bool,
                    retries: int = ACK_RETRIES) -> list[str]:
        """Deliver ``msg`` to every host (self applied in-process),
        retrying failures with backoff. Returns the hosts that never
        acked; raises ResizeError when ``require_all`` and any
        remain. The message is also re-broadcast async (gossip /
        whatever backend) so a temporarily partitioned node converges
        later."""
        data = marshal_message(msg)
        pending = list(hosts)
        for attempt in range(retries):
            if msg.phase != "abort":
                self._check_cancel()
            still = []
            for host in pending:
                try:
                    if host == self.server.host:
                        self.server.receive_message(msg)
                    else:
                        self.server.client_for(host).post_message(
                            data, host=host, deadline_s=10.0)
                    self.touch()
                except Exception as e:  # noqa: BLE001 - retried below
                    self.logger.printf(
                        "resize %s: %s to %s failed (attempt %d): %s",
                        self.id, msg.phase, host, attempt + 1, e)
                    still.append(host)
            pending = still
            if not pending:
                break
            time.sleep(ACK_RETRY_SLEEP_S * min(4, attempt + 1))
        try:
            self.server.broadcaster.send_async(msg)
        except Exception:  # noqa: BLE001 - async catch-up best-effort
            pass
        if pending and require_all:
            raise ResizeError(
                f"resize {self.id}: phase {msg.phase} unacked by"
                f" {pending}")
        return pending

    # -- movement enumeration -------------------------------------------------

    def _moving_slice_groups(self) -> list[tuple]:
        """Every (index, slice, source_hosts, target_hosts) in the
        movement set, from the coordinator's schema knowledge (max
        slices include remote announcements)."""
        holder = self.server.holder
        out = []
        for name in sorted(holder.indexes):
            idx = holder.indexes[name]
            hi = max(idx.max_slice(), idx.max_inverse_slice())
            for slice in range(hi + 1):
                p = self.cluster.partition(name, slice)
                mv = self.moving.get(p)
                if mv is None:
                    continue
                old, new = mv
                targets = [h for h in new if h not in old]
                if targets:
                    out.append((name, slice, list(old), targets))
        return out

    def _stream_pass(self, streamer) -> int:
        """One full block-diff pass over the moving fragment set;
        returns bits moved (0 = converged). Fragments enumerate from
        the SOURCE's view of the schema (its frames' views), so time
        and inverse views migrate too."""
        moved_bits = 0
        view_memo: dict = {}
        for index, slice, sources, targets in self._moving_slice_groups():
            src_host = self._pick_source(sources)
            if src_host is None:
                raise ResizeError(
                    f"resize {self.id}: no reachable source among"
                    f" {sources} for {index}/{slice}")
            idx = self.server.holder.index(index)
            frames = sorted(idx.frames) if idx is not None else []
            group_bits = 0
            self._check_cancel()
            for frame in frames:
                views = view_memo.get((src_host, index, frame))
                if views is None:
                    try:
                        views = self._source_views(src_host, index,
                                                   frame)
                    except Exception:  # noqa: BLE001 - fall back local
                        frame_obj = idx.frames.get(frame)
                        views = (sorted(frame_obj.views)
                                 if frame_obj is not None else [])
                    view_memo[(src_host, index, frame)] = views
                for view in views:
                    for target in targets:
                        if not streamer.wait_allowed(
                                target, closing=self.server._closing):
                            raise ResizeError(
                                f"resize {self.id}: target {target}"
                                f" circuit stayed open")
                        # Byte/progress accounting rides the per-block
                        # on_block callback (the streamer invokes
                        # _on_stream_block), so status + the watchdog
                        # heartbeat advance WHILE a fragment streams.
                        bits, _nbytes = streamer.stream_fragment(
                            index, frame, view, slice, src_host,
                            target)
                        group_bits += bits
                        self.touch()
            moved_bits += group_bits
            if group_bits and (index, slice) not in self._moved_groups:
                # Once per (index, slice) across ALL passes: later
                # catch-up passes re-moving a few live-write bits must
                # not re-count the group (review finding).
                self._moved_groups.add((index, slice))
                self.slices_moved += 1
                obs_metrics.RESIZE_SLICES_MOVED.inc()
        self.bits_streamed += moved_bits
        return moved_bits

    def _source_views(self, src_host: str, index: str,
                      frame: str) -> list[str]:
        client = self.server.client_for(src_host)
        views = client.frame_views(index, frame)
        return [v if isinstance(v, str) else v.get("name", "")
                for v in (views or [])]

    def _sync_slice_knowledge(self) -> None:
        """Announce every index's max (and max inverse) slice to the
        whole union membership as CreateSliceMessage envelopes — the
        same wire the ordinary slice-creation broadcast rides — so
        every node enumerates the full slice range from the first
        post-flip query. Best-effort per host: a miss falls back to
        the gossip status merge."""
        from ..proto import internal_pb2 as pb
        holder = self.server.holder
        for name in sorted(holder.indexes):
            idx = holder.indexes[name]
            for is_inv, mx in ((False, idx.max_slice()),
                               (True, idx.max_inverse_slice())):
                if mx <= 0:
                    continue
                msg = pb.CreateSliceMessage(Index=name, Slice=mx,
                                            IsInverse=is_inv)
                data = marshal_message(msg)
                for host in self._union_hosts():
                    try:
                        if host == self.server.host:
                            self.server.receive_message(msg)
                        else:
                            self.server.client_for(host).post_message(
                                data, host=host, deadline_s=10.0)
                    except Exception as e:  # noqa: BLE001 - advisory
                        self.logger.printf(
                            "resize %s: slice-knowledge sync to %s"
                            " failed: %s", self.id, host, e)

    def _pick_source(self, sources: list[str]) -> Optional[str]:
        fault = self.server.fault
        ordered = list(sources)
        if fault is not None:
            ordered = sorted(
                ordered, key=lambda h: 0 if fault.would_allow(h) else 1)
        for h in ordered:
            if fault is None or fault.would_allow(h):
                return h
        return ordered[0] if ordered else None

    # -- the protocol ---------------------------------------------------------

    def run(self) -> dict:
        """Drive the resize to done (or abort on failure). Returns the
        status dict; raises nothing — errors land in ``self.error``
        with the journal at ``aborted`` and the cluster back on the
        old epoch."""
        self.started_at = time.time()
        try:
            self._run_inner()
        except Exception as e:  # noqa: BLE001 - abort owns cleanup
            self.error = str(e)
            self.logger.printf("resize %s: failed: %s — aborting",
                               self.id, e)
            if self.phase != PHASE_ABORTED:
                # An operator abort() (which sets _cancel and already
                # broadcast) surfaces here as the cancel error — the
                # protocol must not re-abort on top of it.
                try:
                    self.abort(reason=str(e))
                except Exception as e2:  # noqa: BLE001 - keep first
                    self.logger.printf("resize %s: abort itself"
                                       " failed: %s", self.id, e2)
        self.finished_at = self.finished_at or time.time()
        return self.status()

    def _run_inner(self) -> None:
        if set(self.target_hosts) == set(self.old_hosts):
            raise ResizeError("target membership equals current")
        if not self.target_hosts:
            raise ResizeError("target membership empty")
        self.moving = movement(self.old_hosts, self.target_hosts,
                               self.cluster.partition_n,
                               self.cluster.replica_n,
                               self.cluster.hasher)
        self.journal.write(
            id=self.id, phase=PHASE_IDLE,
            epochFrom=self.cluster.epoch,
            old=self.old_hosts, new=self.target_hosts,
            coordinator=self.server.host, startedAt=self.started_at,
            movingPartitions=sorted(self.moving))
        if not self.moving:
            # Same owner sets everywhere (e.g. pure reorder): flip
            # membership without any streaming.
            self.logger.printf("resize %s: empty movement set",
                               self.id)
        # 1. prepare — all-ack before any byte moves (a node that has
        # not installed the union could write old-only past a block
        # the streamer already read). Slice knowledge syncs alongside:
        # a freshly joined target otherwise only learns remote max
        # slices on the ~15 s gossip push/pull cadence, and a
        # coordinator that under-counts an index's slices right after
        # the flip would silently answer over a subset.
        self._set_phase(PHASE_PREPARING)
        self._send_phase(self._message("prepare"), self._union_hosts(),
                         require_all=True)
        self._sync_slice_knowledge()
        # 2. stream until a pass is clean.
        self._set_phase(PHASE_STREAMING)
        from ..server.syncer import FragmentStreamer
        streamer = FragmentStreamer(
            client_factory=self.server._client_factory,
            logger=self.logger, fault=self.server.fault,
            pace_s=self.pace_s, on_block=self._on_stream_block)
        for pass_n in range(1, MAX_STREAM_PASSES + 1):
            self.stream_passes = pass_n
            moved = self._stream_pass(streamer)
            self.journal.write(streamPasses=pass_n,
                               bitsStreamed=self.bits_streamed,
                               bytesStreamed=self.bytes_streamed)
            if moved == 0 and pass_n > 1:
                break
            if moved == 0 and not self.moving:
                break
        else:
            raise ResizeError(
                f"stream did not converge in {MAX_STREAM_PASSES}"
                f" passes (live write rate too high?)")
        # 3. flip — the commit point. All-ack: after ANY node flips,
        # we roll FORWARD (retry until all ack); if the retries
        # exhaust, the abort below reverts flipped nodes (safe:
        # everyone still union-writes).
        self._set_phase(PHASE_FLIPPING)
        self._send_phase(self._message("flip"), self._union_hosts(),
                         require_all=True, retries=ACK_RETRIES * 2)
        # 4. drain-diff: one more pass with every node on the new
        # epoch and still union-writing — catches any write that was
        # placed before its node processed prepare but applied after
        # its block streamed.
        self._set_phase(PHASE_DRAINING)
        self._stream_pass(streamer)
        # 5. finalize — drop the union. Stragglers converge via the
        # async re-broadcast + gossip piggyback + the old owners'
        # write-accept grace window, so this phase tolerates unacked
        # nodes.
        self._set_phase(PHASE_FINALIZING)
        pending = self._send_phase(self._message("finalize"),
                                   self._union_hosts(),
                                   require_all=False)
        if pending:
            self.logger.printf(
                "resize %s: finalize unacked by %s (gossip catch-up"
                " + %.0fs write grace cover them)", self.id, pending,
                self.grace_s)
        self._set_phase(PHASE_DONE, finishedAt=time.time(),
                        slicesMoved=self.slices_moved)
        set_state_gauge(PHASE_IDLE)

    def abort(self, reason: str = "") -> None:
        """Back the whole cluster out to the old epoch. No data loss
        by construction: old owners never dropped anything, and every
        write since prepare double-landed on them. The journal only
        records the abort as fully acked once every node confirmed —
        otherwise recovery re-sends it, so no peer stays stuck
        holding the installed state.

        Callable from any thread (the operator API aborts a LIVE
        coordinator): the cancel flag stops the run loop at its next
        check, so it cannot re-install state and complete a resize
        the operator was told is aborted (review finding)."""
        self._cancel.set()
        self._set_phase(PHASE_ABORTED, abortReason=reason,
                        abortAcked=False)
        pending = self._send_phase(self._message("abort"),
                                   self._union_hosts(),
                                   require_all=False)
        self.journal.write(abortAcked=not pending,
                           abortPending=pending)
        set_state_gauge(PHASE_IDLE)

    def status(self) -> dict:
        with self._mu:
            phase = self.phase
        return {"id": self.id, "phase": phase,
                "error": self.error,
                "old": self.old_hosts, "new": self.target_hosts,
                "movingPartitions": sorted(self.moving),
                "slicesMoved": self.slices_moved,
                "bitsStreamed": self.bits_streamed,
                "bytesStreamed": self.bytes_streamed,
                "streamPasses": self.stream_passes,
                "startedAt": self.started_at,
                "finishedAt": self.finished_at or None,
                "progressAgeS": round(
                    time.monotonic() - self.last_progress, 3)}


def recover(server, logger=None) -> Optional[dict]:
    """Replay the resize journal at server open: an in-flight PRE-FLIP
    resize aborts back to the old epoch (the safe default — nothing
    moved authoritatively yet); a post-flip one rolls FORWARD (some
    nodes may already be serving the new epoch, and the old copies
    stop being written the moment anyone finalizes). Returns the final
    status dict, or None when the journal shows nothing in flight."""
    logger = logger or getattr(server, "logger", logger_mod.NOP)
    journal = ResizeJournal.for_data_dir(server.holder.path)
    state = journal.load()
    if not state or not journal.in_flight():
        return None
    phase = state.get("phase")
    resize_id = state.get("id", "")
    targets = state.get("new") or []
    olds = state.get("old") or []
    logger.printf("resize recovery: journal shows %s in phase %s",
                  resize_id, phase)
    coord = ResizeCoordinator(server, targets, resize_id=resize_id,
                              journal=journal, logger=logger)
    coord.old_hosts = olds
    coord.moving = movement(olds, targets, server.cluster.partition_n,
                            server.cluster.replica_n,
                            server.cluster.hasher)
    # Register as THE live op: the roll-forward must be visible to
    # GET /cluster/resize, drive the resize_stall watchdog, and own
    # the abort API — an unregistered recovery could otherwise race a
    # second operator-spawned coordinator over the same journal
    # (review finding).
    server.resize_op = coord
    if phase in (PHASE_IDLE, PHASE_PREPARING, PHASE_STREAMING,
                 PHASE_ABORTED):
        coord.abort(reason=f"coordinator restarted in phase {phase}")
        return coord.status()
    # Post-flip: roll forward — re-send flip (idempotent; nodes that
    # lost state install from the message), drain-diff, finalize.
    try:
        coord._set_phase(PHASE_FLIPPING)
        coord._send_phase(coord._message("flip"), coord._union_hosts(),
                          require_all=True, retries=ACK_RETRIES * 2)
        from ..server.syncer import FragmentStreamer
        streamer = FragmentStreamer(
            client_factory=server._client_factory, logger=logger,
            fault=server.fault, pace_s=coord.pace_s,
            on_block=coord._on_stream_block)
        coord._set_phase(PHASE_DRAINING)
        coord._stream_pass(streamer)
        coord._set_phase(PHASE_FINALIZING)
        coord._send_phase(coord._message("finalize"),
                          coord._union_hosts(), require_all=False)
        coord._set_phase(PHASE_DONE, finishedAt=time.time())
        set_state_gauge(PHASE_IDLE)
    except Exception as e:  # noqa: BLE001 - surfaced in status
        coord.error = str(e)
        logger.printf("resize recovery: roll-forward failed: %s", e)
    return coord.status()
