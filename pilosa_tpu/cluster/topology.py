"""Cluster topology: nodes, partitioning, and fragment placement.

Reference: cluster.go. A slice maps to one of PARTITION_N partitions via
FNV-1a of (index name, big-endian slice id) (cluster.go:198-207); a
partition maps to its primary owner via jump consistent hash, and to
REPLICA_N consecutive ring successors (cluster.go:220-240).

The placement function is pure and deterministic — every node computes the
same owner set with no coordination, which is exactly the property we need
for the TPU build too: the host-side coordinator uses it to route slices to
hosts, and within a host the same modular arithmetic lays slices onto the
device-mesh axis (pilosa_tpu.parallel.mesh).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_PARTITION_N = 16
DEFAULT_REPLICA_N = 1

NODE_STATE_UP = "UP"
NODE_STATE_DOWN = "DOWN"

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_U64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _U64
    return h


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash: key → bucket in [0, n)
    (cluster.go:266-277, Lamping & Veach)."""
    b, j = -1, 0
    key &= _U64
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & _U64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


@dataclass
class Node:
    """One cluster member (cluster.go:39-56)."""
    host: str
    internal_host: str = ""
    state: str = NODE_STATE_UP
    # Last pb.NodeStatus received from this node (schema + owned slices),
    # set by the status merge like the reference's Node.SetStatus
    # (cluster.go:58-76).
    status: Optional[object] = field(default=None, compare=False,
                                     repr=False)

    def set_state(self, s: str) -> None:
        self.state = s

    def set_status(self, ns) -> None:
        self.status = ns


def filter_host(nodes: list[Node], host: str) -> list[Node]:
    return [n for n in nodes if n.host != host]


def hosts_of(nodes: list[Node]) -> list[str]:
    return [n.host for n in nodes]


@dataclass
class Cluster:
    """Node list + placement math (cluster.go:120-264)."""
    nodes: list[Node] = field(default_factory=list)
    partition_n: int = DEFAULT_PARTITION_N
    replica_n: int = DEFAULT_REPLICA_N
    node_set: Optional[object] = None  # membership backend (broadcast.py)
    hasher: object = None              # override for tests

    def node_by_host(self, host: str) -> Optional[Node]:
        for n in self.nodes:
            if n.host == host:
                return n
        return None

    def _hash(self, key: int, n: int) -> int:
        if self.hasher is not None:
            return self.hasher(key, n)
        return jump_hash(key, n)

    def partition(self, index: str, slice: int) -> int:
        """Slice → partition by FNV-1a(index ∥ BE64(slice)) mod partition_n
        (cluster.go:198-207)."""
        h = fnv1a_64(index.encode() + struct.pack(">Q", slice))
        return h % self.partition_n

    def partition_nodes(self, partition_id: int) -> list[Node]:
        """Primary owner by jump hash + replica_n ring successors
        (cluster.go:220-240)."""
        if not self.nodes:
            return []
        replica_n = min(self.replica_n, len(self.nodes)) or 1
        i = self._hash(partition_id, len(self.nodes))
        return [self.nodes[(i + k) % len(self.nodes)]
                for k in range(replica_n)]

    def fragment_nodes(self, index: str, slice: int) -> list[Node]:
        return self.partition_nodes(self.partition(index, slice))

    def owns_fragment(self, host: str, index: str, slice: int) -> bool:
        return any(n.host == host
                   for n in self.fragment_nodes(index, slice))

    def owns_slices(self, index: str, max_slice: int, host: str
                    ) -> list[int]:
        """Slices whose PRIMARY owner is host (cluster.go:243-254)."""
        if not self.nodes:
            return []
        out = []
        for s in range(max_slice + 1):
            p = self.partition(index, s)
            if self.nodes[self._hash(p, len(self.nodes))].host == host:
                out.append(s)
        return out

    def node_set_hosts(self) -> list[str]:
        if self.node_set is None:
            return []
        return [n.host for n in self.node_set.nodes()]

    def node_states(self) -> dict[str, str]:
        """UP/DOWN per node, by NodeSet membership (cluster.go:157-169)."""
        h = {n.host: NODE_STATE_DOWN for n in self.nodes}
        for host in self.node_set_hosts():
            if host in h:
                h[host] = NODE_STATE_UP
        return h


def new_cluster(hosts: list[str], replica_n: int = DEFAULT_REPLICA_N
                ) -> Cluster:
    return Cluster(nodes=[Node(h) for h in hosts], replica_n=replica_n)
