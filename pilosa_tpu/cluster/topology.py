"""Cluster topology: nodes, partitioning, and fragment placement.

Reference: cluster.go. A slice maps to one of PARTITION_N partitions via
FNV-1a of (index name, big-endian slice id) (cluster.go:198-207); a
partition maps to its primary owner via jump consistent hash, and to
REPLICA_N consecutive ring successors (cluster.go:220-240).

The placement function is pure and deterministic — every node computes the
same owner set with no coordination, which is exactly the property we need
for the TPU build too: the host-side coordinator uses it to route slices to
hosts, and within a host the same modular arithmetic lays slices onto the
device-mesh axis (pilosa_tpu.parallel.mesh).

Elastic resize (docs/CLUSTER_RESIZE.md): membership is no longer
fixed-at-boot. Placement is versioned by an integer **epoch**; an
in-flight resize installs a ``ResizeState`` that makes ownership math
epoch-aware in three regimes:

- ``migrating`` (pre-flip): the CURRENT (old) placement is the read
  authority; writes fan to the union of old and new owners of every
  moving partition, so the target copies stay write-synchronized while
  the streamer backfills their base data.
- ``draining`` (post-flip): ``nodes``/``epoch`` have switched to the
  target membership in one atomic step; reads route by the new
  placement (old owners stay read-valid — everyone still union-writes
  until finalize); writes keep fanning to the union so a node that has
  not yet processed the flip cannot strand a write.
- finalized: resize state clears; for a short grace window the
  previous epoch's owners keep ACCEPTING writes (never serving reads)
  so straggler coordinators' union-writes don't bounce.

The movement set is computable from the jump-hash delta alone
(``movement()``): growing n→n+1 relocates ~1/(n+1) of partitions and
never moves one between two surviving old owners.
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_PARTITION_N = 16
DEFAULT_REPLICA_N = 1

NODE_STATE_UP = "UP"
NODE_STATE_DOWN = "DOWN"

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_U64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _U64
    return h


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash: key → bucket in [0, n)
    (cluster.go:266-277, Lamping & Veach)."""
    b, j = -1, 0
    key &= _U64
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & _U64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


@dataclass
class Node:
    """One cluster member (cluster.go:39-56)."""
    host: str
    internal_host: str = ""
    state: str = NODE_STATE_UP
    # Last pb.NodeStatus received from this node (schema + owned slices),
    # set by the status merge like the reference's Node.SetStatus
    # (cluster.go:58-76).
    status: Optional[object] = field(default=None, compare=False,
                                     repr=False)

    def set_state(self, s: str) -> None:
        self.state = s

    def set_status(self, ns) -> None:
        self.status = ns


def filter_host(nodes: list[Node], host: str) -> list[Node]:
    return [n for n in nodes if n.host != host]


def hosts_of(nodes: list[Node]) -> list[str]:
    return [n.host for n in nodes]


# -- elastic resize: movement-set math + in-flight state ----------------------

RESIZE_MIGRATING = "migrating"   # pre-flip: old placement authoritative
RESIZE_DRAINING = "draining"     # post-flip: new placement authoritative


def owner_hosts(hosts: list[str], partition_id: int, replica_n: int,
                hasher=None) -> tuple[str, ...]:
    """The owner host tuple for one partition over an explicit host
    list — the pure function both epochs' placement reduces to, so the
    movement set is computable without a Cluster instance."""
    if not hosts:
        return ()
    replica_n = min(replica_n, len(hosts)) or 1
    h = (hasher or jump_hash)(partition_id, len(hosts))
    return tuple(hosts[(h + k) % len(hosts)] for k in range(replica_n))


def movement(old_hosts: list[str], new_hosts: list[str],
             partition_n: int, replica_n: int,
             hasher=None) -> dict[int, tuple[tuple, tuple]]:
    """``{partition: (old_owner_hosts, new_owner_hosts)}`` for every
    partition whose owner SET changes between the two memberships —
    the minimal movement set the jump-hash delta gives us. Growing
    n→n+1 (host appended) relocates ~1/(n+1) of partitions, and a
    moved partition's PRIMARY either stays put or becomes the added
    host — jump hash never reassigns a primary between surviving
    buckets (Lamping & Veach). With replica_n>1 the successor ring can
    additionally shift a replica, which the owner-SET comparison here
    deliberately catches: those copies need streaming too."""
    out: dict[int, tuple[tuple, tuple]] = {}
    for p in range(partition_n):
        old = owner_hosts(old_hosts, p, replica_n, hasher)
        new = owner_hosts(new_hosts, p, replica_n, hasher)
        if set(old) != set(new):
            out[p] = (old, new)
    return out


class ResizeState:
    """One in-flight resize as every node tracks it. Built by
    ``Cluster.install_resize`` (the prepare broadcast) and mutated only
    under the cluster lock; readers see a consistent snapshot because
    the phase moves monotonically migrating → draining and the node
    lists are immutable once built."""

    __slots__ = ("id", "phase", "epoch_from", "old_hosts", "new_hosts",
                 "target_nodes", "old_nodes", "moving", "_extra",
                 "started_mono")

    def __init__(self, resize_id: str, epoch_from: int,
                 old_hosts: list[str], new_hosts: list[str],
                 target_nodes: list[Node], old_nodes: list[Node],
                 moving: dict[int, tuple[tuple, tuple]]):
        self.id = resize_id
        self.phase = RESIZE_MIGRATING
        self.epoch_from = epoch_from
        self.old_hosts = list(old_hosts)
        self.new_hosts = list(new_hosts)
        self.target_nodes = target_nodes
        self.old_nodes = old_nodes
        # partition -> (old owner hosts, new owner hosts), owner SET
        # changed. The whole double-write/double-read machinery keys
        # off membership here; non-moving partitions have identical
        # owners in both epochs, so mixed-epoch routing is
        # unobservable for them by construction.
        self.moving = moving
        # partition -> the OTHER side's extra Node objects (identity-
        # stable, so placement consumers that compare by ``is`` keep
        # working): during migrating the targets not already owners,
        # during draining the old owners not in the new set.
        self._extra: dict[int, list[Node]] = {}
        self.started_mono = time.monotonic()
        self._rebuild_extra()

    def _node_for(self, host: str) -> Node:
        for n in self.target_nodes:
            if n.host == host:
                return n
        for n in self.old_nodes:
            if n.host == host:
                return n
        return Node(host)

    def _rebuild_extra(self) -> None:
        extra: dict[int, list[Node]] = {}
        for p, (old, new) in self.moving.items():
            if self.phase == RESIZE_MIGRATING:
                want = [h for h in new if h not in old]
            else:
                want = [h for h in old if h not in new]
            extra[p] = [self._node_for(h) for h in want]
        self._extra = extra

    def extra_nodes(self, partition_id: int) -> list[Node]:
        return self._extra.get(partition_id, ())

    def to_wire(self) -> dict:
        return {"id": self.id, "phase": self.phase,
                "epochFrom": self.epoch_from,
                "old": list(self.old_hosts),
                "new": list(self.new_hosts)}


@dataclass
class Cluster:
    """Node list + placement math (cluster.go:120-264), versioned by a
    placement epoch for elastic resize (docs/CLUSTER_RESIZE.md)."""
    nodes: list[Node] = field(default_factory=list)
    partition_n: int = DEFAULT_PARTITION_N
    replica_n: int = DEFAULT_REPLICA_N
    node_set: Optional[object] = None  # membership backend (broadcast.py)
    hasher: object = None              # override for tests
    # Placement epoch: bumped atomically by flip_epoch so every
    # ownership consumer switches math in one step. ``resize`` is the
    # in-flight ResizeState or None (the hot-path check is one attr
    # read). ``_prev`` keeps the previous epoch's owners write-
    # accepting for a grace window after finalize.
    epoch: int = 0
    resize: Optional[ResizeState] = field(default=None, compare=False)
    _prev: Optional[tuple] = field(default=None, compare=False,
                                   repr=False)
    _mu: threading.Lock = field(default_factory=threading.Lock,
                                compare=False, repr=False)

    def node_by_host(self, host: str) -> Optional[Node]:
        for n in self.nodes:
            if n.host == host:
                return n
        return None

    def _hash(self, key: int, n: int) -> int:
        if self.hasher is not None:
            return self.hasher(key, n)
        return jump_hash(key, n)

    def partition(self, index: str, slice: int) -> int:
        """Slice → partition by FNV-1a(index ∥ BE64(slice)) mod partition_n
        (cluster.go:198-207)."""
        h = fnv1a_64(index.encode() + struct.pack(">Q", slice))
        return h % self.partition_n

    def partition_nodes(self, partition_id: int) -> list[Node]:
        """Primary owner by jump hash + replica_n ring successors
        (cluster.go:220-240) — the CURRENT epoch's authoritative
        placement (old pre-flip, new post-flip)."""
        if not self.nodes:
            return []
        replica_n = min(self.replica_n, len(self.nodes)) or 1
        i = self._hash(partition_id, len(self.nodes))
        return [self.nodes[(i + k) % len(self.nodes)]
                for k in range(replica_n)]

    def fragment_nodes(self, index: str, slice: int) -> list[Node]:
        """WRITE/general placement: the current epoch's owners, plus —
        during a resize — the other epoch's owners of a moving
        partition, so every write double-lands on old and new copies
        from prepare until finalize."""
        p = self.partition(index, slice)
        owners = self.partition_nodes(p)
        rs = self.resize
        if rs is not None:
            extra = rs.extra_nodes(p)
            if extra:
                owners = owners + [n for n in extra
                                   if all(o.host != n.host
                                          for o in owners)]
        return owners

    def read_nodes(self, index: str, slice: int) -> list[Node]:
        """READ authority: who may SERVE this slice without risk of an
        incomplete copy. No resize → the current owners. Migrating →
        the old (current) owners only — a stream target's copy is
        incomplete until the flip. Draining → new owners plus old
        owners (both copies receive every write until finalize).
        Post-finalize grace never extends read authority — the old
        copy goes stale the moment finalized writers stop
        double-writing."""
        p = self.partition(index, slice)
        owners = self.partition_nodes(p)
        rs = self.resize
        if rs is None or rs.phase != RESIZE_DRAINING:
            return owners
        extra = rs.extra_nodes(p)
        if extra:
            owners = owners + [n for n in extra
                               if all(o.host != n.host for o in owners)]
        return owners

    def read_allowed(self, host: str, index: str, slice: int) -> bool:
        return any(n.host == host
                   for n in self.read_nodes(index, slice))

    def moving_slice(self, index: str, slice: int):
        """``(phase, old_owner_hosts, new_owner_hosts)`` when the slice
        sits in a moving partition of an in-flight resize, else None.
        One attr read on the no-resize hot path."""
        rs = self.resize
        if rs is None:
            return None
        mv = rs.moving.get(self.partition(index, slice))
        if mv is None:
            return None
        return rs.phase, mv[0], mv[1]

    def owns_fragment(self, host: str, index: str, slice: int) -> bool:
        """Write-accepting ownership: the resize union, plus (post-
        finalize) the previous epoch's owners inside the grace window —
        a straggler coordinator's union-write must not bounce off the
        old owner with a 412. Read-path gates use read_allowed, never
        this."""
        if any(n.host == host
               for n in self.fragment_nodes(index, slice)):
            return True
        prev = self._prev
        if prev is not None:
            deadline, old_hosts, _epoch = prev
            if time.monotonic() < deadline:
                p = self.partition(index, slice)
                return host in owner_hosts(old_hosts, p, self.replica_n,
                                           self.hasher)
            # Expired: clear under the lock, and only the tuple we
            # read — an unsynchronized None could clobber a grace
            # window a concurrent finalize just installed (review
            # finding).
            with self._mu:
                if self._prev is prev:
                    self._prev = None
        return False

    # -- resize lifecycle (driven by ResizeMessage broadcasts) ---------------

    def install_resize(self, resize_id: str,
                       new_hosts: list[str]) -> ResizeState:
        """The prepare step: atomically install the in-flight state.
        Idempotent for the same id; a different in-flight id raises
        (one resize at a time, cluster-wide)."""
        with self._mu:
            rs = self.resize
            if rs is not None:
                if rs.id == resize_id:
                    return rs
                raise ValueError(
                    f"resize {rs.id} already in flight (phase"
                    f" {rs.phase}); cannot install {resize_id}")
            old_hosts = [n.host for n in self.nodes]
            by_host = {n.host: n for n in self.nodes}
            target_nodes = [by_host.get(h) or Node(h)
                            for h in new_hosts]
            rs = ResizeState(
                resize_id, self.epoch, old_hosts, new_hosts,
                target_nodes, list(self.nodes),
                movement(old_hosts, new_hosts, self.partition_n,
                         self.replica_n, self.hasher))
            self.resize = rs
            return rs

    def flip_epoch(self, resize_id: str) -> bool:
        """The epoch-atomic switch: nodes/epoch move to the target
        membership and the resize enters draining, all under one lock
        — every subsequent placement consult on this node uses the new
        math. Returns True when this call performed the flip (False =
        already flipped). Raises if no matching resize is installed
        (the caller installs from the flip message first — it carries
        everything needed)."""
        with self._mu:
            rs = self.resize
            if rs is None or rs.id != resize_id:
                raise ValueError(f"no resize {resize_id} installed")
            if rs.phase == RESIZE_DRAINING:
                return False
            self.nodes = list(rs.target_nodes)
            self.epoch = rs.epoch_from + 1
            rs.phase = RESIZE_DRAINING
            rs._rebuild_extra()
            return True

    def finalize_resize(self, resize_id: str,
                        grace_s: float = 30.0) -> bool:
        """Drop the union: single-path writes resume; old owners keep
        write-accepting (never read-serving) for ``grace_s``."""
        with self._mu:
            rs = self.resize
            if rs is None or rs.id != resize_id:
                return False
            if rs.phase != RESIZE_DRAINING:
                # Finalize without flip = protocol violation upstream.
                raise ValueError(
                    f"resize {resize_id} not flipped (phase {rs.phase})")
            self.resize = None
            self._prev = (time.monotonic() + grace_s,
                          list(rs.old_hosts), rs.epoch_from)
            return True

    def abort_resize(self, resize_id: str) -> bool:
        """Back out to the old epoch. Pre-flip this only clears state;
        post-flip (a node that flipped before the coordinator decided
        to abort) it reverts nodes/epoch — safe because every node
        union-writes until finalize, so the old copies never missed a
        write."""
        with self._mu:
            rs = self.resize
            if rs is None or rs.id != resize_id:
                return False
            if rs.phase == RESIZE_DRAINING:
                self.nodes = list(rs.old_nodes)
                self.epoch = rs.epoch_from
            self.resize = None
            return True

    def owns_slices(self, index: str, max_slice: int, host: str
                    ) -> list[int]:
        """Slices whose PRIMARY owner is host (cluster.go:243-254)."""
        if not self.nodes:
            return []
        out = []
        for s in range(max_slice + 1):
            p = self.partition(index, s)
            if self.nodes[self._hash(p, len(self.nodes))].host == host:
                out.append(s)
        return out

    def node_set_hosts(self) -> list[str]:
        if self.node_set is None:
            return []
        return [n.host for n in self.node_set.nodes()]

    def node_states(self) -> dict[str, str]:
        """UP/DOWN per node, by NodeSet membership (cluster.go:157-169)."""
        h = {n.host: NODE_STATE_DOWN for n in self.nodes}
        for host in self.node_set_hosts():
            if host in h:
                h[host] = NODE_STATE_UP
        return h


def new_cluster(hosts: list[str], replica_n: int = DEFAULT_REPLICA_N
                ) -> Cluster:
    return Cluster(nodes=[Node(h) for h in hosts], replica_n=replica_n)
